"""Unified telemetry subsystem (deepspeed_tpu/telemetry/).

Covers the acceptance contract:
  - telemetry-enabled ``train_batch`` adds ZERO device syncs per step
    (spans close lazily at the periodic steps_per_print sync);
  - the exported trace file is valid Chrome trace-event JSON (loadable
    by ``json.loads``, every event carrying ph/ts/name);
  - ``recompiles_total`` increments when a jitted program retraces
    (shape-change test) and the Prometheus exporter output parses
    line-by-line.
"""
import json
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.telemetry import (CompileMonitor, MetricsRegistry,
                                     TelemetryHub, TraceRecorder,
                                     prometheus_text)
from deepspeed_tpu.telemetry.cli import summarize

from simple_model import SimpleModel, base_config


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", "help text")
    c.inc()
    c.inc(2, route="train")
    assert c.value() == 1
    assert c.value(route="train") == 2
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("hbm_bytes")
    g.set(5, device="0")
    g.set(7, device="0")  # last write wins
    assert g.value(device="0") == 7
    h = reg.histogram("lat_seconds")
    for v in range(1, 101):
        h.observe(v / 100)
    res = h.reservoir()
    assert res.count == 100 and res.min == 0.01 and res.max == 1.0
    assert abs(res.percentile(0.5) - 0.5) < 0.05
    assert abs(res.percentile(0.99) - 0.99) < 0.05
    # idempotent re-registration; kind mismatch is an error
    assert reg.counter("requests_total") is c
    with pytest.raises(ValueError):
        reg.gauge("requests_total")


def test_histogram_reservoir_is_bounded():
    reg = MetricsRegistry()
    h = reg.histogram("x", reservoir_size=64)
    for v in range(10_000):
        h.observe(float(v))
    res = h.reservoir()
    assert len(res.samples) == 64        # bounded memory
    assert res.count == 10_000           # exact count survives
    assert res.percentile(0.5) > 1000    # samples span the stream


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

def test_trace_recorder_span_and_export(tmp_path):
    tr = TraceRecorder()
    with tr.span("outer", cat="test", step=3):
        with tr.span("inner"):
            pass
    tr.instant("marker")
    tr.counter("hbm", {"bytes": 123.0})
    h = tr.begin("lazy")
    h.end(steps=5)
    h.end()  # idempotent
    path = tr.export(str(tmp_path / "trace.json"))
    doc = json.loads(open(path).read())
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"outer", "inner", "marker", "hbm", "lazy"} <= names
    for e in evs:
        assert "ph" in e and "ts" in e and "name" in e
    lazy = next(e for e in evs if e["name"] == "lazy")
    assert lazy["args"]["steps"] == 5
    outer = next(e for e in evs if e["name"] == "outer")
    inner = next(e for e in evs if e["name"] == "inner")
    assert outer["ts"] <= inner["ts"]
    assert outer["dur"] >= inner["dur"]


def test_trace_recorder_bounds_events():
    tr = TraceRecorder(max_events=10)
    for i in range(25):
        tr.instant(f"e{i}")
    assert len(tr.events()) == 10
    assert tr.dropped == 15


# ---------------------------------------------------------------------------
# prometheus exporter — parses line-by-line (acceptance)
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? \S+)$")


def test_prometheus_text_parses_line_by_line():
    reg = MetricsRegistry()
    reg.counter("recompiles_total", "retraces").inc(3, program="train_step")
    reg.gauge("device_bytes_in_use").set(1.5e9, device="0")
    h = reg.histogram("train_step_seconds", "synced step time")
    h.observe(0.25)
    h.observe(0.75)
    text = prometheus_text(reg)
    lines = text.strip().splitlines()
    assert lines, "exporter produced no output"
    for line in lines:
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
    assert 'recompiles_total{program="train_step"} 3.0' in lines
    assert any(l.startswith("train_step_seconds{quantile=") for l in lines)
    assert "train_step_seconds_count 2.0" in lines


# ---------------------------------------------------------------------------
# compile monitor — recompiles_total increments on retrace (acceptance)
# ---------------------------------------------------------------------------

def test_recompiles_total_increments_on_shape_change():
    reg = MetricsRegistry()
    cm = CompileMonitor(reg, storm_threshold=100)
    f = jax.jit(lambda x: x * 2)
    assert cm.track("prog", f)
    f(jnp.ones((2,)))
    cm.sample()
    assert reg.counter("recompiles_total").value(program="prog") == 0
    f(jnp.ones((3,)))  # new shape -> retrace
    cm.sample()
    assert reg.counter("recompiles_total").value(program="prog") == 1
    cm.sample()  # idempotent between retraces
    assert reg.counter("recompiles_total").value(program="prog") == 1
    # the exporter carries the label through, line-parseable
    text = prometheus_text(reg)
    assert 'recompiles_total{program="prog"} 1.0' in text.splitlines()


def test_compile_monitor_jax_monitoring_listener():
    reg = MetricsRegistry()
    cm = CompileMonitor(reg)
    installed = cm.install()
    try:
        if not installed:
            pytest.skip("jax.monitoring unavailable in this jax")
        before = reg.counter("jax_compiles_total").value()
        jax.jit(lambda x: x + 1)(jnp.ones((4,)))  # fresh program compiles
        assert reg.counter("jax_compiles_total").value() > before
    finally:
        cm.uninstall()


def test_compile_monitor_storm_warning(monkeypatch):
    from deepspeed_tpu.telemetry import compile_monitor as cm_mod
    warnings = []
    monkeypatch.setattr(
        cm_mod.logger, "warning",
        lambda msg, *args: warnings.append(msg % args if args else msg))
    reg = MetricsRegistry()
    cm = CompileMonitor(reg, storm_threshold=2)
    f = jax.jit(lambda x: x * 1.5)
    cm.track("stormy", f)
    for n in range(1, 5):
        f(jnp.ones((n,)))
    cm.sample()
    assert any("recompile storm" in w and "stormy" in w for w in warnings)
    warnings.clear()
    cm.sample()  # warned once per program, not per sample
    assert not warnings


def test_track_skips_non_jitted_drivers():
    reg = MetricsRegistry()
    cm = CompileMonitor(reg)
    assert not cm.track("python_driver", lambda s, b: (s, b))


# ---------------------------------------------------------------------------
# memory
# ---------------------------------------------------------------------------

def test_collect_memory_stats_structured():
    from deepspeed_tpu.runtime.utils import (collect_memory_stats,
                                             format_memory_status,
                                             memory_status)
    stats = collect_memory_stats()
    assert isinstance(stats["devices"], list)
    assert "host_rss_bytes" in stats
    if stats["host_rss_bytes"] is not None:
        assert stats["host_rss_bytes"] > 0
    # the log line and the dict share one collection path
    line = format_memory_status(stats, "probe")
    assert line.startswith("MEMORY probe:")
    assert memory_status("probe").startswith("MEMORY probe:")


def test_memory_sampler_sets_gauges():
    from deepspeed_tpu.telemetry.memory import MemorySampler
    reg = MetricsRegistry()
    ms = MemorySampler(reg)
    stats = ms.sample()
    if stats["host_rss_bytes"] is not None:
        assert reg.gauge("host_rss_bytes").value() == \
            stats["host_rss_bytes"]
    # CPU test meshes expose no allocator stats; devices list may be
    # empty, but the call must never throw or sync


# ---------------------------------------------------------------------------
# summarize CLI
# ---------------------------------------------------------------------------

def test_summarize_cli(tmp_path, capsys):
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        for i in range(6):
            f.write(json.dumps({"kind": "step", "ts": i, "step": i + 1,
                                "dispatch_s": 0.001}) + "\n")
        f.write(json.dumps({"kind": "sync", "ts": 6, "step": 3,
                            "interval_s": 0.6, "steps": 3,
                            "step_avg_s": 0.2,
                            "samples_per_sec": 160.0}) + "\n")
        f.write(json.dumps({"kind": "sync", "ts": 9, "step": 6,
                            "interval_s": 1.2, "steps": 3,
                            "step_avg_s": 0.4,
                            "samples_per_sec": 80.0}) + "\n")
        f.write(json.dumps({"kind": "memory", "ts": 9, "step": 6,
                            "stats": {"devices": [
                                {"id": 0, "peak_bytes_in_use": 2 ** 30}],
                                "host_rss_bytes": 2 ** 28}}) + "\n")
        f.write("not json\n")
    rep = summarize(str(path))
    assert rep["steps"] == 6
    assert rep["step_time_source"] == "synced intervals"
    assert abs(rep["p50_s"] - 0.3) < 1e-9     # [.2 x3, .4 x3] weighted
    assert rep["samples_per_sec"] == pytest.approx(120.0)
    assert rep["peak_hbm_bytes"] == 2 ** 30
    assert rep["bad_lines"] == 1

    from deepspeed_tpu.telemetry.cli import main
    assert main(["summarize", str(path)]) == 0
    out = capsys.readouterr().out
    assert "p50" in out and "peak HBM" in out
    assert main(["summarize", str(tmp_path / "missing.jsonl")]) == 2


def test_summarize_dispatch_only_is_labelled(tmp_path):
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "step", "step": 1,
                            "dispatch_s": 0.001}) + "\n")
    rep = summarize(str(path))
    assert "DISPATCH-ONLY" in rep["step_time_source"]


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

HIDDEN = 16


def _make_engine(tmp_path, telemetry: bool, steps_per_print=10 ** 9):
    import deepspeed_tpu
    cfg = base_config(micro_bs=2, grad_acc=1, stage=0)
    cfg["steps_per_print"] = steps_per_print
    if telemetry:
        cfg["telemetry"] = {"enabled": True, "output_path": str(tmp_path)}
    eng, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=HIDDEN),
                                       config=cfg)
    return eng


def _batch(eng, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((int(eng.train_batch_size),
                             HIDDEN)).astype(np.float32)
    return (x, 0.5 * x)


@pytest.fixture(scope="module")
def engine_pair(tmp_path_factory):
    tel_dir = tmp_path_factory.mktemp("telemetry_out")
    eng_off = _make_engine(tel_dir / "unused", telemetry=False)
    eng_on = _make_engine(tel_dir, telemetry=True)
    # warm up: compile both step programs outside the counted window
    for eng in (eng_off, eng_on):
        eng.train_batch(_batch(eng))
        eng.train_batch(_batch(eng, seed=1))
    yield eng_off, eng_on, tel_dir
    eng_on.close()
    eng_off.close()


class _SyncCounter:
    """Counts device-draining calls: jax.block_until_ready,
    jax.device_get, jax.effects_barrier, and np.asarray on jax Arrays
    (materialization).  Installed around a window of train_batch calls."""

    def __init__(self, monkeypatch):
        self.count = 0
        real_bur = jax.block_until_ready
        real_dg = jax.device_get
        real_eb = jax.effects_barrier
        real_asarray = np.asarray

        def wrap(real):
            def inner(*a, **k):
                self.count += 1
                return real(*a, **k)
            return inner

        def asarray(obj, *a, **k):
            if isinstance(obj, jax.Array):
                self.count += 1
            return real_asarray(obj, *a, **k)

        monkeypatch.setattr(jax, "block_until_ready", wrap(real_bur))
        monkeypatch.setattr(jax, "device_get", wrap(real_dg))
        monkeypatch.setattr(jax, "effects_barrier", wrap(real_eb))
        monkeypatch.setattr(np, "asarray", asarray)


def test_train_batch_adds_zero_device_syncs(engine_pair, monkeypatch):
    """THE overhead contract: with steps_per_print not yet reached,
    telemetry-enabled steps perform exactly as many device syncs as
    telemetry-disabled ones (zero — spans are host-side stamps that
    close lazily; the drain happens only at the periodic sync)."""
    eng_off, eng_on, _ = engine_pair
    counts = {}
    for name, eng in (("off", eng_off), ("on", eng_on)):
        with pytest.MonkeyPatch.context() as mp:
            sc = _SyncCounter(mp)
            for i in range(4):
                eng.train_batch(_batch(eng, seed=10 + i))
            counts[name] = sc.count
    assert counts["on"] == counts["off"], counts
    assert counts["on"] == 0, (
        "train_batch itself must not sync between steps_per_print "
        f"boundaries; counted {counts['on']}")


def test_engine_trace_prom_and_events(engine_pair):
    """Runs AFTER the zero-sync test (same module-scoped engines):
    trigger the periodic sync, close, and validate every artifact."""
    _, eng_on, tel_dir = engine_pair
    # steps_per_print is read per call — flip it so the boundary fires
    eng_on.config.steps_per_print = 1
    eng_on.train_batch(_batch(eng_on, seed=99))
    eng_on.train_batch(_batch(eng_on, seed=100))
    eng_on.close()
    eng_on.close()  # idempotent

    # Chrome trace-event JSON: json.loads-able, ph/ts/name on every event
    doc = json.loads(open(os.path.join(tel_dir, "trace.json")).read())
    evs = doc["traceEvents"]
    assert evs
    for e in evs:
        assert "ph" in e and "ts" in e and "name" in e, e
    names = {e["name"] for e in evs}
    assert "train/dispatch" in names
    assert "train/shard_batch" in names
    assert "train/steps_interval" in names   # lazy close at the sync

    # prometheus scrape file parses line-by-line
    for line in open(os.path.join(tel_dir, "metrics.prom")):
        line = line.strip()
        if line:
            assert _PROM_LINE.match(line), line

    # JSONL stream: step + sync + metrics records, summarize runs
    kinds = set()
    with open(os.path.join(tel_dir, "events.jsonl")) as f:
        for raw in f:
            kinds.add(json.loads(raw)["kind"])
    assert {"step", "sync", "metrics"} <= kinds
    rep = summarize(os.path.join(tel_dir, "events.jsonl"))
    assert rep["steps"] >= 8
    assert rep["p50_s"] is not None


def test_engine_tracks_train_step_program(engine_pair):
    _, eng_on, _ = engine_pair
    assert "train_step" in eng_on.telemetry.compile_monitor \
        .tracked_programs()


def test_telemetry_config_block_defaults_and_validation():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1}, 1)
    assert not cfg.telemetry_config.enabled
    assert cfg.telemetry_config.trace
    assert cfg.telemetry_config.compile_events
    assert cfg.telemetry_config.memory
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "telemetry": {"enabled": True,
                                       "recompile_storm_threshold": 0}}, 1)
    with pytest.raises(DeepSpeedConfigError):
        # bool is an int subclass; it must not slip through as 1
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "telemetry": {"enabled": True,
                                       "recompile_storm_threshold": True}},
                        1)


def test_prometheus_hostile_label_values_escaped():
    """Satellite: label values containing backslash, double-quote, and
    newline must escape per the exposition format (and still parse
    line-by-line — a newline smuggled into a label would tear the
    format)."""
    reg = MetricsRegistry()
    hostile = 'pa\\th"quoted"\nline2'
    reg.counter("hostile_total", "h").inc(1, label=hostile)
    text = prometheus_text(reg)
    lines = text.strip().splitlines()
    for line in lines:
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
    sample = next(l for l in lines if l.startswith("hostile_total{"))
    assert '\\\\' in sample          # backslash doubled
    assert '\\"' in sample           # quote escaped
    assert '\\n' in sample and "\n" not in sample  # newline literalized


def test_prometheus_help_fallback_and_escaping():
    """Satellite: every metric emits a # HELP line — gauges/summaries
    registered without help text fall back to their name, and help text
    with newlines/backslashes is escaped (one line per record)."""
    reg = MetricsRegistry()
    reg.gauge("helpless_gauge").set(1.0)           # no help text
    reg.histogram("helpless_seconds").observe(0.5)  # no help text
    reg.counter("multi_total", "line one\nline two \\ slash").inc()
    text = prometheus_text(reg)
    lines = text.strip().splitlines()
    assert "# HELP helpless_gauge helpless_gauge" in lines
    assert "# HELP helpless_seconds helpless_seconds" in lines
    assert ("# HELP multi_total line one\\nline two \\\\ slash"
            in lines)
    for line in lines:
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"


def test_summarize_and_diagnose_tolerate_torn_tail(tmp_path, capsys):
    """Satellite: a killed run's truncated final events.jsonl line is
    skipped AND counted — never silently dropped."""
    from deepspeed_tpu.telemetry.cli import diagnose
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        for i in range(4):
            f.write(json.dumps({"kind": "step", "step": i + 1,
                                "dispatch_s": 0.001}) + "\n")
        # the torn tail: a write killed mid-record, no trailing newline
        f.write('{"kind": "sync", "step": 4, "interval_')
    rep = summarize(str(path))
    assert rep["steps"] == 4
    assert rep["bad_lines"] == 1
    out = capsys.readouterr().out
    assert "skipped 1 unparseable" in out
    drep = diagnose(str(tmp_path))
    assert drep["skipped_lines"] == 1
    assert drep["last_step"] == 4
    dout = capsys.readouterr().out
    assert "skipped 1 malformed/torn" in dout


def test_heartbeat_ages_and_summarize_liveness_row(tmp_path, capsys):
    """Satellite: heartbeat staleness is operator-visible — beat_ages
    over real heartbeat fixtures, the heartbeat_age_s gauge path, and
    the summarize liveness row built from a metrics snapshot."""
    from deepspeed_tpu.telemetry.heartbeat import (HeartbeatWriter,
                                                   beat_ages,
                                                   read_heartbeats)
    hb_dir = tmp_path / "hb"
    w0 = HeartbeatWriter(str(hb_dir), process_index=0, host="hostA")
    w1 = HeartbeatWriter(str(hb_dir), process_index=1, host="hostB")
    w0.beat(3)
    w1.beat(3)
    beats = read_heartbeats(str(hb_dir))
    now = beats["hostA/0"]["time"]
    ages = beat_ages(beats, now=now + 7.5)
    assert set(ages) == {"hostA/0", "hostB/1"}
    assert ages["hostA/0"] == pytest.approx(7.5, abs=1.0)
    # clock skew clamps at zero, never negative
    assert beat_ages(beats, now=now - 100)["hostA/0"] == 0.0

    # the gauge lands in the metrics snapshot -> summarize liveness row
    reg = MetricsRegistry()
    g = reg.gauge("heartbeat_age_s", "beat age")
    for key, age in ages.items():
        g.set(age, host=key)
    reg.counter("straggler_detected_total", "s").inc()
    path = tmp_path / "events.jsonl"
    hub_like = json.dumps({"kind": "metrics", "step": 3,
                           "metrics": reg.snapshot()})
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "step", "step": 1,
                            "dispatch_s": 0.001}) + "\n")
        f.write(hub_like + "\n")
    rep = summarize(str(path))
    assert rep["liveness_hosts"] == 2
    assert rep["liveness_max_age_s"] == pytest.approx(
        max(ages.values()), rel=1e-6)
    out = capsys.readouterr().out
    assert "liveness" in out and "2 host(s)" in out


def test_hub_close_idempotent(tmp_path):
    hub = TelemetryHub(str(tmp_path), compile_events=False, memory=False)
    hub.record_step(1, 0.01)
    hub.on_sync(1, interval_s=0.01, steps=1)
    hub.close()
    hub.close()
    hub.on_sync(2)  # post-close: silently ignored
    assert os.path.isfile(tmp_path / "trace.json")
    assert os.path.isfile(tmp_path / "metrics.prom")


def test_summarize_offload_attribution_split(tmp_path, capsys):
    """The H2D-tier attribution scalars (offload_h2d_s /
    offload_cpu_adam_s) get summarize rows like the disk tier's
    read/write split — emitted-but-never-consumed was a jaxlint JL102
    finding."""
    p = tmp_path / "events.jsonl"
    lines = [{"kind": "sync", "step": 10 * (i + 1), "interval_s": 1.0,
              "steps": 10, "step_avg_s": 0.1,
              "scalars": {"offload_overlap_ratio": r,
                          "offload_h2d_s": 0.12,
                          "offload_cpu_adam_s": 0.30}}
             for i, r in enumerate((0.6, 0.8))]
    p.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
    rep = summarize(str(p))
    assert rep["offload_overlap_ratio"] == pytest.approx(0.7)
    assert rep["offload_h2d_s"] == pytest.approx(0.12)
    assert rep["offload_cpu_adam_s"] == pytest.approx(0.30)
    out = capsys.readouterr().out
    assert "offload H2D overlap" in out
    assert "H2D" in out and "Adam" in out
