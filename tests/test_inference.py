"""KV-cached decode engine: kernel differentials, prefill==decode logit
parity against the training forward, the one-compiled-decode-program
(zero recompile) contract, slot lifecycle, chaos, and the telemetry/
bench plumbing (docs/serving.md).
"""
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.inference import (KVCacheSpec, ServeEngine, init_cache,
                                     shard_cache)
from deepspeed_tpu.inference.kv_cache import validate_cache_mesh
from deepspeed_tpu.inference.scheduler import Request, SlotScheduler
from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2Model,
                                       gpt2_decode_step, gpt2_prefill)
from deepspeed_tpu.ops.pallas.decode_attention import (
    decode_attention, decode_attention_reference)
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.stages import reset_fault_injection

TINY = GPT2Config(vocab_size=128, n_positions=64, d_model=32, n_layer=2,
                  n_head=4, remat=None, attn_impl="dense")
TINY_FLASH = GPT2Config(**{**TINY.__dict__, "attn_impl": "flash"})

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


def _tokens(n, vocab=128, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, (n,)).astype(np.int32)


# ---------------------------------------------------------------------------
# decode kernel differentials
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("block_k", [32, 64, 256])
def test_decode_kernel_matches_dense(block_k):
    rng = np.random.RandomState(0)
    S, H, T, Dh = 5, 3, 130, 32
    q = jnp.asarray(rng.randn(S, H, Dh), jnp.float32)
    k = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    v = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    lengths = jnp.asarray([0, 1, 33, 77, 130], jnp.int32)
    out_p = decode_attention(q, k, v, lengths, impl="pallas",
                             block_k=block_k)
    out_d = decode_attention(q, k, v, lengths, impl="dense")
    np.testing.assert_allclose(out_p, out_d, atol=2e-6, rtol=2e-6)
    # free slot (length 0) outputs exact zeros on BOTH paths
    assert (np.asarray(out_p[0]) == 0).all()
    assert (np.asarray(out_d[0]) == 0).all()


def test_decode_kernel_masks_garbage_tail():
    """Positions beyond a slot's live length hold garbage (evicted
    request, uninitialized cache) and must never be attended."""
    rng = np.random.RandomState(1)
    S, H, T, Dh = 2, 2, 64, 16
    q = jnp.asarray(rng.randn(S, H, Dh), jnp.float32)
    k = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    v = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    poisoned_k = k.at[:, :, 20:].set(1e4)
    poisoned_v = v.at[:, :, 20:].set(1e4)
    lengths = jnp.asarray([20, 7], jnp.int32)
    for impl in ("pallas", "dense"):
        clean = decode_attention(q, k, v, lengths, impl=impl)
        poisoned = decode_attention(q, poisoned_k, poisoned_v, lengths,
                                    impl=impl)
        np.testing.assert_array_equal(np.asarray(clean),
                                      np.asarray(poisoned))


def test_decode_kernel_single_compile_across_lengths():
    """Traced lengths: one jit cache entry no matter the mix."""
    rng = np.random.RandomState(2)
    S, H, T, Dh = 4, 2, 64, 16
    q = jnp.asarray(rng.randn(S, H, Dh), jnp.float32)
    k = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    v = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    f = jax.jit(lambda q, k, v, l: decode_attention(q, k, v, l,
                                                    impl="pallas"))
    for lens in ([0, 0, 0, 0], [1, 5, 64, 0], [64, 64, 64, 64]):
        f(q, k, v, jnp.asarray(lens, jnp.int32)).block_until_ready()
    assert f._cache_size() == 1


def test_decode_kernel_bf16():
    rng = np.random.RandomState(3)
    S, H, T, Dh = 2, 2, 32, 16
    mk = lambda *s: jnp.asarray(rng.randn(*s), jnp.bfloat16)
    q, k, v = mk(S, H, Dh), mk(S, H, T, Dh), mk(S, H, T, Dh)
    lengths = jnp.asarray([9, 32], jnp.int32)
    out = decode_attention(q, k, v, lengths, impl="pallas")
    ref = decode_attention_reference(q, k, v, lengths)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


# ---------------------------------------------------------------------------
# prefill == decode logit parity vs the training forward
# ---------------------------------------------------------------------------


def _decode_chain(cfg, params, toks, t_prompt, t_max, impl):
    """Teacher-forced prefill + step-decode; returns (prefill_logits,
    [decode logits per position t_prompt..T-1]) for slot 1 of a 3-slot
    cache (free slots ride along masked)."""
    model_dtype = params["wte"].dtype
    L, H, Dh = cfg.n_layer, cfg.n_head, cfg.d_head
    logits_p, ks, vs = gpt2_prefill(cfg, params,
                                    jnp.asarray(toks[:, :t_prompt]))
    S = 3
    kc = jnp.zeros((L, S, H, t_max, Dh), model_dtype)
    vc = jnp.zeros((L, S, H, t_max, Dh), model_dtype)
    kc = kc.at[:, 1, :, :t_prompt].set(ks[:, 0])
    vc = vc.at[:, 1, :, :t_prompt].set(vs[:, 0])
    lens = jnp.asarray([0, t_prompt, 0], jnp.int32)
    active = jnp.asarray([False, True, False])
    out = []
    for t in range(t_prompt, toks.shape[1]):
        tok_t = jnp.asarray([0, toks[0, t], 0], jnp.int32)
        lg, kc, vc, lens = gpt2_decode_step(cfg, params, tok_t, kc, vc,
                                            lens, active, impl=impl)
        out.append(lg[1])
    return logits_p, out


@pytest.mark.parametrize("cfg,impl", [(TINY, "dense"),
                                      (TINY_FLASH, "pallas")],
                         ids=["dense", "pallas"])
def test_prefill_decode_parity_fp32(cfg, impl):
    """fp32 parity bar: the pallas arm (the production serving path) is
    BITWISE against the training forward at block-covering shapes; the
    dense arm is ulp-bounded (XLA lowers the single-query score einsum
    to a different matmul shape than the batched training one)."""
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = _tokens(24, seed=0)[None]
    full = model.apply(params, jnp.asarray(toks), jax.random.PRNGKey(1),
                       train=False)
    logits_p, decs = _decode_chain(cfg, params, toks, 8, 32, impl)
    if impl == "pallas":
        np.testing.assert_array_equal(np.asarray(logits_p),
                                      np.asarray(full[:, :8]))
        for i, lg in enumerate(decs):
            np.testing.assert_array_equal(np.asarray(lg),
                                          np.asarray(full[0, 8 + i]))
    else:
        np.testing.assert_allclose(logits_p, full[:, :8], atol=1e-6)
        for i, lg in enumerate(decs):
            np.testing.assert_allclose(lg, full[0, 8 + i], atol=1e-5)


@pytest.mark.parametrize("impl", ["dense", "pallas"])
def test_prefill_decode_parity_fp16(impl):
    cfg = TINY if impl == "dense" else TINY_FLASH
    model = GPT2Model(cfg)
    p16 = jax.tree.map(lambda a: a.astype(jnp.float16),
                       model.init(jax.random.PRNGKey(0)))
    toks = _tokens(20, seed=1)[None]
    full = model.apply(p16, jnp.asarray(toks), jax.random.PRNGKey(1),
                       train=False)
    logits_p, decs = _decode_chain(cfg, p16, toks, 6, 32, impl)
    scale = float(np.abs(np.asarray(full, np.float32)).max())
    tol = max(1e-2 * scale, 1e-2)
    np.testing.assert_allclose(np.asarray(logits_p, np.float32),
                               np.asarray(full[:, :6], np.float32),
                               atol=tol)
    for i, lg in enumerate(decs):
        np.testing.assert_allclose(np.asarray(lg, np.float32),
                                   np.asarray(full[0, 6 + i], np.float32),
                                   atol=tol)


def test_decode_parity_interpret_explicit():
    """The kernel's interpret path (forced, not auto-detected) matches
    the dense reference — the interpretable CPU fallback contract."""
    rng = np.random.RandomState(5)
    S, H, T, Dh = 3, 2, 48, 16
    q = jnp.asarray(rng.randn(S, H, Dh), jnp.float32)
    k = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    v = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    lengths = jnp.asarray([0, 17, 48], jnp.int32)
    out = decode_attention(q, k, v, lengths, impl="pallas",
                           interpret=True)
    ref = decode_attention(q, k, v, lengths, impl="dense")
    np.testing.assert_allclose(out, ref, atol=2e-6, rtol=2e-6)


# ---------------------------------------------------------------------------
# ServeEngine: greedy correctness, lifecycle, zero recompiles
# ---------------------------------------------------------------------------


def _serve_cfg(slots=4, max_seq=32, prefill=8, telemetry_path=None,
               **serving_extra):
    cfg = {"serving": {"slots": slots, "max_seq_len": max_seq,
                       "prefill_len": prefill, **serving_extra}}
    if telemetry_path is not None:
        cfg["telemetry"] = {"enabled": True,
                            "output_path": str(telemetry_path)}
    return cfg


def _greedy_reference(model, params, prompt, n):
    """Teacher-forced argmax chain through the TRAINING forward."""
    seq = list(prompt)
    out = []
    for _ in range(n):
        lg = model.apply(params, jnp.asarray([seq]),
                         jax.random.PRNGKey(0), train=False)
        tok = int(jnp.argmax(lg[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


@pytest.mark.parametrize("cfg", [TINY, TINY_FLASH], ids=["dense", "flash"])
def test_serve_greedy_matches_training_forward(cfg):
    model = GPT2Model(cfg)
    eng = ServeEngine(model, _serve_cfg())
    prompts = [list(_tokens(int(n), seed=i))
               for i, n in enumerate([3, 7, 1, 5, 8, 2])]
    reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    eng.run_until_idle()
    for p, r in zip(prompts, reqs):
        assert r.error is None
        assert r.finish_reason == "length"
        assert r.tokens == _greedy_reference(model, eng.params, p, 5)
    eng.close()


def test_serve_mixed_load_zero_recompiles(tmp_path):
    """THE acceptance bar: one compiled decode program survives an
    arbitrary request mix — varying prompt lengths, generation lengths,
    admissions and evictions interleaved — with zero recompiles,
    asserted via recompiles_total{program=decode_step}."""
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        slots=3, telemetry_path=tmp_path))
    rng = np.random.default_rng(7)
    reqs = []
    for wave in range(3):
        for i in range(5):
            reqs.append(eng.submit(
                list(_tokens(int(rng.integers(1, 8)), seed=100 * wave + i)),
                max_new_tokens=int(rng.integers(1, 9))))
        eng.run_until_idle()
    assert all(r.error is None for r in reqs)
    eng.telemetry.compile_monitor.sample()
    reg = eng.telemetry.registry
    assert reg.counter("recompiles_total").value(program="decode_step") == 0
    assert reg.counter("recompiles_total").value(program="prefill") == 0
    assert eng._decode_fn._cache_size() == 1
    assert reg.counter("serve_requests_total").value() == len(reqs)
    eng.close()


def test_serve_slot_lifecycle_reasons():
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(slots=2, max_seq=16, prefill=8))
    # length: budget exhausts
    r_len = eng.submit([1, 2, 3], max_new_tokens=2)
    # eos: pick the greedy chain's 2nd token as the eos id
    chain = _greedy_reference(model, eng.params, [5, 6], 4)
    r_eos = eng.submit([5, 6], max_new_tokens=10, eos_id=chain[1])
    # kv_capacity: prompt 8 + decode hits max_seq_len=16 before the
    # 100-token budget
    r_cap = eng.submit(list(_tokens(8, seed=3)), max_new_tokens=100)
    eng.run_until_idle()
    assert r_len.finish_reason == "length" and len(r_len.tokens) == 2
    assert r_eos.finish_reason == "eos"
    # truncated at the FIRST greedy occurrence of the eos id
    stop = chain.index(chain[1]) + 1
    assert r_eos.tokens == chain[:stop]
    assert r_cap.finish_reason == "kv_capacity"
    # prompt(8) fills 8 rows; decode ticks append until the slot is full
    assert len(r_cap.tokens) == 16 - 8 + 1
    eng.close()


def test_serve_slot_reuse_is_isolated():
    """A slot's stale KV rows from an evicted request must not leak
    into the next request served from that slot (masked by length)."""
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(slots=1))
    p1, p2 = list(_tokens(7, seed=11)), list(_tokens(4, seed=12))
    r1 = eng.submit(p1, max_new_tokens=6)
    r2 = eng.submit(p2, max_new_tokens=6)
    eng.run_until_idle()
    assert r1.tokens == _greedy_reference(model, eng.params, p1, 6)
    assert r2.tokens == _greedy_reference(model, eng.params, p2, 6)
    eng.close()


def test_serve_continuous_admission_mid_flight():
    """Continuous batching: a request submitted while others are
    mid-decode is admitted into a free slot on the next tick without
    waiting for the batch to drain."""
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(slots=2))
    r1 = eng.submit(list(_tokens(3, seed=21)), max_new_tokens=8)
    r2 = eng.submit(list(_tokens(5, seed=22)), max_new_tokens=8)
    for _ in range(3):
        eng.step()
    r3 = eng.submit(list(_tokens(2, seed=23)), max_new_tokens=3)
    # both slots busy: r3 waits queued until one finishes, then decodes
    eng.run_until_idle()
    for r in (r1, r2, r3):
        assert r.error is None
        assert r.tokens == _greedy_reference(
            model, eng.params, r.prompt, len(r.tokens))
    eng.close()


def test_serve_submit_validation():
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(prefill=4))
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([])
    with pytest.raises(ValueError, match="prefill_len"):
        eng.submit([1, 2, 3, 4, 5])
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([1], max_new_tokens=0)
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit([1])


def test_serve_close_fails_queued_requests():
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(slots=1))
    reqs = [eng.submit([1, 2], max_new_tokens=4) for _ in range(3)]
    eng.close()
    for r in reqs:
        assert r.done.is_set()
        with pytest.raises(RuntimeError, match="closed"):
            r.result(timeout=0)
    # idempotent
    eng.close()


# ---------------------------------------------------------------------------
# TP / DP sharded serving
# ---------------------------------------------------------------------------


def test_serve_tp_dp_sharded_matches_single_device():
    model = GPT2Model(TINY_FLASH)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(5, seed=i)) for i in range(4)]

    def run(mesh):
        eng = ServeEngine(model, _serve_cfg(), mesh=mesh, params=params)
        rs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        toks = [r.tokens for r in rs]
        eng.close()
        return toks

    base = run(None)
    sharded = run(build_mesh(dp=2, tp=2, devices=jax.devices()[:4]))
    assert base == sharded


def test_cache_mesh_validation():
    spec = KVCacheSpec(layers=2, slots=3, heads=4, max_len=8, head_dim=8)
    with pytest.raises(ValueError, match="slots"):
        validate_cache_mesh(build_mesh(dp=2, devices=jax.devices()[:2]),
                            spec)
    spec2 = KVCacheSpec(layers=2, slots=4, heads=3, max_len=8, head_dim=8)
    with pytest.raises(ValueError, match="model axis"):
        validate_cache_mesh(
            build_mesh(dp=1, tp=2, devices=jax.devices()[:2]), spec2)
    with pytest.raises(ValueError, match="pipe"):
        validate_cache_mesh(
            build_mesh(pp=2, dp=1, devices=jax.devices()[:2]),
            KVCacheSpec(layers=2, slots=4, heads=4, max_len=8, head_dim=8))


# ---------------------------------------------------------------------------
# chaos: the serve stage rides the shared fault plane
# ---------------------------------------------------------------------------


def test_serve_transient_fault_absorbed(monkeypatch):
    monkeypatch.setenv("DS_STAGE_FAULT", "serve:admit:1,serve:step:2")
    reset_fault_injection()
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(slots=2))
    r = eng.submit(list(_tokens(3, seed=31)), max_new_tokens=4)
    eng.run_until_idle()
    assert r.error is None
    assert r.tokens == _greedy_reference(model, eng.params, r.prompt, 4)
    assert eng.stage.failures == 2
    assert not eng.stage.degraded
    eng.close()


def test_serve_sticky_fault_degrades_and_keeps_serving(monkeypatch):
    """Budget-exhausting sticky faults degrade the serve stage to its
    chaos-free direct path with ONE warning — the run completes with
    correct tokens instead of dying."""
    monkeypatch.setenv("DS_STAGE_FAULT", "serve:step:1+")
    reset_fault_injection()
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(slots=2))
    r = eng.submit(list(_tokens(4, seed=32)), max_new_tokens=5)
    eng.run_until_idle()
    assert eng.stage.degraded
    assert r.error is None
    assert r.tokens == _greedy_reference(model, eng.params, r.prompt, 5)
    eng.close()


def test_serve_injected_delay_applies(monkeypatch):
    monkeypatch.setenv("DS_STAGE_DELAY_S", "serve:0.05")
    import time
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(slots=1))
    eng.submit([1, 2], max_new_tokens=2)
    t0 = time.perf_counter()
    eng.run_until_idle()
    # admit + >=1 decode tick each pay the injected delay
    assert time.perf_counter() - t0 >= 0.1
    eng.close()


# ---------------------------------------------------------------------------
# config block
# ---------------------------------------------------------------------------


def test_serving_config_validation():
    from deepspeed_tpu.config.config import DeepSpeedServingConfig
    ok = DeepSpeedServingConfig({"serving": {"slots": 2}})
    assert ok.slots == 2 and ok.decode_impl == "auto"
    with pytest.raises(DeepSpeedConfigError, match="slots"):
        DeepSpeedServingConfig({"serving": {"slots": 0}})
    with pytest.raises(DeepSpeedConfigError, match="prefill_len"):
        DeepSpeedServingConfig({"serving": {"max_seq_len": 8,
                                            "prefill_len": 16}})
    with pytest.raises(DeepSpeedConfigError, match="decode_impl"):
        DeepSpeedServingConfig({"serving": {"decode_impl": "cuda"}})
    with pytest.raises(DeepSpeedConfigError, match="eos_id"):
        DeepSpeedServingConfig({"serving": {"eos_id": "</s>"}})
    with pytest.raises(DeepSpeedConfigError, match="queue_capacity"):
        DeepSpeedServingConfig({"serving": {"queue_capacity": True}})


def test_serving_block_parses_in_full_config():
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "serving": {"slots": 16}}, world_size=8)
    assert cfg.serving_config.slots == 16


# ---------------------------------------------------------------------------
# telemetry: summarize gains a serving row
# ---------------------------------------------------------------------------


def test_serving_scalars_flow_to_summarize(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import summarize
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        slots=2, telemetry_path=tmp_path, flush_interval_ticks=2))
    for i in range(3):
        eng.submit(list(_tokens(3, seed=40 + i)), max_new_tokens=4)
    eng.run_until_idle()
    eng.close()
    events = os.path.join(str(tmp_path), "events.jsonl")
    syncs = [json.loads(l) for l in open(events)
             if json.loads(l).get("kind") == "sync"]
    assert any("serve_tokens_per_s" in (s.get("scalars") or {})
               for s in syncs)
    report = summarize(events)
    out = capsys.readouterr().out
    assert report["serve_tokens_per_s"] is not None
    assert report["serve_token_p50_s"] is not None
    assert "serving" in out


# ---------------------------------------------------------------------------
# scheduler unit contracts
# ---------------------------------------------------------------------------


def test_slot_scheduler_contracts():
    s = SlotScheduler(2)
    r1 = Request(rid=1, prompt=[1], max_new_tokens=3)
    r2 = Request(rid=2, prompt=[2], max_new_tokens=3)
    a = s.admit(r1)
    b = s.admit(r2)
    assert {a, b} == {0, 1} and not s.has_free()
    rel = s.release(a, "eos")
    assert rel is r1 and rel.finish_reason == "eos" and s.has_free()
    # finish reasons
    r = Request(rid=3, prompt=[1], max_new_tokens=2, eos_id=7)
    r.tokens = [7]
    r.kv_len = 4
    assert s.finish_reason(r, 7, 16) == "eos"
    r.eos_id = None
    r.tokens = [1, 2]
    assert s.finish_reason(r, 1, 16) == "length"
    r.tokens = [1]
    r.kv_len = 16
    assert s.finish_reason(r, 1, 16) == "kv_capacity"
    r.kv_len = 4
    assert s.finish_reason(r, 1, 16) is None


def test_kv_cache_shard_roundtrip():
    spec = KVCacheSpec(layers=2, slots=8, heads=4, max_len=8, head_dim=4)
    mesh = build_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    cache = shard_cache(init_cache(spec), mesh)
    assert cache["k"].shape == (2, 8, 4, 8, 4)
    assert (np.asarray(cache["lengths"]) == 0).all()
    assert spec.bytes == 2 * 2 * 8 * 4 * 8 * 4 * 4


# ---------------------------------------------------------------------------
# bench smoke: continuous batching beats sequential decode
# ---------------------------------------------------------------------------


def test_bench_serve_smoke(tmp_path):
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "bench_serve.py")
    spec = importlib.util.spec_from_file_location("bench_serve_for_test",
                                                  path)
    bench_serve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_serve)
    rec = bench_serve.run_ab(slots=2, n_requests=4, prompt_len=3,
                             gen_tokens=5, tick_delay_s=0.03,
                             out_dir=str(tmp_path))
    assert rec["metric"] == "serve_continuous_batching_speedup"
    assert rec["value"] > 1.2
    assert rec["batched"]["tokens_per_s"] > rec["sequential"]["tokens_per_s"]
    assert os.path.exists(os.path.join(str(tmp_path), "BENCH_serve.json"))
