"""ZeRO-Infinity-style parameter streaming (xla offload tier).

``zero_optimization.param_streaming`` keeps the compute copies of the
model's stacked scan leaves in HOST memory; the model fetches one
layer's slice per scan tick (``TrainModule.streaming_param_spec`` +
GPT2's ``stream_scan``), so device-resident parameter bytes ~ one layer
instead of 2 bytes/param for the whole model.  The reference reaches the
same capacity point by partitioning fp16 params to CPU/NVMe (reference:
deepspeed/runtime/zero/stage2.py fp16 partition machinery; generalized
by the ZeRO-Infinity paper).  On the CPU test mesh memory kinds degrade
to one space — these tests pin down numerics, composition, and the
config contract; the capacity claim itself is bench_capacity.py's job
on hardware.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine


def _model_cfg(stream: bool, scan: bool = True):
    return GPT2Config(d_model=64, n_layer=3, n_head=4, vocab_size=256,
                      n_positions=64, remat="block", scan_layers=scan,
                      stream_scan=stream, attn_impl="dense")


def _ds_cfg(world: int, stage: int = 2, stream: bool = True, **zero_extra):
    return DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2 if world == 1 else 1,
        "gradient_accumulation_steps": 2 if world == 1 else 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": dict(
            {"stage": stage, "cpu_offload": True, "offload_impl": "xla",
             "param_streaming": stream}, **zero_extra),
    }, world_size=world)


def _tokens():
    return np.random.default_rng(0).integers(0, 256, (4, 33),
                                             dtype=np.int32)


def _run(engine, tokens, steps=5):
    return [float(engine.train_batch(tokens)) for _ in range(steps)]


# ---------------------------------------------------------------------
# numerics
# ---------------------------------------------------------------------
def test_streaming_matches_plain_offload():
    """Streaming is a memory PLACEMENT change — losses must match the
    non-streamed offload path exactly (same math, same rng)."""
    mesh = build_mesh(devices=jax.devices()[:1])
    tok = _tokens()
    plain = DeepSpeedEngine(GPT2Model(_model_cfg(False)),
                            _ds_cfg(1, stream=False), mesh=mesh)
    stream = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                             _ds_cfg(1, stream=True), mesh=mesh)
    lp, ls = _run(plain, tok), _run(stream, tok)
    np.testing.assert_allclose(ls, lp, rtol=1e-5, atol=1e-5)
    assert lp[-1] < lp[0]  # and it actually trains


def test_streaming_model_apply_matches_plain_apply():
    """Model-level: the stream_scan fetch form computes the same function
    as the xs-scan form."""
    rng = jax.random.PRNGKey(0)
    m_plain = GPT2Model(_model_cfg(False))
    m_stream = GPT2Model(_model_cfg(True))
    params = m_plain.init(rng)
    tok = jnp.asarray(_tokens()[:, :32])
    lo_p = m_plain.apply(params, tok, rng, train=False)
    lo_s = m_stream.apply(params, tok, rng, train=False)
    np.testing.assert_allclose(np.asarray(lo_s), np.asarray(lo_p),
                               rtol=1e-5, atol=1e-5)


def test_streaming_composes_with_grad_chunks():
    """param_streaming × offload_grad_chunks: the full capacity stack
    (device grads bounded by group, device params ~ one layer)."""
    mesh = build_mesh(devices=jax.devices()[:1])
    tok = _tokens()
    ref = DeepSpeedEngine(GPT2Model(_model_cfg(False)),
                          _ds_cfg(1, stream=False), mesh=mesh)
    stk = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                          _ds_cfg(1, stream=True, offload_grad_chunks=3),
                          mesh=mesh)
    lr_, ls = _run(ref, tok, 3), _run(stk, tok, 3)
    np.testing.assert_allclose(ls, lr_, rtol=5e-4, atol=5e-4)


def test_streaming_zero3_dp4():
    """ZeRO-3 × streaming × dp>1: host leaves stay data-sharded (no
    host-side collectives) and the run matches the dp=1 trajectory."""
    tok = _tokens()
    mesh1 = build_mesh(devices=jax.devices()[:1])
    ref = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                          _ds_cfg(1, stream=True), mesh=mesh1)
    mesh4 = build_mesh(dp=4, devices=jax.devices()[:4])
    eng = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                          _ds_cfg(4, stage=3, stream=True), mesh=mesh4)
    l1, l4 = _run(ref, tok, 3), _run(eng, tok, 3)
    np.testing.assert_allclose(l4, l1, rtol=2e-3, atol=2e-3)


def test_streaming_with_delayed_param_update():
    """DPU staleness semantics are placement-independent."""
    mesh = build_mesh(devices=jax.devices()[:1])
    tok = _tokens()
    a = DeepSpeedEngine(GPT2Model(_model_cfg(False)),
                        _ds_cfg(1, stream=False, delayed_param_update=True),
                        mesh=mesh)
    b = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                        _ds_cfg(1, stream=True, delayed_param_update=True),
                        mesh=mesh)
    la, lb = _run(a, tok), _run(b, tok)
    np.testing.assert_allclose(lb, la, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------
# contract
# ---------------------------------------------------------------------
def test_config_rejects_streaming_without_offload():
    with pytest.raises(DeepSpeedConfigError, match="param_streaming"):
        DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 1,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2, "param_streaming": True},
        }, world_size=1)


def test_config_rejects_streaming_on_host_tier():
    with pytest.raises(DeepSpeedConfigError, match="xla-tier"):
        DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 1,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2, "cpu_offload": True,
                                  "offload_impl": "host",
                                  "param_streaming": True},
        }, world_size=1)


def test_engine_rejects_streaming_dp_gt1_below_stage3():
    mesh = build_mesh(dp=4, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="requires ZeRO-3"):
        DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                        _ds_cfg(4, stage=2, stream=True), mesh=mesh)


def test_engine_rejects_streaming_without_model_support():
    """A model whose streaming_param_spec is None must fail loudly, not
    silently run un-streamed."""
    mesh = build_mesh(devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="streaming_param_spec"):
        DeepSpeedEngine(GPT2Model(_model_cfg(False)),
                        _ds_cfg(1, stream=True), mesh=mesh)


def test_streaming_chunks_dpu_triple_composition():
    """The full capacity+overlap stack at once: host-resident streamed
    params × K-group chunked grads × delayed parameter update."""
    mesh = build_mesh(devices=jax.devices()[:1])
    tok = _tokens()
    eng = DeepSpeedEngine(
        GPT2Model(_model_cfg(True)),
        _ds_cfg(1, stream=True, offload_grad_chunks=3,
                delayed_param_update=True),
        mesh=mesh)
    ls = _run(eng, tok, 5)
    assert all(np.isfinite(v) for v in ls), ls
    assert ls[-1] < ls[0], ls


def test_moe_streaming_matches_plain_offload():
    """MoE param streaming (one GROUP of stacked attn/dense/expert
    params fetched per scan tick) must match the unstreamed group-scan
    offload path exactly — placement, not math."""
    from deepspeed_tpu.models import GPT2MoEConfig, GPT2MoEModel

    tok = _tokens()
    mesh = build_mesh(devices=jax.devices()[:1])
    losses = {}
    for stream in (False, True):
        cfg_m = GPT2MoEConfig(
            vocab_size=256, n_positions=64, d_model=64, n_layer=4,
            n_head=4, n_experts=4, moe_layer_freq=2, attn_impl="dense",
            remat="block", scan_groups=True, stream_scan=stream,
            dropout=0.0)
        ds = DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": dict(
                {"stage": 2, "cpu_offload": True, "offload_impl": "xla"},
                **({"param_streaming": True} if stream else {})),
        }, world_size=1)
        eng = DeepSpeedEngine(GPT2MoEModel(cfg_m), ds, mesh=mesh)
        losses[stream] = _run(eng, tok, 4)
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-5)
    assert losses[True][-1] < losses[True][0]


def test_moe_stream_scan_requires_scan_groups():
    from deepspeed_tpu.models import GPT2MoEConfig

    with pytest.raises(ValueError, match="scan_groups"):
        GPT2MoEConfig(vocab_size=256, n_positions=64, d_model=64,
                      n_layer=4, n_head=4, n_experts=4,
                      moe_layer_freq=2, stream_scan=True)


def test_streaming_composes_with_ring_sequence_parallel():
    """Long-context × capacity: host-resident stacked params fetched per
    scan tick WHILE the attention inside each layer runs ring-parallel
    over the 'seq' axis (the fetch's device placement and the ring's
    shard_map both read the engine's ambient mesh)."""
    tok = _tokens()[:2]
    mesh = build_mesh(dp=1, sp=2, devices=jax.devices()[:2])
    cfg_m = GPT2Config(d_model=64, n_layer=3, n_head=4, vocab_size=256,
                      n_positions=64, remat="block", scan_layers=True,
                      stream_scan=True, attn_impl="ring", dropout=0.0)
    ds = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "xla",
                              "param_streaming": True},
    }, world_size=1)
    eng = DeepSpeedEngine(GPT2Model(cfg_m), ds, mesh=mesh)
    ls = _run(eng, tok, 3)
    assert all(np.isfinite(v) for v in ls) and ls[-1] < ls[0], ls


def test_engine_step_traces_under_ambient_mesh():
    """The engine must establish jax.set_mesh around compiled-step
    tracing: the streaming fetch, sequence-parallel axis discovery, and
    the MoE constraint all read jax.sharding.get_abstract_mesh() during
    trace, and WITHOUT the ambient mesh that read returns an empty
    AbstractMesh inside jit (argument shardings do not populate it) —
    every one of those features would silently degrade."""
    from deepspeed_tpu.runtime.module import TrainModule

    seen = []

    class Probe(TrainModule):
        def init(self, rng):
            return {"w": jnp.ones((8, 4))}

        def loss_fn(self, params, batch, rng, train=True):
            am = jax.sharding.get_abstract_mesh()
            seen.append(dict(getattr(am, "shape", {})))
            return jnp.mean((batch[0] @ params["w"] - batch[1]) ** 2)

    mesh = build_mesh(dp=4, devices=jax.devices()[:4])
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
    }, world_size=4)
    eng = DeepSpeedEngine(Probe(), cfg, mesh=mesh)
    x = np.ones((4, 8), np.float32)
    y = np.ones((4, 4), np.float32)
    eng.train_batch((x, y))
    assert seen, "loss_fn never traced"
    assert any(s.get("data") == 4 for s in seen), seen


def test_stream_mask_marks_blocks_only():
    """The engine's flat-order mask must cover exactly the stacked block
    leaves — embeddings and final LN stay device-resident."""
    mesh = build_mesh(devices=jax.devices()[:1])
    eng = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                          _ds_cfg(1, stream=True), mesh=mesh)
    model = GPT2Model(_model_cfg(True))
    params = model.init(jax.random.PRNGKey(0))
    paths = [jax.tree_util.keystr(p)
             for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]]
    assert len(paths) == len(eng._stream_mask)
    for path, m in zip(paths, eng._stream_mask):
        assert m == ("blocks" in path), (path, m)


def test_streaming_composes_with_split_update():
    """param_streaming x offload_split_update x grad chunks: the deepest
    capacity stack the 1.5B/bench_capacity chain can select.  Trajectory
    must match the fused-update streaming engine."""
    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    es = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                         _ds_cfg(1, offload_split_update=True,
                                 offload_grad_chunks=2),
                         mesh=mesh, seed=3)
    ef = DeepSpeedEngine(GPT2Model(_model_cfg(True)),
                         _ds_cfg(1, offload_grad_chunks=2),
                         mesh=mesh, seed=3)
    toks = _tokens()
    ls = _run(es, toks)
    lf = _run(ef, toks)
    np.testing.assert_allclose(ls, lf, rtol=0, atol=3e-4)
    assert ls[-1] < ls[0]


def test_zero3_dp4_split_update():
    """ZeRO-3 x split update at dp=4: per-piece programs must respect the
    data-sharded piece placement (each update touches only local rows)."""
    mesh = build_mesh(dp=4, devices=jax.devices()[:4])
    e3 = DeepSpeedEngine(GPT2Model(_model_cfg(False)),
                         _ds_cfg(4, stage=3, stream=False,
                                 offload_split_update=True),
                         mesh=mesh, seed=3)
    ef = DeepSpeedEngine(GPT2Model(_model_cfg(False)),
                         _ds_cfg(4, stage=3, stream=False),
                         mesh=mesh, seed=3)
    toks = _tokens()
    ls = _run(e3, toks)
    lf = _run(ef, toks)
    np.testing.assert_allclose(ls, lf, rtol=0, atol=3e-4)
