"""Real-data GPT-2 convergence run — the repo's analogue of the
reference's Megatron-GPT2 convergence tier (it trains on real corpora and
diffs loss curves against checked-in baselines; reference:
tests/model/Megatron_GPT2/test_common.py:12+).

Trains a scaled-down GPT-2 on the vendored real-text corpus
(``data/tokens.npz`` — installed-package documentation prose, byte-BPE
tokenized, see tools/build_corpus.py) through the full user path:
``ds`` launcher -> argparse injection -> ``deepspeed_tpu.initialize`` ->
``engine.train_batch``.  Writes the per-step loss curve as JSON.

Baseline regeneration (the checked-in artifact the regression test
diffs against):

    python bin/ds --num_nodes 1 --num_gpus 1 examples/convergence_gpt2.py \
        --deepspeed --cpu --steps 600 \
        --out tests/baselines/convergence_gpt2.json

Determinism: data order, init, and dropout(=0) are all driven by fixed
seeds; on a fixed platform + mesh the curve reproduces to float32
round-off, so the regression test uses a tight relative tolerance.
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models import GPT2Config, GPT2Model  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = {
    "train_micro_batch_size_per_gpu": 8,
    "gradient_accumulation_steps": 1,
    "steps_per_print": 50,
    "gradient_clipping": 1.0,
    "optimizer": {
        "type": "Adam",
        "params": {"lr": 6e-4, "betas": [0.9, 0.95], "weight_decay": 0.01},
    },
    "scheduler": {
        "type": "WarmupLR",
        "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 6e-4,
                   "warmup_num_steps": 40},
    },
}


def real_batches(tokens: np.ndarray, seq: int, batch: int, seed: int = 0):
    """Deterministic shuffled contiguous windows, cycling epochs."""
    n_windows = (len(tokens) - 1) // seq
    rng = np.random.default_rng(seed)
    order = np.arange(n_windows)
    while True:
        rng.shuffle(order)
        for i in range(0, n_windows - batch + 1, batch):
            idx = order[i:i + batch]
            yield np.stack([tokens[j * seq:j * seq + seq + 1]
                            for j in idx]).astype(np.int32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=600)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--d_model", type=int, default=256)
    parser.add_argument("--n_layer", type=int, default=4)
    parser.add_argument("--n_head", type=int, default=8)
    parser.add_argument("--out", type=str, default="convergence_gpt2.json")
    parser.add_argument("--cpu", action="store_true",
                        help="single-device CPU run (the baseline platform)")
    parser = deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    blob = np.load(os.path.join(REPO, "data", "tokens.npz"))
    tokens = blob["tokens"]
    vocab = 4096
    assert int(tokens.max()) < vocab

    model = GPT2Model(GPT2Config(
        vocab_size=vocab, n_positions=args.seq, d_model=args.d_model,
        n_layer=args.n_layer, n_head=args.n_head, dropout=0.0))

    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, model=model, config=dict(CONFIG))

    data = real_batches(tokens, args.seq, engine.train_batch_size, seed=1234)
    losses = []
    for step in range(args.steps):
        loss = float(np.asarray(engine.train_batch(next(data))))
        losses.append(round(loss, 6))
        if (step + 1) % 50 == 0:
            tail = np.mean(losses[-50:])
            print(f"step {step + 1}: loss {loss:.4f} (50-step mean {tail:.4f})",
                  flush=True)

    first = float(np.mean(losses[:20]))
    last = float(np.mean(losses[-50:]))
    artifact = {
        "model": {"vocab": vocab, "seq": args.seq, "d_model": args.d_model,
                  "n_layer": args.n_layer, "n_head": args.n_head},
        "config": CONFIG,
        "data": "data/tokens.npz (real corpus, tools/build_corpus.py)",
        "data_seed": 1234, "init_seed": 0,
        "steps": args.steps,
        "first20_mean": round(first, 4),
        "last50_mean": round(last, 4),
        "losses": losses,
    }
    out = args.out if os.path.isabs(args.out) else os.path.join(
        os.getcwd(), args.out)
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"loss {first:.4f} -> {last:.4f} over {args.steps} steps; "
          f"curve -> {out}")


if __name__ == "__main__":
    main()
