"""1-bit Adam convergence-parity artifact.

Trains the same toy regression model with OneBitAdam (freeze_step=15,
error-feedback sign-compressed gradient exchange after the boundary) and
plain Adam on identical data/seeds over an 8-way data-parallel mesh, and
writes both loss curves to ``docs/artifacts/onebit_convergence.json``.

This is the loss-curve evidence behind the reference's "same convergence
as Adam" claim (reference
docs/_posts/2020-09-09-onebit-adam-blog-post.md:85); the regression test
asserting terminal parity is
tests/test_onebit_engine.py::test_onebit_terminal_loss_parity_with_adam.

Run from the repo root:  python examples/onebit_convergence.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

# CPU-mesh artifact by design: pin cpu BEFORE any backend init — even
# enumerating backends on this image opens the axon TPU tunnel and
# blocks when it is down (same guard as __graft_entry__.dryrun_multichip)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deepspeed_tpu.config import DeepSpeedConfig  # noqa: E402
from deepspeed_tpu.parallel import build_mesh  # noqa: E402
from deepspeed_tpu.runtime.engine import DeepSpeedEngine  # noqa: E402
from simple_model import SimpleModel, base_config, random_batches  # noqa: E402

STEPS, FREEZE, LR = 120, 30, 5e-3


def _run(opt_type: str, extra: dict) -> list:
    cfg_dict = base_config(micro_bs=8, grad_acc=1)
    cfg_dict["optimizer"] = {"type": opt_type,
                             "params": {"lr": LR, **extra}}
    eng = DeepSpeedEngine(
        SimpleModel(hidden_dim=16, nlayers=2),
        DeepSpeedConfig(cfg_dict, world_size=8),
        mesh=build_mesh(dp=8, devices=jax.devices()[:8]))
    return [float(np.asarray(eng.train_batch(b)))
            for b in random_batches(64, 16, num_batches=STEPS, seed=21)]


def main():
    onebit = _run("OneBitAdam", {"freeze_step": FREEZE})
    adam = _run("Adam", {})
    tail = max(1, STEPS // 10)
    out = {
        "task": "SimpleModel regression, dp=8, bf16, lr=%g" % LR,
        "steps": STEPS,
        "freeze_step": FREEZE,
        "onebit_loss": onebit,
        "adam_loss": adam,
        "terminal_tail_mean": {
            "onebit": float(np.mean(onebit[-tail:])),
            "adam": float(np.mean(adam[-tail:])),
        },
        "parity_ratio": float(np.mean(onebit[-tail:])
                              / max(np.mean(adam[-tail:]), 1e-12)),
    }
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "artifacts", "onebit_convergence.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"parity_ratio": out["parity_ratio"],
                      "onebit_terminal": out["terminal_tail_mean"]["onebit"],
                      "adam_terminal": out["terminal_tail_mean"]["adam"]}))


if __name__ == "__main__":
    main()
