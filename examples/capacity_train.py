"""The full ZeRO-Offload capacity stack — train a model far past what
HBM alone holds (the reference's "10x larger models" headline,
docs/_pages/features.md:115 there; this stack reaches past it):

  cpu_offload + offload_impl=xla   fp32 master + Adam moments live in
                                   pinned host memory, Adam runs as an
                                   XLA host computation
  offload_grad_chunks=K            gradients computed in K balanced
                                   groups (K forward recomputes) so
                                   device grad bytes ~ largest group
  param_streaming + stream_scan    ZeRO-Infinity-style: compute copies
                                   of the stacked block params stay in
                                   host memory; the model fetches ONE
                                   layer per scan tick — device param
                                   bytes ~ one layer, past the
                                   2 bytes/param floor

    python examples/capacity_train.py --cpu --steps 5      # smoke
    python examples/capacity_train.py --layers 96          # on TPU

``bench_capacity.py`` measures the resulting peak trainable params per
chip (plain vs offload vs chunked vs streamed).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models import GPT2Config, GPT2Model  # noqa: E402
from deepspeed_tpu.parallel import build_mesh  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--layers", type=int, default=48)
    parser.add_argument("--d-model", type=int, default=1600)
    parser.add_argument("--seq", type=int, default=1024)
    parser.add_argument("--chunks", type=int, default=4,
                        help="offload_grad_chunks (1 disables)")
    parser.add_argument("--no-stream", action="store_true",
                        help="disable param streaming (chunks only)")
    parser.add_argument("--cpu", action="store_true")
    parser = deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
        # tiny shapes for the smoke: the point here is the code path
        args.layers, args.d_model, args.seq = 3, 64, 64
    import jax

    deepspeed_tpu.init_distributed()
    mesh = build_mesh(devices=jax.devices()[:1])  # capacity is per-chip
    stream = not args.no_stream
    vocab = 4096 if args.cpu else 50257
    model = GPT2Model(GPT2Config(
        vocab_size=vocab, n_positions=args.seq, d_model=args.d_model,
        n_layer=args.layers, n_head=max(4, args.d_model // 64),
        remat="block", scan_layers=True, stream_scan=stream))

    config = args.deepspeed_config or {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 5,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": dict(
            {"stage": 2, "cpu_offload": True, "offload_impl": "xla"},
            **({"offload_grad_chunks": args.chunks}
               if args.chunks > 1 else {}),
            **({"param_streaming": True} if stream else {})),
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config,
                                               mesh=mesh)
    n_params = model.config.num_params
    print(f"{n_params / 1e9:.2f}B params | chunks={args.chunks} "
          f"stream={stream} | mesh={dict(mesh.shape)}")
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        toks = rng.integers(0, vocab,
                            (engine.train_batch_size, args.seq + 1),
                            dtype=np.int32)
        loss = engine.train_batch(toks)
        if (step + 1) % 5 == 0:
            print(f"step {step + 1}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
