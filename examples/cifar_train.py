"""CIFAR-10-shaped CNN training — ladder rung 1 (BASELINE.json configs[0]:
the reference's DeepSpeedExamples/cifar tutorial, ZeRO stage 0).

Uses synthetic 32x32x3 images (this environment has no dataset egress);
swap ``synthetic_cifar`` for a real loader to train CIFAR-10 proper.

    python examples/cifar_train.py --cpu --steps 30
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.runtime.module import TrainModule  # noqa: E402


class CifarCNN(TrainModule):
    """conv-pool x2 -> dense, cross-entropy over 10 classes (the tutorial
    network's shape, expressed as a loss-returning TrainModule)."""

    def init(self, rng):
        import jax
        import jax.numpy as jnp
        k = jax.random.split(rng, 4)
        he = lambda key, shape, fan: (
            jax.random.normal(key, shape, jnp.float32)
            * np.sqrt(2.0 / fan))
        return {
            "conv1": he(k[0], (5, 5, 3, 16), 5 * 5 * 3),
            "conv2": he(k[1], (5, 5, 16, 32), 5 * 5 * 16),
            "fc1_w": he(k[2], (8 * 8 * 32, 128), 8 * 8 * 32),
            "fc1_b": jnp.zeros((128,), jnp.float32),
            "fc2_w": he(k[3], (128, 10), 128),
            "fc2_b": jnp.zeros((10,), jnp.float32),
        }

    def loss_fn(self, params, batch, rng, train=True):
        import jax
        import jax.numpy as jnp
        x, y = batch
        x = x.astype(jnp.float32)

        def block(h, w):
            h = jax.lax.conv_general_dilated(
                h, w.astype(h.dtype), (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            h = jax.nn.relu(h)
            return jax.lax.reduce_window(
                h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                "VALID")

        h = block(x, params["conv1"])
        h = block(h, params["conv2"])
        h = h.reshape(h.shape[0], -1)
        h = jax.nn.relu(h @ params["fc1_w"].astype(h.dtype)
                        + params["fc1_b"].astype(h.dtype))
        logits = (h @ params["fc2_w"].astype(h.dtype)
                  + params["fc2_b"].astype(h.dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def synthetic_cifar(batch, seed=0):
    """Class-conditional gaussian blobs — learnable, so accuracy/loss
    actually move like the tutorial's."""
    rng = np.random.default_rng(seed)
    prototypes = rng.standard_normal((10, 32, 32, 3)).astype(np.float32)
    while True:
        y = rng.integers(0, 10, (batch,), dtype=np.int32)
        x = prototypes[y] + 0.5 * rng.standard_normal(
            (batch, 32, 32, 3)).astype(np.float32)
        yield (x, y)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--cpu", action="store_true")
    parser = deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    config = args.deepspeed_config or {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=CifarCNN(),
                                               config=config)
    data = synthetic_cifar(engine.train_batch_size)
    for step in range(args.steps):
        loss = engine.train_batch(next(data))
        if (step + 1) % 10 == 0:
            print(f"step {step + 1}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
