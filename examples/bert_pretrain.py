"""BERT MLM+NSP pretraining with deepspeed_tpu (fused transformer blocks,
optional sparse attention) — the BingBertSquad/bert-pretrain shape from the
reference's examples.

    python examples/bert_pretrain.py --cpu --steps 20
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models.bert import BertConfig, BertModel  # noqa: E402


def parse_args():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--cpu", action="store_true")
    parser = deepspeed_tpu.add_config_arguments(parser)
    return parser.parse_args()


MASK_TOKEN = 1  # BERT's [MASK] id is 103; any reserved id works here


def mlm_batches(vocab, seq, batch, mask_prob=0.15, seed=0):
    """BERT masking recipe: labels carry the TRUE token at selected
    positions (-100 elsewhere) and the inputs are corrupted — 80% [MASK],
    10% random token, 10% left as-is — so the model cannot just copy."""
    rng = np.random.default_rng(seed)
    while True:
        ids = rng.integers(2, vocab, (batch, seq), dtype=np.int32)
        selected = rng.random((batch, seq)) < mask_prob
        labels = np.where(selected, ids, -100).astype(np.int32)
        roll = rng.random((batch, seq))
        corrupted = np.where(selected & (roll < 0.8), MASK_TOKEN, ids)
        corrupted = np.where(
            selected & (roll >= 0.8) & (roll < 0.9),
            rng.integers(2, vocab, (batch, seq)), corrupted)
        yield {
            "input_ids": corrupted.astype(np.int32),
            "masked_lm_labels": labels,
            "next_sentence_label": rng.integers(0, 2, (batch,),
                                                dtype=np.int32),
        }


def main():
    args = parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    model = BertModel(BertConfig(
        vocab_size=8192, hidden_size=args.hidden,
        num_hidden_layers=args.layers, num_attention_heads=args.heads,
        intermediate_size=4 * args.hidden,
        max_position_embeddings=max(args.seq, 128)))

    config = args.deepspeed_config or {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10,
        "bf16": {"enabled": True},
        "optimizer": {"type": "lamb", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    data = mlm_batches(8192, args.seq, engine.train_batch_size)
    for step in range(args.steps):
        loss = engine.train_batch(next(data))
        if (step + 1) % 10 == 0:
            print(f"step {step + 1}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
