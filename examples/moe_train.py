"""Mixture-of-Experts GPT-2 training with expert parallelism.

Expert parallelism is a modern feature slot (absent from the reference
v0.3.2 snapshot, SURVEY.md §2.4): alternating dense/MoE blocks, top-1/2
token routing, experts sharded over the data-parallel mesh axis (ep ⊆ dp,
the DeepSpeed-MoE mapping) — declared as placement, not process groups.

Run (virtual 8-device CPU mesh smoke; real TPU by default):

    python examples/moe_train.py --cpu --steps 30 --n_experts 4
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models import GPT2MoEConfig, GPT2MoEModel  # noqa: E402


def parse_args():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--d_model", type=int, default=256)
    parser.add_argument("--n_layer", type=int, default=4)
    parser.add_argument("--n_head", type=int, default=8)
    parser.add_argument("--vocab", type=int, default=50257)
    parser.add_argument("--n_experts", type=int, default=8)
    parser.add_argument("--top_k", type=int, default=1,
                        help="1 = Switch routing, 2 = GShard")
    parser.add_argument("--capacity_factor", type=float, default=1.25)
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel degree (expert hidden dim)")
    parser.add_argument("--cpu", action="store_true",
                        help="run on a virtual 8-device CPU mesh")
    parser = deepspeed_tpu.add_config_arguments(parser)
    return parser.parse_args()


def synthetic_documents(vocab: int, seq: int, batch: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    base = rng.integers(0, vocab, size=(1 << 16,), dtype=np.int32)
    while True:
        idx = rng.integers(0, len(base) - seq - 1, size=(batch,))
        yield np.stack([base[i:i + seq + 1] for i in idx])


def main():
    args = parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu.parallel import build_mesh
    mesh = build_mesh(tp=args.tp)

    model = GPT2MoEModel(GPT2MoEConfig(
        vocab_size=args.vocab, n_positions=max(args.seq, 128),
        d_model=args.d_model, n_layer=args.n_layer, n_head=args.n_head,
        n_experts=args.n_experts, moe_top_k=args.top_k,
        capacity_factor=args.capacity_factor))

    config = args.deepspeed_config or {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 3e-4}},
        "zero_optimization": {"stage": 2},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, model=model, config=config, mesh=mesh)

    wi = engine.state.master_params["moe"]["wi"]
    print(f"experts: {model.config.n_experts} on layers "
          f"{model.config.moe_layers}; wi sharding {wi.sharding.spec} "
          f"(shard {wi.sharding.shard_shape(wi.shape)} of {wi.shape})")

    data = synthetic_documents(args.vocab, args.seq,
                               engine.train_batch_size)
    for step in range(args.steps):
        loss = engine.train_batch(next(data))
        if (step + 1) % 10 == 0:
            print(f"step {step + 1}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
