"""Long-context training with ring-attention sequence parallelism —
the modern replacement for the reference's sparse-attention long-sequence
slot (SURVEY.md §5.7), plus the Pallas flash kernel for the non-sharded
case.

    python examples/long_context.py --cpu --steps 5 --seq 2048 --sp 2

Each device holds seq/sp of every activation; K/V shards rotate over the
``seq`` mesh axis with an online-softmax accumulator, so the attention
memory per device stays O(seq/sp) — no T×T scores anywhere.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models import GPT2Config, GPT2Model  # noqa: E402
from deepspeed_tpu.parallel import build_mesh  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--seq", type=int, default=2048)
    parser.add_argument("--sp", type=int, default=2,
                        help="sequence-parallel shards (ring attention)")
    parser.add_argument("--attn", type=str, default="ring",
                        choices=("ring", "ulysses", "flash"))
    parser.add_argument("--dropout", type=float, default=0.0,
                        help="attention-probability dropout — runs inside "
                        "the sequence-parallel schemes via the "
                        "position-hashed mask (layout-independent)")
    parser.add_argument("--cpu", action="store_true")
    parser = deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    # multi-host contract: join the jax.distributed world BEFORE the
    # first device enumeration (initialize() does this internally when it
    # builds the mesh; here we build our own)
    deepspeed_tpu.init_distributed()
    sp = args.sp if args.attn in ("ring", "ulysses") else 1
    if args.seq % max(sp, 1):
        parser.error(f"--seq {args.seq} must be divisible by --sp {sp}")
    mesh = build_mesh(pp=1, sp=sp, tp=1, devices=jax.devices())
    model = GPT2Model(GPT2Config(
        vocab_size=4096, n_positions=args.seq, d_model=128, n_layer=2,
        n_head=8, dropout=args.dropout, embd_dropout=0.0,
        attn_impl=args.attn, remat="block"))

    config = args.deepspeed_config or {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 5,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 3e-4}},
        "zero_optimization": {"stage": 2},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config,
                                               mesh=mesh)
    print(f"mesh={dict(mesh.shape)} attn={args.attn} seq={args.seq}")
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        toks = rng.integers(0, 4096,
                            (engine.train_batch_size, args.seq + 1),
                            dtype=np.int32)
        loss = engine.train_batch(toks)
        if (step + 1) % 5 == 0:
            print(f"step {step + 1}: loss {float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
