"""GPT-2 pretraining with deepspeed_tpu — the user-facing training-script
shape the reference documents (argparse injection + ds_config.json +
initialize + train_batch loop + checkpointing).

Run (CPU mesh for a smoke, real TPU by default):

    python examples/gpt2_pretrain.py --deepspeed \
        --deepspeed_config examples/ds_config.json --steps 50

Swap the synthetic corpus for a real token stream by replacing
``synthetic_documents``.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models import GPT2Config, GPT2Model  # noqa: E402


def parse_args():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--d_model", type=int, default=256)
    parser.add_argument("--n_layer", type=int, default=4)
    parser.add_argument("--n_head", type=int, default=8)
    parser.add_argument("--vocab", type=int, default=50257)
    parser.add_argument("--checkpoint_dir", type=str, default="")
    parser.add_argument("--cpu", action="store_true",
                        help="run on a virtual 8-device CPU mesh")
    parser = deepspeed_tpu.add_config_arguments(parser)
    # LR tuning flags (--lr_schedule WarmupLR --warmup_max_lr ... etc.)
    parser = deepspeed_tpu.add_tuning_arguments(parser)
    return parser.parse_args()


def synthetic_documents(vocab: int, seq: int, batch: int, seed: int = 0):
    """Endless [batch, seq+1] int32 token batches with bigram structure."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, vocab, size=(1 << 16,), dtype=np.int32)
    while True:
        idx = rng.integers(0, len(base) - seq - 1, size=(batch,))
        yield np.stack([base[i:i + seq + 1] for i in idx])


def main():
    args = parse_args()
    if args.cpu:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    model = GPT2Model(GPT2Config(
        vocab_size=args.vocab, n_positions=max(args.seq, 128),
        d_model=args.d_model, n_layer=args.n_layer, n_head=args.n_head,
        remat="block"))

    from deepspeed_tpu.runtime.lr_schedules import schedule_params_from_args
    config = args.deepspeed_config or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "ds_config.json")
    sched_override = schedule_params_from_args(args)
    if sched_override is not None:
        import json
        with open(config) as f:
            config = json.load(f)
        config["scheduler"] = sched_override

    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, model=model, config=config)

    data = synthetic_documents(args.vocab, args.seq,
                               engine.train_batch_size)
    for step in range(args.steps):
        loss = engine.train_batch(next(data))
        if (step + 1) % 10 == 0:
            print(f"step {step + 1}: loss {float(np.asarray(loss)):.4f}")

    if args.checkpoint_dir:
        engine.save_checkpoint(args.checkpoint_dir, tag="final")
        print(f"checkpoint written to {args.checkpoint_dir}")


if __name__ == "__main__":
    main()
