"""Packaging (reference: setup.py — DS_BUILD_* driven op pre-compilation).

``pip install .`` ships the pure-Python package plus the csrc/ sources;
the native host ops build lazily on first use (ops/op_builder.py) or
eagerly here with DS_BUILD_CPU_ADAM=1, mirroring the reference's
pre-install vs JIT split (reference setup.py + op_builder/builder.py).
"""
import os

from setuptools import find_packages, setup


def _maybe_prebuild():
    if os.environ.get("DS_BUILD_CPU_ADAM", "0") == "1":
        import sys
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from deepspeed_tpu.ops.op_builder import build_cpu_ops
        print(f"[deepspeed_tpu] prebuilt native ops: {build_cpu_ops()}")


_maybe_prebuild()

version = {}
with open("deepspeed_tpu/version.py") as f:
    exec(f.read(), version)

setup(
    name="deepspeed_tpu",
    version=version["__version__"],
    description="TPU-native deep learning optimization library "
                "(ZeRO, pipeline/tensor/sequence parallelism, 1-bit Adam, "
                "sparse attention) built on JAX/XLA/Pallas",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    # csrc/ ships in the sdist via MANIFEST.in; a wheel install without the
    # sources degrades gracefully (op_builder reports the numpy fallback)
    scripts=["bin/ds", "bin/ds_report", "bin/ds_ssh", "bin/deepspeed", "bin/deepspeed.pt"],
    python_requires=">=3.10",
    install_requires=["jax", "optax", "numpy", "ml_dtypes"],
)
