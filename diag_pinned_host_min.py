"""Minimal mechanism repro for the xla-offload HBM question.

The 1.5B step OOM'd with fp32 piece-shaped HBM temps despite pinned_host
residency (round-5 window); the engine-scale diag compiles too slowly to
iterate.  This strips the mechanism to its skeleton: N pinned_host fp32
"master pieces", one donated jit that (a) host-casts them to bf16 and
uploads, (b) computes a stand-in gradient on device, (c) ships grad
pieces to host, (d) runs the Adam recurrences in a compute_on host
section, returning updated pinned_host pieces.  Then prints the
compiler's memory analysis and a one-step wall time.

If HBM temps ~ bf16 bytes -> mechanism works; the engine's OOM is
program structure.  If HBM temps ~ fp32 state -> the AOT path ignores
host placement and the fix is program-boundary chunking.

Knobs: PIECES (default 8), PIECE_MB (default 256), DS_MIN_COMPUTE_ON=0
to run the optimizer math on device with pinned_host residency only.
"""
import json
import os
import sys
import time

import numpy as np

_T0 = time.time()


def _mark(m):
    print(f"[min {time.time() - _T0:6.1f}s] {m}", file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental import compute_on

    n_pieces = int(os.environ.get("PIECES", "8"))
    piece_mb = int(os.environ.get("PIECE_MB", "256"))
    use_compute_on = os.environ.get("DS_MIN_COMPUTE_ON", "1") == "1"
    w = piece_mb * (1 << 20) // 4

    dev = jax.devices()[0]
    mesh = jax.sharding.Mesh(np.array([dev]), ("data",))
    s_dev = NamedSharding(mesh, P())
    s_host = s_dev.with_memory_kind("pinned_host")

    def host_section():
        if use_compute_on:
            return compute_on.compute_on("device_host")
        import contextlib
        return contextlib.nullcontext()

    _mark(f"staging {3 * n_pieces * piece_mb} MB fp32 to pinned_host")
    masters = tuple(
        jax.device_put(jnp.full((w,), 0.01 * (i + 1), jnp.float32), s_host)
        for i in range(n_pieces))
    mus = tuple(jax.device_put(jnp.zeros((w,), jnp.float32), s_host)
                for _ in range(n_pieces))
    nus = tuple(jax.device_put(jnp.zeros((w,), jnp.float32), s_host)
                for _ in range(n_pieces))

    def step(masters, mus, nus, x):
        # (a) cast-up on host, upload bf16
        with host_section():
            lowp = [m.astype(jnp.bfloat16) for m in masters]
        params = [jax.device_put(p, s_dev) for p in lowp]
        # (b) stand-in gradient: a little device math per piece
        grads = [jnp.tanh(p * x) * 0.1 for p in params]
        # (c) ship grad pieces to host
        ghost = [jax.device_put(g, s_host) for g in grads]
        # (d) Adam on host
        with host_section():
            new_m, new_mu, new_nu = [], [], []
            for m, mu, nu, g in zip(masters, mus, nus, ghost):
                g32 = g.astype(jnp.float32)
                mu2 = 0.9 * mu + 0.1 * g32
                nu2 = 0.999 * nu + 0.001 * g32 * g32
                upd = mu2 / (jnp.sqrt(nu2) + 1e-8)
                new_m.append(m - 1e-3 * upd)
                new_mu.append(mu2)
                new_nu.append(nu2)
        loss = sum(jnp.sum(g[:8].astype(jnp.float32)) for g in grads)
        return tuple(new_m), tuple(new_mu), tuple(new_nu), loss

    shard = (
        (s_host,) * n_pieces, (s_host,) * n_pieces, (s_host,) * n_pieces,
        s_dev)
    fn = jax.jit(step, donate_argnums=(0, 1, 2), out_shardings=shard)
    x = jax.device_put(jnp.asarray(2.0, jnp.bfloat16), s_dev)

    jax.block_until_ready(masters)
    _mark("staged; lowering")
    t0 = time.time()
    lowered = fn.lower(masters, mus, nus, x)
    _mark("lowered; compiling")
    compiled = lowered.compile()
    _mark("compiled")
    compile_s = time.time() - t0
    rec = {"pieces": n_pieces, "piece_mb": piece_mb,
           "compute_on": use_compute_on,
           "compile_s": round(compile_s, 1),
           "fp32_state_mb": 3 * n_pieces * piece_mb,
           "bf16_params_mb": n_pieces * piece_mb // 2}
    try:
        mem = compiled.memory_analysis()
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes"):
            v = getattr(mem, k, None)
            if v is not None:
                rec[k.replace("_size_in_bytes", "_mb")] = round(
                    int(v) / (1 << 20), 1)
    except Exception as e:  # noqa: BLE001
        rec["memory_analysis_error"] = repr(e)

    # one real step: does it run, and how fast
    t0 = time.time()
    masters, mus, nus, loss = compiled(masters, mus, nus, x)
    jax.block_until_ready(loss)
    rec["first_step_s"] = round(time.time() - t0, 2)
    t0 = time.time()
    masters, mus, nus, loss = compiled(masters, mus, nus, x)
    jax.block_until_ready(loss)
    rec["steady_step_s"] = round(time.time() - t0, 3)
    rec["loss"] = float(np.asarray(loss))
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
