"""Hardware probes for the two open offload design questions (round 3).

1. host-section bandwidth: how fast does an XLA ``compute_on
   ('device_host')`` elementwise pass run over pinned_host data on THIS
   platform?  The 1.5B step's host Adam touches ~37 GB of pinned_host
   state per optimizer step; at the measured GB/s this either vanishes
   behind ga=32 amortization or dominates the step — the direct signal
   for whether a delayed-parameter-update overlap is worth building.

2. param streaming: can a lax.scan consume a HOST-resident stacked
   array one slice per iteration without materializing the whole array
   in device memory (checked via memory_stats peak deltas)?  If yes,
   ZeRO-Infinity-style param streaming (device param bytes ~ one layer)
   is expressible directly in XLA — the capacity path past the 2 bytes/
   param floor that bounds offload_grad_chunks.

Run on a healthy tunnel: ``python diag_hostperf.py``.  Writes
DIAG_hostperf.json.  CPU smoke: pinned_host degrades to device memory,
numbers are meaningless but the program shapes are validated.
"""
import json
import sys
import time

import numpy as np


def _mark(m):
    print(f"[hostperf] {m}", file=sys.stderr, flush=True)


def bench_host_section(jax, jnp, real_host: bool, gb: float = 1.0):
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = None
    from deepspeed_tpu.parallel import build_mesh
    mesh = build_mesh(devices=jax.devices()[:1])
    sh = NamedSharding(mesh, P())
    host_sh = sh.with_memory_kind("pinned_host") if real_host else sh
    n = int(gb * (1 << 30) / 4)
    _mark(f"allocating {gb} GiB in {'pinned_host' if real_host else 'device'}")
    x = jax.device_put(jnp.zeros((n,), jnp.float32), host_sh)
    y = jax.device_put(jnp.ones((n,), jnp.float32), host_sh)

    def host_fma(x, y):
        if real_host:
            from jax.experimental import compute_on
            with compute_on.compute_on("device_host"):
                out = x * 0.999 + y * 1e-3
        else:
            out = x * 0.999 + y * 1e-3
        return out

    f = jax.jit(host_fma, out_shardings=host_sh, donate_argnums=(0,))
    x = f(x, y)
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    iters = 5
    for _ in range(iters):
        x = f(x, y)
    jax.block_until_ready(x)
    dt = (time.perf_counter() - t0) / iters
    gbs = 3 * gb / dt  # 2 reads + 1 write per element
    _mark(f"host-section fma: {dt*1e3:.1f} ms/pass -> {gbs:.1f} GB/s")
    return {"host_fma_ms": round(dt * 1e3, 2),
            "host_fma_gbps": round(gbs, 2)}


def bench_param_stream(jax, jnp, real_host: bool, layers=16, mb=64):
    """Scan over a host-resident [L, n] stack, one slice used per
    iteration; compare device peak_bytes delta to full-stack size."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.parallel import build_mesh
    mesh = build_mesh(devices=jax.devices()[:1])
    sh = NamedSharding(mesh, P())
    host_sh = sh.with_memory_kind("pinned_host") if real_host else sh
    n = int(mb * (1 << 20) / 4)
    stack_bytes = layers * n * 4
    _mark(f"staging [{layers}, {n}] ({stack_bytes >> 20} MiB) on host")
    stack = jax.device_put(jnp.ones((layers, n), jnp.float32), host_sh)
    d = jax.local_devices()[0]

    def stats():
        try:
            return dict(d.memory_stats() or {})
        except Exception:
            return {}

    # two access patterns — GPT-2's scan consumes stacked params as scan
    # XS (models/gpt2.py:171); the closure+dynamic_index form is the
    # fallback shape a streaming redesign would use if xs don't stream
    def step_xs(stack, x):
        def body(carry, w):
            return carry * 0.5 + jnp.dot(w[:8], carry[:8]) * 0.01, None

        out, _ = jax.lax.scan(body, x, stack)
        return out

    def step_index(stack, x):
        def body(carry, i):
            w = jax.lax.dynamic_index_in_dim(stack, i, 0, keepdims=False)
            w = jax.device_put(w, sh)  # host -> device, one layer
            return carry * 0.5 + jnp.dot(w[:8], carry[:8]) * 0.01, None

        out, _ = jax.lax.scan(body, x, jnp.arange(layers))
        return out

    rec = {"stack_mb": stack_bytes >> 20}
    x = jnp.ones((n,), jnp.float32)
    for name, fn in (("xs", step_xs), ("index", step_index)):
        f = jax.jit(fn)
        before = stats().get("peak_bytes_in_use", 0)
        try:
            out = f(stack, x)
            jax.block_until_ready(out)
        except Exception as e:
            _mark(f"{name}: FAILED {type(e).__name__}: {e}")
            rec[name] = {"error": str(e)[:200]}
            continue
        after = stats().get("peak_bytes_in_use", 0)
        delta = after - before
        streamed = bool(after and delta < stack_bytes * 0.6)
        _mark(f"{name}: peak delta {delta >> 20} MiB vs stack "
              f"{stack_bytes >> 20} MiB -> "
              f"{'STREAMED' if streamed else 'materialized/unknown'}")
        t0 = time.perf_counter()
        for _ in range(3):
            out = f(stack, out)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / 3
        rec[name] = {
            "peak_delta_mb": int(delta) >> 20 if after else None,
            "streamed": streamed if after else None,
            "scan_ms": round(dt * 1e3, 2),
            "stream_gbps": round(stack_bytes / (1 << 30) / dt, 2)}
    return rec


def bench_remat_offload(jax, jnp, real_host: bool, n=2048, depth=4):
    """cpu_checkpointing's remat-offload policy ON HARDWARE: does the
    lowered grad program actually annotate saved dot residuals into host
    memory (the thing the CPU test suite cannot see — CPU lowering
    erases memory kinds), and what does the offload cost per pass?"""
    pol = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
        "device", "pinned_host")

    def block(x, w):
        for _ in range(depth):
            x = jnp.tanh(x @ w)
        return x.sum()

    w = jnp.ones((n, n), jnp.bfloat16)
    x = jnp.ones((8, n), jnp.bfloat16)
    rec = {}
    for name, p in (("offload", pol), ("full_remat", None)):
        g = jax.jit(jax.grad(jax.checkpoint(block, policy=p), argnums=1))
        try:
            txt = g.lower(x, w).as_text()
            annotated = ("pinned_host" in txt
                         or "annotate_device_placement" in txt)
            out = g(x, w)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(5):
                out = g(x, w)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / 5
            rec[name] = {"host_annotated": bool(annotated),
                         "grad_ms": round(dt * 1e3, 2)}
        except Exception as e:
            _mark(f"remat_offload[{name}]: FAILED {type(e).__name__}: {e}")
            rec[name] = {"error": str(e)[:200]}
    _mark(f"remat_offload: {rec}")
    return rec


def main():
    sys.path.insert(0, ".")
    from bench import guarded_devices
    devices = guarded_devices()
    on_tpu = devices[0].platform != "cpu"
    import jax
    import jax.numpy as jnp
    rec = {"device": str(devices[0]), "real_host": on_tpu}
    gb = 1.0 if on_tpu else 0.02
    rec["host_section"] = bench_host_section(jax, jnp, on_tpu, gb=gb)
    rec["param_stream"] = bench_param_stream(
        jax, jnp, on_tpu, layers=16, mb=256 if on_tpu else 4)
    rec["remat_offload"] = bench_remat_offload(
        jax, jnp, on_tpu, n=2048 if on_tpu else 64)
    print(json.dumps(rec))
    if on_tpu:
        with open("DIAG_hostperf.json", "w") as f:
            json.dump(rec, f, indent=1)


if __name__ == "__main__":
    main()
