"""CPU-Adam kernel microbenchmark — the analogue of the reference's
tests/perf/adam_test.py (it times DeepSpeedCPUAdam on large tensors; the
reference claims 5-7x over torch.optim.Adam, ops/adam/cpu_adam.py:18 there).

Times one optimizer step over a large fp32 parameter buffer for:
  native   — the C++ SIMD/OpenMP kernel (csrc/cpu_adam.cpp) with fused
             bf16 copy-out (the ZeRO-Offload hot loop)
  numpy    — the pure-numpy fallback path
  torch    — torch.optim.Adam (the reference's comparison target)

Prints one JSON line; vs_baseline = torch_time / native_time / 5.0
(>=1 matches the low end of the reference's 5-7x claim).
"""
import json
import time

import numpy as np

N = 50_000_000  # 50M params ~ 200 MB fp32, matches the reference's scale
STEPS = 5


def _time(fn, steps=STEPS):
    fn()  # warmup
    t0 = time.perf_counter()
    for _ in range(steps):
        fn()
    return (time.perf_counter() - t0) / steps


def main():
    import sys
    sys.path.insert(0, ".")
    from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam

    rng = np.random.default_rng(0)
    grads = rng.standard_normal(N).astype(np.float32) * 1e-3

    results = {}

    p_nat = rng.standard_normal(N).astype(np.float32)
    opt_nat = DeepSpeedCPUAdam(lr=1e-3, use_native=True)
    results["native_s"] = _time(
        lambda: opt_nat.step(p_nat, grads, out_dtype="bfloat16"))

    p_np = p_nat.copy()
    opt_np = DeepSpeedCPUAdam(lr=1e-3, use_native=False)
    results["numpy_s"] = _time(
        lambda: opt_np.step(p_np, grads, out_dtype="bfloat16"))

    try:
        import torch
        tp = torch.from_numpy(p_nat.copy())
        tp.grad = torch.from_numpy(grads.copy())
        topt = torch.optim.Adam([tp], lr=1e-3)
        results["torch_s"] = _time(lambda: topt.step())
    except Exception:
        results["torch_s"] = None

    native = results["native_s"]
    # None (not 0.0) when torch is unavailable: "comparison missing" must
    # be distinguishable from "infinitely slower"
    speedup_torch = (round(results["torch_s"] / native, 2)
                     if results["torch_s"] else None)
    speedup_numpy = results["numpy_s"] / native
    import os
    out = {
        "metric": "cpu_adam_native_step_time_50m",
        "value": round(native, 4),
        "unit": "s/step",
        "speedup_vs_torch": speedup_torch,
        "speedup_vs_numpy": round(speedup_numpy, 2),
        # the reference's 5-7x is measured on many-core hosts; the OpenMP
        # scaling that delivers it needs cores (record how many we had)
        "cpu_count": os.cpu_count(),
        "vs_baseline": (round(speedup_torch / 5.0, 4)
                        if speedup_torch is not None else 0.0),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
