"""Peak trainable parameters on ONE chip with ZeRO-Offload — the second
BASELINE metric (BASELINE.md:26; the reference's headline is 13B on a
32 GB V100 with CPU offload vs 1.4B plain DP, features.md:115 there).

Binary-searches GPT-2 depth (d_model fixed at 1600) for the largest model
that completes one full training step, twice: with the XLA host-offload
tier (fp32 master + moments in pinned host memory) and without offload
(fp32 state in HBM).  Reports both and the ratio — the "10x larger models"
claim is the ratio.  Writes BENCH_capacity.json.

Each probe runs in a fresh subprocess: an OOM'd XLA client can leave HBM
fragmented, and a clean exit releases everything deterministically.
"""
import json
import os
import subprocess
import sys

PROBE = """
import sys
import numpy as np
import jax
sys.path.insert(0, {repo!r})
try:  # shared persistent compile cache (bench.py's dir): re-runs skip
    import os
    jax.config.update("jax_compilation_cache_dir", os.path.join(
        {repo!r}, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

n_layer, offload = int(sys.argv[1]), bool(int(sys.argv[2]))
chunks = int(os.environ.get("CAPACITY_GRAD_CHUNKS", "0"))
stream = os.environ.get("CAPACITY_PARAM_STREAM", "0") == "1"
if len(sys.argv) > 3 and sys.argv[3] == "smoke":  # CPU plumbing check
    jax.config.update("jax_platforms", "cpu")
    cfg_model = GPT2Config(d_model=64, n_layer=n_layer, n_head=4,
                           vocab_size=256, n_positions=64, remat=None,
                           scan_layers=True, stream_scan=stream)
else:
    cfg_model = GPT2Config(d_model=1600, n_layer=n_layer, n_head=25,
                           vocab_size=50257, n_positions=1024,
                           remat="block", scan_layers=True,
                           stream_scan=stream)
zero = {{"stage": 2, "cpu_offload": True, "offload_impl": "xla"}} if offload \
    else {{"stage": 0}}
if offload and chunks > 1:
    zero["offload_grad_chunks"] = chunks
if offload and stream:
    zero["param_streaming"] = True
# split update by default for offload probes: the fused update program
# materializes the whole fp32 state as HBM temps on the AOT compile
# path (the round-5 1.5B OOM), which would cap the measured offload
# capacity at roughly the no-offload level.  CAPACITY_SPLIT_UPDATE=0
# measures the fused structure deliberately.
if offload and os.environ.get("CAPACITY_SPLIT_UPDATE", "1") == "1":
    zero["offload_split_update"] = True
ds_cfg = DeepSpeedConfig({{
    "train_micro_batch_size_per_gpu": 1,
    "gradient_accumulation_steps": 1,
    "steps_per_print": 10 ** 9,
    "bf16": {{"enabled": True}},
    "optimizer": {{"type": "Adam", "params": {{"lr": 1e-4}}}},
    "zero_optimization": zero,
}}, world_size=1)
engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg,
                         mesh=build_mesh(devices=jax.devices()[:1]))
tokens = np.zeros((1, min(cfg_model.n_positions, 1024) + 1), dtype=np.int32)
loss = float(np.asarray(engine.train_batch(tokens)))
assert np.isfinite(loss), loss
print("PROBE_OK", cfg_model.num_params)
"""


def _split_update_env() -> str:
    """One resolution of the split-update knob, recorded in the artifact:
    a fused-structure run's capacity number must be distinguishable from
    the (default) split-update run's."""
    return os.environ.get("CAPACITY_SPLIT_UPDATE", "1")


def _probe(n_layer: int, offload: bool, timeout: int,
           smoke: bool = False, chunks: int = 0,
           stream: bool = False) -> int:
    """Return param count if one step trains at this depth, else 0."""
    argv = [sys.executable, "-u", "-c",
            PROBE.format(repo=os.path.dirname(os.path.abspath(__file__))),
            str(n_layer), str(int(offload))]
    if smoke:
        argv.append("smoke")
    env = dict(os.environ)
    env["CAPACITY_GRAD_CHUNKS"] = str(chunks)
    env["CAPACITY_PARAM_STREAM"] = "1" if stream else "0"
    env["CAPACITY_SPLIT_UPDATE"] = _split_update_env()
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        # a wedged probe near the OOM boundary counts as a failed size —
        # the bisection must continue, not abort
        print(f"  probe n_layer={n_layer} offload={offload} timed out "
              f"after {timeout}s", file=sys.stderr)
        return 0
    for line in proc.stdout.splitlines():
        if line.startswith("PROBE_OK"):
            return int(line.split()[1])
    print(f"  probe n_layer={n_layer} offload={offload} failed "
          f"(rc={proc.returncode}): {proc.stderr.strip()[-300:]}",
          file=sys.stderr)
    return 0


D_MODEL = 1600
PER_LAYER = 12 * D_MODEL * D_MODEL + 13 * D_MODEL  # GPT-2 block params
EMB = (50257 + 1024) * D_MODEL


def _hbm_bytes(timeout: int) -> int:
    """bytes_limit of the real chip, probed in a subprocess (the probe
    only initializes a backend — killable without wedging device state)."""
    code = ("import jax; d = jax.local_devices()[0]; "
            "print('HBM', d.memory_stats().get('bytes_limit', 0))")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
        for line in p.stdout.splitlines():
            if line.startswith("HBM"):
                v = int(line.split()[1])
                if v > 0:
                    return v
    except subprocess.TimeoutExpired:
        pass
    return 16 << 30  # v5e default


def _predict_layers(offload: bool, hbm: int, chunks: int = 0,
                    stream: bool = False) -> int:
    """Analytic seed for the search: device bytes/param at micro=1 ga=1.

    no-offload stage 0: fp32 master+mu+nu (12) + bf16 params (2) + fp32
    grads (4) = 18 B/param.  offload xla tier (piece-wise staging, bf16
    init above the fp32 limit, scanless ga=1 grads): bf16 params (2) +
    bf16 grads (2) + one staging piece ~= 4.5 B/param.  param_streaming
    removes the resident bf16 params (device holds ~ one layer), leaving
    the grad term — 2/K with K grad chunks — plus slack for the in-
    flight slices.  ~1.5 GB margin for activations (seq 1024, micro 1,
    block remat + fp32 logits), workspace, and fragmentation."""
    margin = int(1.5 * (1 << 30))
    if not offload:
        per_param = 18.0
    elif stream:
        per_param = (2.0 / chunks if chunks > 1 else 2.0) + 0.6
    elif chunks > 1:
        # chunked: bf16 params (2) + largest grad group (~2/K) + slack
        per_param = 2.0 + 2.0 / chunks + 0.6
    else:
        per_param = 4.5
    budget = max(hbm - margin, 1 << 30)
    return max(1, int((budget / per_param - EMB) / PER_LAYER))


def _search_seeded(offload: bool, seed_layers: int, timeout: int,
                   max_probes: int = 6, chunks: int = 0,
                   stream: bool = False):
    """Largest working n_layer with a bounded probe budget: start at the
    analytic prediction, climb geometrically while passing (the model
    may be conservative), fall back geometrically while failing, then
    one refinement bisect in the final bracket.  Each probe is a fresh
    subprocess (OOM leaves fragmented HBM; exit releases it)."""
    probes = 0

    def probe(n):
        nonlocal probes
        probes += 1
        return _probe(n, offload, timeout, chunks=chunks, stream=stream)

    n = max(1, seed_layers)
    params = probe(n)
    if params:
        best, best_params = n, params
        hi_fail = None
        while probes < max_probes:
            nxt = max(best + 1, int(best * 1.3))
            p = probe(nxt)
            if p:
                best, best_params = nxt, p
            else:
                hi_fail = nxt
                break
    else:
        # prediction too optimistic: halve until something trains (no
        # give-up floor — a failing size only tightens the bracket), then
        # refine upward like the climb branch
        hi_fail, best, best_params = n, 0, 0
        while probes < max_probes and hi_fail > 1:
            n = max(1, hi_fail // 2)
            params = probe(n)
            if params:
                best, best_params = n, params
                break
            hi_fail = n
        if not best_params:
            return 0, 0
    # refinement bisect in the final (best, hi_fail) bracket
    while hi_fail is not None and probes < max_probes:
        mid = (best + hi_fail) // 2
        if mid <= best:
            break
        p = probe(mid)
        if p:
            best, best_params = mid, p
        else:
            hi_fail = mid
    return best, best_params


def main():
    timeout = int(os.environ.get("CAPACITY_PROBE_TIMEOUT", "1200"))
    if os.environ.get("CAPACITY_SMOKE"):
        # validate the subprocess plumbing on CPU (no OOM boundary there)
        ok = _probe(2, False, timeout, smoke=True)
        ok_off = _probe(2, True, timeout, smoke=True)
        ok_stream = _probe(2, True, timeout, smoke=True, chunks=2,
                           stream=True)
        print(json.dumps({"metric": "capacity_smoke", "value": 1.0,
                          "unit": "ok",
                          "vs_baseline": float(bool(ok and ok_off
                                                    and ok_stream))}))
        return
    hbm = _hbm_bytes(timeout=min(timeout, 300))
    chunks = int(os.environ.get("CAPACITY_CHUNKS", "4"))
    p_plain = _predict_layers(False, hbm)
    p_off = _predict_layers(True, hbm)
    p_ck = _predict_layers(True, hbm, chunks)
    p_st = _predict_layers(True, hbm, chunks, stream=True)
    max_probes = int(os.environ.get("CAPACITY_MAX_PROBES", "6"))
    print(f"  hbm={hbm / (1 << 30):.1f} GiB predict: plain={p_plain} "
          f"offload={p_off} chunked(k={chunks})={p_ck} "
          f"stream+chunked={p_st} layers",
          file=sys.stderr)
    plain_layers, plain_params = _search_seeded(False, p_plain, timeout,
                                                max_probes)
    off_layers, off_params = _search_seeded(True, p_off, timeout,
                                            max_probes)
    ck_layers, ck_params = (0, 0)
    if chunks > 1:
        ck_layers, ck_params = _search_seeded(
            True, max(p_ck, off_layers), timeout, max_probes,
            chunks=chunks)
    # param streaming (ZeRO-Infinity-style): host-resident stacked
    # compute params break the 2 B/param device floor entirely —
    # the mode that reaches past the reference's 10x claim
    st_layers, st_params = _search_seeded(
        True, max(p_st, ck_layers, off_layers), timeout, max_probes,
        chunks=chunks, stream=True)
    best_params = max(off_params, ck_params, st_params)
    ratio = best_params / plain_params if plain_params else 0.0
    out = {
        "metric": "offload_peak_trainable_params_per_chip",
        "value": round(best_params / 1e9, 3),
        "unit": "B params",
        "no_offload_params_b": round(plain_params / 1e9, 3),
        "offload_params_b": round(off_params / 1e9, 3),
        "offload_chunked_params_b": round(ck_params / 1e9, 3),
        "offload_stream_params_b": round(st_params / 1e9, 3),
        "grad_chunks": chunks,
        "split_update": _split_update_env() == "1",
        "offload_layers": off_layers,
        "offload_chunked_layers": ck_layers,
        "offload_stream_layers": st_layers,
        "no_offload_layers": plain_layers,
        "capacity_ratio": round(ratio, 2),
        # reference: 10x larger models via offload (BASELINE.md:16)
        "vs_baseline": round(ratio / 10.0, 4),
    }
    print(json.dumps(out))
    with open("BENCH_capacity.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
