"""Peak trainable parameters on ONE chip with ZeRO-Offload — the second
BASELINE metric (BASELINE.md:26; the reference's headline is 13B on a
32 GB V100 with CPU offload vs 1.4B plain DP, features.md:115 there).

Binary-searches GPT-2 depth (d_model fixed at 1600) for the largest model
that completes one full training step, twice: with the XLA host-offload
tier (fp32 master + moments in pinned host memory) and without offload
(fp32 state in HBM).  Reports both and the ratio — the "10x larger models"
claim is the ratio.  Writes BENCH_capacity.json.

Each probe runs in a fresh subprocess: an OOM'd XLA client can leave HBM
fragmented, and a clean exit releases everything deterministically.
"""
import json
import os
import subprocess
import sys

PROBE = """
import sys
import numpy as np
import jax
sys.path.insert(0, {repo!r})
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

n_layer, offload = int(sys.argv[1]), bool(int(sys.argv[2]))
if len(sys.argv) > 3 and sys.argv[3] == "smoke":  # CPU plumbing check
    jax.config.update("jax_platforms", "cpu")
    cfg_model = GPT2Config(d_model=64, n_layer=n_layer, n_head=4,
                           vocab_size=256, n_positions=64, remat=None)
else:
    cfg_model = GPT2Config(d_model=1600, n_layer=n_layer, n_head=25,
                           vocab_size=50257, n_positions=1024,
                           remat="block", scan_layers=True)
zero = {{"stage": 2, "cpu_offload": True, "offload_impl": "xla"}} if offload \
    else {{"stage": 0}}
ds_cfg = DeepSpeedConfig({{
    "train_micro_batch_size_per_gpu": 1,
    "gradient_accumulation_steps": 1,
    "steps_per_print": 10 ** 9,
    "bf16": {{"enabled": True}},
    "optimizer": {{"type": "Adam", "params": {{"lr": 1e-4}}}},
    "zero_optimization": zero,
}}, world_size=1)
engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg,
                         mesh=build_mesh(devices=jax.devices()[:1]))
tokens = np.zeros((1, min(cfg_model.n_positions, 1024) + 1), dtype=np.int32)
loss = float(np.asarray(engine.train_batch(tokens)))
assert np.isfinite(loss), loss
print("PROBE_OK", cfg_model.num_params)
"""


def _probe(n_layer: int, offload: bool, timeout: int,
           smoke: bool = False) -> int:
    """Return param count if one step trains at this depth, else 0."""
    argv = [sys.executable, "-u", "-c",
            PROBE.format(repo=os.path.dirname(os.path.abspath(__file__))),
            str(n_layer), str(int(offload))]
    if smoke:
        argv.append("smoke")
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        # a wedged probe near the OOM boundary counts as a failed size —
        # the bisection must continue, not abort
        print(f"  probe n_layer={n_layer} offload={offload} timed out "
              f"after {timeout}s", file=sys.stderr)
        return 0
    for line in proc.stdout.splitlines():
        if line.startswith("PROBE_OK"):
            return int(line.split()[1])
    print(f"  probe n_layer={n_layer} offload={offload} failed "
          f"(rc={proc.returncode}): {proc.stderr.strip()[-300:]}",
          file=sys.stderr)
    return 0


def _search(offload: bool, lo: int, hi: int, timeout: int):
    """Largest working n_layer in [lo, hi] by bisection (lo must work)."""
    best_params = _probe(lo, offload, timeout)
    if not best_params:
        return 0, 0
    best = lo
    while lo < hi:
        mid = (lo + hi + 1) // 2
        params = _probe(mid, offload, timeout)
        if params:
            best, best_params, lo = mid, params, mid
        else:
            hi = mid - 1
    return best, best_params


def main():
    timeout = int(os.environ.get("CAPACITY_PROBE_TIMEOUT", "1200"))
    if os.environ.get("CAPACITY_SMOKE"):
        # validate the subprocess plumbing on CPU (no OOM boundary there)
        ok = _probe(2, False, timeout, smoke=True)
        ok_off = _probe(2, True, timeout, smoke=True)
        print(json.dumps({"metric": "capacity_smoke", "value": 1.0,
                          "unit": "ok",
                          "vs_baseline": float(bool(ok and ok_off))}))
        return
    # v5e: 16 GB HBM.  no-offload holds 14 B/param of fp32 state + bf16
    # copies -> O(1B); offload keeps only bf16 params+grads on chip.
    plain_layers, plain_params = _search(False, 8, 96, timeout)
    off_layers, off_params = _search(True, 32, 512, timeout)
    ratio = off_params / plain_params if plain_params else 0.0
    out = {
        "metric": "offload_peak_trainable_params_per_chip",
        "value": round(off_params / 1e9, 3),
        "unit": "B params",
        "no_offload_params_b": round(plain_params / 1e9, 3),
        "offload_layers": off_layers,
        "no_offload_layers": plain_layers,
        "capacity_ratio": round(ratio, 2),
        # reference: 10x larger models via offload (BASELINE.md:16)
        "vs_baseline": round(ratio / 10.0, 4),
    }
    print(json.dumps(out))
    with open("BENCH_capacity.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
