"""MoE routing/dispatch overhead benchmark (round-2 verdict weak #6).

Question: how much of an MoE layer's step time is routing + dispatch +
combine rather than expert FFN math, as E and tokens-per-group grow —
and does the scatter dispatch (``MoEConfig.dispatch_impl='scatter'``)
beat the one-hot einsum?

Analysis the numbers check: with capacity C = k·cf·S/E the one-hot
dispatch einsum ("gsec,gsd->egcd") does G·S·(E·C)·d ≈ G·S²·cf·k·d MACs —
*independent of E* at fixed group size, but quadratic in S; the expert
FFN does G·S·k·cf·2·d·f MACs (linear in S).  So dispatch overhead is a
function of S/(2f), not of E.  The scatter path moves O(S·d) per group
instead.  Emits one JSON line per measurement; writes BENCH_moe.json on
TPU (never clobbered by CPU smoke runs).
"""
import json
import sys
def _bench(fn, *args, iters=None):
    """Calibrated timing (bench.py helper): the round-5 first-window MoE
    artifact showed fwd+bwd 'faster' than fwd and flat ~0.04 ms rows —
    a 10-iteration window measures dispatch jitter at these kernel
    sizes, not the kernels."""
    from bench import calibrated_time
    return calibrated_time(lambda: fn(*args), iters)


def main():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from bench import guarded_devices
    on_tpu = guarded_devices()[0].platform != "cpu"
    from deepspeed_tpu.moe import MoEConfig, init_moe_params, moe_ffn

    if on_tpu:
        d, G = 1024, 4
        experts = [8, 32, 64]
        seqs = [1024, 4096, 8192]
    else:
        d, G = 64, 2
        experts = [4, 8]
        seqs = [128]
    f = 4 * d
    results = []
    for E in experts:
        for S in seqs:
            # on-device generation: no bulk H2D through the tunnel
            x = jax.random.normal(jax.random.PRNGKey(2), (G, S, d),
                                  jnp.bfloat16)
            key = jax.random.PRNGKey(0)
            rec = {"E": E, "S": S, "G": G, "d": d}
            params = None
            for impl in ("einsum", "scatter"):
                cfg = MoEConfig(n_experts=E, d_model=d, d_ff=f, top_k=2,
                                capacity_factor=1.25, dispatch_impl=impl)
                if params is None:
                    params = init_moe_params(jax.random.PRNGKey(1), cfg)

                def step(p, xin, c=cfg):
                    y, aux = moe_ffn(c, p, xin, key, train=True)
                    return jnp.sum(y.astype(jnp.float32) ** 2) + aux

                fwd = jax.jit(lambda p, xin, c=cfg: moe_ffn(
                    c, p, xin, key, train=True)[0])
                bwd = jax.jit(jax.grad(step))
                rec[f"{impl}_fwd_ms"] = round(_bench(fwd, params, x) * 1e3, 3)
                rec[f"{impl}_fwdbwd_ms"] = round(
                    _bench(bwd, params, x) * 1e3, 3)

            # FFN-equivalent floor: the same expert math with dispatch
            # replaced by a reshape — tokens pre-packed into E·C slots.
            C = cfg.capacity(S, True)
            packed = jax.random.normal(jax.random.PRNGKey(3), (E, G, C, d),
                                       jnp.bfloat16)

            def ffn_only(p, ein):
                dt = ein.dtype
                h = jnp.einsum("egcd,edf->egcf", ein, p["wi"].astype(dt))
                h = jax.nn.gelu(h + p["bi"].astype(dt)[:, None, None, :],
                                approximate=True)
                eo = jnp.einsum("egcf,efd->egcd", h, p["wo"].astype(dt))
                return jnp.sum(eo.astype(jnp.float32) ** 2)

            rec["ffn_only_fwdbwd_ms"] = round(
                _bench(jax.jit(jax.grad(ffn_only)), params, packed) * 1e3, 3)
            for impl in ("einsum", "scatter"):
                t = rec[f"{impl}_fwdbwd_ms"]
                rec[f"{impl}_overhead_frac"] = round(
                    max(0.0, t - rec["ffn_only_fwdbwd_ms"]) / t, 3)
            rec["scatter_speedup_fwdbwd"] = round(
                rec["einsum_fwdbwd_ms"] / rec["scatter_fwdbwd_ms"], 2)
            results.append(rec)
            print(json.dumps(rec), flush=True)

    if on_tpu:
        with open("BENCH_moe.json", "w") as fh:
            json.dump({"device": str(jax.devices()[0]),
                       "top_k": 2, "capacity_factor": 1.25,
                       "results": results}, fh, indent=1)


if __name__ == "__main__":
    main()
