"""ZeRO-Offload XLA-tier stall diagnosis (round-2 verdict missing #2).

The one healthy round-2 hardware window saw the 1.5B xla-tier attempt
produce no output for 9.5 min.  Candidates: (a) slow remote compile of
the 48-layer scan + host-section program, (b) a pinned_host /
``compute_on('device_host')`` stall on the axon platform.  This driver
discriminates them by running a matrix of variants lowest-risk-first,
each in a fresh subprocess with timestamped phase markers on stderr and
JAX_LOG_COMPILES=1 (so "compiling" vs "executing" is visible in the
log).  A variant that hangs natively leaves its last marker as the
verdict; later variants never run under a wedged tunnel, and nothing
here SIGTERMs a TPU client (that wedges the tunnel — BENCH_NOTES.md).

Engine knobs used (runtime/engine.py):
  DS_OFFLOAD_PINNED_HOST=0  master/moments stay in device memory
  DS_OFFLOAD_COMPUTE_ON=0   pinned_host residency, but no host compute

Usage: python diag_offload.py [--full]   (--full includes the 1.5B legs)
"""
import json
import os
import subprocess
import sys
import time

CHILD = r"""
import os, sys, time
T0 = time.perf_counter()
def mark(m):
    print(f"[diag {time.perf_counter()-T0:7.1f}s] {m}", file=sys.stderr,
          flush=True)

import numpy as np
mark("importing jax")
import jax
mark(f"devices: {[d.device_kind for d in jax.devices()]}")
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

size = os.environ["DIAG_SIZE"]
if size == "124M":
    cfg_m = GPT2Config(vocab_size=50257, n_positions=1024, d_model=768,
                       n_layer=12, n_head=12, remat="block",
                       scan_layers=True)
    micro, seq = 4, 1024
else:
    cfg_m = GPT2Config(vocab_size=50257, n_positions=1024, d_model=1600,
                       n_layer=48, n_head=25, remat="block",
                       scan_layers=True)
    micro, seq = int(os.environ.get("DIAG_MICRO", "1")), 1024
cfg = DeepSpeedConfig({
    "train_micro_batch_size_per_gpu": micro,
    "gradient_accumulation_steps": 1,
    "steps_per_print": 10 ** 9,
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "zero_optimization": {"stage": 2, "cpu_offload": True,
                          "offload_impl": "xla"},
    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
}, world_size=1)
mark(f"{size}: constructing engine")
mesh = build_mesh(pp=1, dp=1, tp=1, devices=jax.devices()[:1])
eng = DeepSpeedEngine(GPT2Model(cfg_m), cfg, mesh=mesh)
mark(f"{size}: engine ready (real_host={eng._offload_real_host}); "
     "first train_batch (trace+compile+step)")
toks = np.random.default_rng(0).integers(0, 50257, (micro, seq),
                                         dtype=np.int32)
t1 = time.perf_counter()
loss = float(eng.train_batch(toks))
mark(f"{size}: first step done in {time.perf_counter()-t1:.1f}s "
     f"loss={loss:.3f}")
t2 = time.perf_counter()
loss = float(eng.train_batch(toks))
mark(f"{size}: steady step {time.perf_counter()-t2:.2f}s loss={loss:.3f}")
print(json.dumps({"size": size, "ok": True, "loss": loss}))
"""


def run_variant(name, size, env_over, deadline):
    env = dict(os.environ)
    env.update(env_over)
    env["DIAG_SIZE"] = size
    env["JAX_LOG_COMPILES"] = "1"
    print(f"=== variant {name} (size={size}, {env_over}, "
          f"deadline={deadline}s) ===", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run([sys.executable, "-c", CHILD], env=env,
                           timeout=deadline, capture_output=True, text=True)
        rc, out = p.returncode, p.stderr[-3000:]
        child_ok = any(l.startswith("{") and '"ok": true' in l
                       for l in p.stdout.splitlines())
        verdict = "OK" if rc == 0 and child_ok else f"rc={rc}"
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired kills the child (unavoidable here); run this
        # variant LAST so a wedged tunnel cannot poison later variants.
        rc, out = -1, ((e.stderr or b"")[-3000:].decode()
                       if isinstance(e.stderr, bytes) else
                       (e.stderr or "")[-3000:])
        verdict = f"TIMEOUT after {deadline}s"
    dt = time.time() - t0
    print(out, flush=True)
    rec = {"variant": name, "size": size, "env": env_over,
           "verdict": verdict, "wall_s": round(dt, 1)}
    print(json.dumps(rec), flush=True)
    return rec


def main():
    full = "--full" in sys.argv
    results = []
    # lowest-risk first; the known-stall candidate (1.5B full xla) LAST
    results.append(run_variant(
        "124M-no-host", "124M", {"DS_OFFLOAD_PINNED_HOST": "0"}, 1200))
    results.append(run_variant(
        "124M-pinned-no-computeon", "124M",
        {"DS_OFFLOAD_COMPUTE_ON": "0"}, 1200))
    results.append(run_variant("124M-full-xla", "124M", {}, 1200))
    if full:
        results.append(run_variant(
            "1.5B-pinned-no-computeon", "1.5B",
            {"DS_OFFLOAD_COMPUTE_ON": "0"}, 2400))
        results.append(run_variant("1.5B-full-xla", "1.5B", {}, 2400))
    with open("DIAG_offload.json", "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps({"diag": "done",
                      "verdicts": {r["variant"]: r["verdict"]
                                   for r in results}}))


if __name__ == "__main__":
    main()
