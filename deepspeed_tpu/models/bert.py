"""BERT model family — the reference's headline pretraining workload.

The reference's fastest-BERT results come from the fused transformer
kernel applied to BERT-large (reference:
docs/_posts/2020-05-28-fastest-bert-training.md; the model itself lives in
the vendored test copy tests/unit/modeling.py:1578).  Here the encoder
stacks ``DeepSpeedTransformerLayer`` blocks under ``lax.scan`` with
layer-stacked parameters (one compiled block for any depth), with
embeddings, MLM + NSP pretraining heads, and Megatron-style tensor-parallel
partition specs — same structure as the GPT-2 family (models/gpt2.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.transformer import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer)
from ..ops.transformer.transformer import _dropout, _layer_norm
from ..parallel.mesh import MODEL_AXIS
from ..runtime.module import TrainModule


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    pre_layer_norm: bool = False      # classic BERT is post-LN
    remat: Optional[str] = "block"    # None | 'block'
    # memory knobs forwarded to the layer (reference config surface)
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    # 'flash' (Pallas kernel, the fused path the reference's CUDA BERT
    # always takes) | 'dense' (jnp softmax); mirrors GPT2Config.attn_impl
    attn_impl: str = "flash"
    scan_layers: bool = True          # False: unroll the stack (XLA then
                                      # optimizes across layer boundaries,
                                      # ≈25% faster on TPU like
                                      # GPT2Config.scan_layers, at
                                      # depth-linear compile cost)


BERT_BASE = BertConfig()
BERT_LARGE = BertConfig(hidden_size=1024, num_hidden_layers=24,
                        num_attention_heads=16, intermediate_size=4096)


class BertModel(TrainModule):
    """BERT encoder with MLM + NSP pretraining loss.

    Batches: dict with ``input_ids`` [B, T]; optional ``token_type_ids``,
    ``attention_mask`` (1 keep / 0 pad), ``masked_lm_labels`` [B, T] with
    -100 for unmasked positions, ``next_sentence_label`` [B].
    """

    def __init__(self, config: BertConfig):
        self.config = config
        self.layer = DeepSpeedTransformerLayer(
            DeepSpeedTransformerConfig(
                hidden_size=config.hidden_size,
                intermediate_size=config.intermediate_size,
                heads=config.num_attention_heads,
                attn_dropout_ratio=config.attention_probs_dropout_prob,
                hidden_dropout_ratio=config.hidden_dropout_prob,
                num_hidden_layers=config.num_hidden_layers,
                initializer_range=config.initializer_range,
                pre_layer_norm=config.pre_layer_norm,
                normalize_invertible=config.normalize_invertible,
                gelu_checkpoint=config.gelu_checkpoint,
                attn_dropout_checkpoint=config.attn_dropout_checkpoint,
                stochastic_mode=config.stochastic_mode,
                attn_impl=config.attn_impl))

    # ---------------- init ----------------
    def init(self, rng) -> Dict[str, Any]:
        cfg = self.config
        d, L = cfg.hidden_size, cfg.num_hidden_layers
        keys = jax.random.split(rng, 6 + L)
        std = cfg.initializer_range
        n = jax.random.normal

        layer_params = [self.layer.init(keys[6 + i]) for i in range(L)]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params)

        return {
            "word_embeddings": n(keys[0], (cfg.vocab_size, d)) * std,
            "position_embeddings": n(
                keys[1], (cfg.max_position_embeddings, d)) * std,
            "token_type_embeddings": n(
                keys[2], (cfg.type_vocab_size, d)) * std,
            "emb_ln_scale": jnp.ones((d,), jnp.float32),
            "emb_ln_bias": jnp.zeros((d,), jnp.float32),
            "layers": stacked,
            "pooler_w": n(keys[3], (d, d)) * std,
            "pooler_b": jnp.zeros((d,), jnp.float32),
            # MLM head: transform + LN + decoder bias (decoder weights tied
            # to word embeddings)
            "mlm_transform_w": n(keys[4], (d, d)) * std,
            "mlm_transform_b": jnp.zeros((d,), jnp.float32),
            "mlm_ln_scale": jnp.ones((d,), jnp.float32),
            "mlm_ln_bias": jnp.zeros((d,), jnp.float32),
            "mlm_bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
            "nsp_w": n(keys[5], (d, 2)) * std,
            "nsp_b": jnp.zeros((2,), jnp.float32),
        }

    # ---------------- TP declaration ----------------
    def param_partition_specs(self, params) -> Dict[str, Any]:
        m = MODEL_AXIS
        return {
            "word_embeddings": P(m, None),
            "position_embeddings": P(),
            "token_type_embeddings": P(),
            "emb_ln_scale": P(), "emb_ln_bias": P(),
            "layers": {
                "attn_qkvw": P(None, None, None, m),
                "attn_qkvb": P(None, None, m),
                "attn_ow": P(None, m, None), "attn_ob": P(),
                "attn_nw": P(), "attn_nb": P(),
                "inter_w": P(None, None, m), "inter_b": P(None, m),
                "output_w": P(None, m, None), "output_b": P(),
                "norm_w": P(), "norm_b": P(),
            },
            "pooler_w": P(), "pooler_b": P(),
            "mlm_transform_w": P(), "mlm_transform_b": P(),
            "mlm_ln_scale": P(), "mlm_ln_bias": P(),
            "mlm_bias": P(m),
            "nsp_w": P(), "nsp_b": P(),
        }

    # ---------------- forward ----------------
    def encode(self, params, input_ids, token_type_ids=None,
               attention_mask=None, rng=None, train: bool = True,
               pld_theta=None):
        """→ sequence output [B, T, D].

        ``pld_theta``: progressive-layer-drop keep-probability scalar (the
        engine injects it per step when ``progressive_layer_drop`` is
        enabled, runtime/engine.py; schedule in
        runtime/progressive_layer_drop.py — reference engine.py:189-190,
        787-788).  Layer i keeps with p_i = 1 - (i/L)(1-θ) — deeper
        layers drop more, per the PLD paper's depth schedule; dropped
        layers pass the residual through unchanged.  Eval ignores it."""
        cfg = self.config
        B, T = input_ids.shape
        if T > cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {T} exceeds max_position_embeddings="
                f"{cfg.max_position_embeddings}")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        tt = (token_type_ids if token_type_ids is not None
              else jnp.zeros_like(input_ids))
        x = (params["word_embeddings"][input_ids]
             + params["position_embeddings"][:T][None]
             + params["token_type_embeddings"][tt])
        x = _layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"])
        x = _dropout(x, cfg.hidden_dropout_prob if train else 0.0,
                     jax.random.fold_in(rng, 997))

        # HF-style additive mask [B, 1, 1, T]
        add_mask = None
        if attention_mask is not None:
            add_mask = (1.0 - attention_mask.astype(jnp.float32)
                        )[:, None, None, :] * -10000.0

        layer = self.layer
        L = cfg.num_hidden_layers

        def body(carry, xs):
            h = carry
            lp, i = xs
            lrng = jax.random.fold_in(rng, i)
            if pld_theta is not None and train:
                # lax.cond (not where): a dropped layer must SKIP its
                # FLOPs at runtime — the throughput gain is the point of
                # PLD, not just the regularization
                p_keep = 1.0 - (i.astype(jnp.float32) / L) * (
                    1.0 - pld_theta.astype(jnp.float32))
                keep = jax.random.bernoulli(
                    jax.random.fold_in(lrng, 131), p_keep)
                y = jax.lax.cond(
                    keep,
                    lambda hh: layer(lp, hh, add_mask, lrng, train),
                    lambda hh: hh, h)
            else:
                y = layer(lp, h, add_mask, lrng, train)
            return y, None

        body_fn = jax.checkpoint(body) if cfg.remat == "block" else body
        if cfg.scan_layers:
            x, _ = jax.lax.scan(
                body_fn, x, (params["layers"], jnp.arange(L)))
        else:
            for i in range(L):
                lp = jax.tree.map(lambda a: a[i], params["layers"])
                x, _ = body_fn(x, (lp, jnp.asarray(i, jnp.int32)))
        return x

    def apply(self, params, batch, rng=None, train: bool = True):
        """→ (mlm_logits [B, T, V], nsp_logits [B, 2])."""
        pld = batch.get("pld_theta")
        seq = self.encode(params, batch["input_ids"],
                          batch.get("token_type_ids"),
                          batch.get("attention_mask"), rng, train,
                          pld_theta=(pld.reshape(-1)[0]
                                     if pld is not None else None))
        # MLM head
        h = seq @ params["mlm_transform_w"].astype(seq.dtype) \
            + params["mlm_transform_b"].astype(seq.dtype)
        h = jax.nn.gelu(h, approximate=False)
        h = _layer_norm(h, params["mlm_ln_scale"], params["mlm_ln_bias"])
        mlm_logits = h @ params["word_embeddings"].astype(h.dtype).T \
            + params["mlm_bias"].astype(h.dtype)
        # NSP head on pooled [CLS]
        pooled = jnp.tanh(
            seq[:, 0] @ params["pooler_w"].astype(seq.dtype)
            + params["pooler_b"].astype(seq.dtype))
        nsp_logits = pooled @ params["nsp_w"].astype(seq.dtype) \
            + params["nsp_b"].astype(seq.dtype)
        return mlm_logits, nsp_logits

    def loss_fn(self, params, batch, rng, train: bool = True):
        mlm_logits, nsp_logits = self.apply(params, batch, rng, train)
        mlm_logits = mlm_logits.astype(jnp.float32)
        loss = jnp.asarray(0.0, jnp.float32)
        labels = batch.get("masked_lm_labels")
        if labels is not None:
            logp = jax.nn.log_softmax(mlm_logits, axis=-1)
            safe = jnp.maximum(labels, 0)
            nll = -jnp.take_along_axis(logp, safe[..., None], -1)[..., 0]
            mask = (labels >= 0).astype(jnp.float32)
            loss = loss + jnp.sum(nll * mask) / jnp.maximum(
                jnp.sum(mask), 1.0)
        nsl = batch.get("next_sentence_label")
        if nsl is not None:
            logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), -1)
            loss = loss - jnp.mean(
                jnp.take_along_axis(logp, nsl[:, None], -1))
        return loss
