from .gpt2 import (
    GPT2Config, GPT2Model,
    GPT2_SMALL, GPT2_MEDIUM, GPT2_LARGE, GPT2_XL,
)
from .gpt2_moe import GPT2MoEConfig, GPT2MoEModel
from .bert import BertConfig, BertModel, BERT_BASE, BERT_LARGE
