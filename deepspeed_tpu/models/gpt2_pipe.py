"""GPT-2 as a pipeline-parallel module.

The pipeline flavor of the flagship model: per-layer LayerSpecs instead of
the scan-over-layers stack, so stages can own layer ranges (the analogue of
the reference's GPT2 PipelineModule usage; reference pattern:
deepspeed/runtime/pipe/module.py:85 + DeepSpeedExamples Megatron pipe
models).  The embedding is a TiedLayerSpec and the LM head reads the same
``wte`` through the 3-ary loss head — gradient tying falls out of AD
(replacing the tied-weight allreduce, reference pipe/module.py:405-474).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MODEL_AXIS
from ..pipe.module import LayerSpec, TiedLayerSpec, PipelineModule
from .gpt2 import GPT2Config, _dropout, _layer_norm, gpt2_block_forward


class GPT2EmbeddingPipe:
    def __init__(self, cfg: GPT2Config):
        self.cfg = cfg

    def init(self, rng):
        cfg = self.cfg
        k1, k2 = jax.random.split(rng)
        return {
            "wte": jax.random.normal(
                k1, (cfg.vocab_size, cfg.d_model), jnp.float32) * 0.02,
            "wpe": jax.random.normal(
                k2, (cfg.n_positions, cfg.d_model), jnp.float32) * 0.02,
        }

    def param_partition_specs(self):
        return {"wte": P(MODEL_AXIS, None), "wpe": P()}

    def apply(self, params, tokens, rng, train: bool = True):
        cfg = self.cfg
        T = tokens.shape[1]
        if T > cfg.n_positions:
            raise ValueError(
                f"sequence length {T} exceeds n_positions={cfg.n_positions}")
        # one-hot contraction, not wte[tokens]: the gather's VJP is a
        # scatter-add into the (possibly vocab-sharded) table, which the
        # SPMD partitioner cannot handle inside the pipeline's
        # manual(pipe)/auto(model) nesting — and the one-hot dot runs on
        # the MXU where the scatter serializes.  ~V/d extra FLOPs on a
        # layer that is <<1% of the model's compute.
        wte = params["wte"]
        onehot = jax.nn.one_hot(tokens, wte.shape[0], dtype=wte.dtype)
        x = onehot @ wte + params["wpe"][:T][None]
        return _dropout(x, cfg.embd_dropout if train else 0.0, rng)


class GPT2BlockPipe:
    """One transformer block (same math as GPT2Model._block, unstacked)."""

    def __init__(self, cfg: GPT2Config, layer_idx: int):
        self.cfg = cfg
        self.layer_idx = layer_idx

    def init(self, rng):
        import math
        cfg = self.cfg
        d = cfg.d_model
        ks = jax.random.split(rng, 4)
        std = 0.02
        resid_std = std / math.sqrt(2.0 * cfg.n_layer)
        return {
            "ln1_scale": jnp.ones((d,), jnp.float32),
            "ln1_bias": jnp.zeros((d,), jnp.float32),
            "qkv_w": jax.random.normal(
                ks[0], (d, 3, d), jnp.float32) * std,
            "qkv_b": jnp.zeros((3, d), jnp.float32),
            "out_w": jax.random.normal(ks[1], (d, d), jnp.float32) * resid_std,
            "out_b": jnp.zeros((d,), jnp.float32),
            "ln2_scale": jnp.ones((d,), jnp.float32),
            "ln2_bias": jnp.zeros((d,), jnp.float32),
            "fc_w": jax.random.normal(ks[2], (d, 4 * d), jnp.float32) * std,
            "fc_b": jnp.zeros((4 * d,), jnp.float32),
            "proj_w": jax.random.normal(
                ks[3], (4 * d, d), jnp.float32) * resid_std,
            "proj_b": jnp.zeros((d,), jnp.float32),
        }

    def param_partition_specs(self):
        """Megatron column/row layout (same as GPT2Model's stacked specs,
        minus the layer axis)."""
        m = MODEL_AXIS
        return {
            "ln1_scale": P(), "ln1_bias": P(),
            "qkv_w": P(None, None, m), "qkv_b": P(None, m),
            "out_w": P(m, None), "out_b": P(),
            "ln2_scale": P(), "ln2_bias": P(),
            "fc_w": P(None, m), "fc_b": P(m),
            "proj_w": P(m, None), "proj_b": P(),
        }

    def apply(self, bp, x, rng, train: bool = True):
        return gpt2_block_forward(self.cfg, bp, x, rng, train)


class GPT2FinalNormPipe:
    def __init__(self, cfg: GPT2Config):
        self.cfg = cfg

    def init(self, rng):
        d = self.cfg.d_model
        return {"scale": jnp.ones((d,), jnp.float32),
                "bias": jnp.zeros((d,), jnp.float32)}

    def apply(self, params, x, rng, train: bool = True):
        return _layer_norm(x, params["scale"], params["bias"])


def gpt2_loss_head(params, hidden, labels):
    """Tied LM head + next-token CE; 3-ary so it can read the tied wte
    (labels are the raw token ids; hidden covers positions [0, T-1))."""
    wte = params["tied"]["embed"]["wte"]
    logits = hidden @ wte.astype(hidden.dtype).T
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    # one-hot contraction, not take_along_axis: its VJP is a dense
    # multiply (XLA fuses the one-hot into a masked reduce), whereas the
    # gather's VJP is a scatter-add — which the SPMD partitioner cannot
    # handle inside the pipeline's manual(pipe)/auto(model) nesting (and
    # scatters onto a vocab-sharded logit cotangent are slow on TPU
    # regardless).
    onehot = jax.nn.one_hot(labels, logp.shape[-1], dtype=logp.dtype)
    nll = -jnp.sum(logp * onehot, axis=-1)
    return jnp.mean(nll)


def build_gpt2_pipe(cfg: GPT2Config, num_stages: int,
                    partition_method: str = "type:GPT2BlockPipe",
                    activation_checkpoint_interval: int = 0
                    ) -> PipelineModule:
    layers = [TiedLayerSpec("embed", GPT2EmbeddingPipe, cfg)]
    layers += [LayerSpec(GPT2BlockPipe, cfg, i) for i in range(cfg.n_layer)]
    layers += [LayerSpec(GPT2FinalNormPipe, cfg)]
    return PipelineModule(
        layers, num_stages=num_stages, loss_fn=gpt2_loss_head,
        partition_method=partition_method,
        activation_checkpoint_interval=activation_checkpoint_interval)


def split_gpt2_batch(tokens):
    """tokens [B, T+1] → (inputs [B, T], labels [B, T]) for the pipeline
    (inputs enter stage 0; labels are consumed by the last-stage loss)."""
    return tokens[:, :-1], tokens[:, 1:]
