"""BERT as a pipeline-parallel module.

Per-layer LayerSpecs over the fused ``DeepSpeedTransformerLayer`` block
(the reference's BERT + PipelineModule combination; pattern:
deepspeed/runtime/pipe/module.py:85).  The word-embedding table is a
TiedLayerSpec read again by the MLM head through the 3-ary loss — gradient
tying falls out of AD (replacing the tied-weight allreduce, reference
pipe/module.py:405-474).

Batches: ``(input_ids [B, T], masked_lm_labels [B, T])`` with -100 at
unmasked label positions (``split_bert_batch`` builds the pair from a
dict batch).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.transformer import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer)
from ..parallel.mesh import MODEL_AXIS
from ..pipe.module import LayerSpec, TiedLayerSpec, PipelineModule
from .bert import BertConfig, _dropout, _layer_norm


def _layer_cfg(cfg: BertConfig) -> DeepSpeedTransformerConfig:
    return DeepSpeedTransformerConfig(
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        heads=cfg.num_attention_heads,
        attn_dropout_ratio=cfg.attention_probs_dropout_prob,
        hidden_dropout_ratio=cfg.hidden_dropout_prob,
        num_hidden_layers=cfg.num_hidden_layers,
        initializer_range=cfg.initializer_range,
        pre_layer_norm=cfg.pre_layer_norm,
        normalize_invertible=cfg.normalize_invertible,
        gelu_checkpoint=cfg.gelu_checkpoint,
        attn_dropout_checkpoint=cfg.attn_dropout_checkpoint,
        stochastic_mode=cfg.stochastic_mode)


class BertEmbeddingPipe:
    def __init__(self, cfg: BertConfig):
        self.cfg = cfg

    def init(self, rng):
        cfg = self.cfg
        d = cfg.hidden_size
        k = jax.random.split(rng, 3)
        n = jax.random.normal
        std = cfg.initializer_range
        return {
            "wte": n(k[0], (cfg.vocab_size, d), jnp.float32) * std,
            "wpe": n(k[1], (cfg.max_position_embeddings, d),
                     jnp.float32) * std,
            "tte": n(k[2], (cfg.type_vocab_size, d), jnp.float32) * std,
            "ln_scale": jnp.ones((d,), jnp.float32),
            "ln_bias": jnp.zeros((d,), jnp.float32),
        }

    def param_partition_specs(self):
        return {"wte": P(MODEL_AXIS, None), "wpe": P(), "tte": P(),
                "ln_scale": P(), "ln_bias": P()}

    def apply(self, params, input_ids, rng, train: bool = True):
        T = input_ids.shape[1]
        if T > self.cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {T} exceeds max_position_embeddings="
                f"{self.cfg.max_position_embeddings}")
        # pipe batches carry no token_type_ids: segment 0 for every token,
        # which is tte row 0 broadcast (no per-token gather needed).
        # one-hot contraction for the word lookup — scatter-free VJP under
        # the pipeline's manual/auto nesting (see gpt2_pipe equivalent)
        wte = params["wte"]
        onehot = jax.nn.one_hot(input_ids, wte.shape[0], dtype=wte.dtype)
        x = (onehot @ wte + params["wpe"][:T][None]
             + params["tte"][0][None, None])
        x = _layer_norm(x, params["ln_scale"], params["ln_bias"])
        return _dropout(x, self.cfg.hidden_dropout_prob if train else 0.0,
                        rng)


class BertLayerPipe:
    """One fused encoder block (unstacked DeepSpeedTransformerLayer)."""

    def __init__(self, cfg: BertConfig, layer_idx: int):
        self.cfg = cfg
        self.layer_idx = layer_idx
        self.layer = DeepSpeedTransformerLayer(_layer_cfg(cfg))

    def init(self, rng):
        return self.layer.init(rng)

    def param_partition_specs(self):
        m = MODEL_AXIS
        return {
            "attn_qkvw": P(None, None, m), "attn_qkvb": P(None, m),
            "attn_ow": P(m, None), "attn_ob": P(),
            "attn_nw": P(), "attn_nb": P(),
            "inter_w": P(None, m), "inter_b": P(m),
            "output_w": P(m, None), "output_b": P(),
            "norm_w": P(), "norm_b": P(),
        }

    def apply(self, bp, x, rng, train: bool = True):
        return self.layer(bp, x, attention_mask=None, rng=rng, train=train)


class BertMLMTransformPipe:
    """MLM head transform + LN (the decoder matmul happens in the tied
    loss head so it can read the embedding table)."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg

    def init(self, rng):
        cfg = self.cfg
        d = cfg.hidden_size
        return {
            "w": jax.random.normal(rng, (d, d), jnp.float32)
            * cfg.initializer_range,
            "b": jnp.zeros((d,), jnp.float32),
            "ln_scale": jnp.ones((d,), jnp.float32),
            "ln_bias": jnp.zeros((d,), jnp.float32),
        }

    def apply(self, params, x, rng, train: bool = True):
        h = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
        h = jax.nn.gelu(h, approximate=False)
        return _layer_norm(h, params["ln_scale"], params["ln_bias"])


def bert_mlm_loss_head(params, hidden, labels):
    """Tied MLM decoder + masked cross-entropy (labels -100 = unmasked;
    decoder weights are the embedding table — the per-vocab decoder bias
    the non-pipe BertModel carries is omitted here, GPT-2 style)."""
    wte = params["tied"]["embed"]["wte"]
    logits = (hidden @ wte.astype(hidden.dtype).T).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    mask = labels != -100
    safe = jnp.where(mask, labels, 0)
    # one-hot contraction (scatter-free VJP; see gpt2_pipe.gpt2_loss_head)
    onehot = jax.nn.one_hot(safe, logp.shape[-1], dtype=logp.dtype)
    nll = -jnp.sum(logp * onehot, axis=-1)
    denom = jnp.maximum(mask.sum(), 1)
    return jnp.sum(jnp.where(mask, nll, 0.0)) / denom


def build_bert_pipe(cfg: BertConfig, num_stages: int,
                    partition_method: str = "type:BertLayerPipe",
                    activation_checkpoint_interval: int = 0
                    ) -> PipelineModule:
    layers = [TiedLayerSpec("embed", BertEmbeddingPipe, cfg)]
    layers += [LayerSpec(BertLayerPipe, cfg, i)
               for i in range(cfg.num_hidden_layers)]
    layers += [LayerSpec(BertMLMTransformPipe, cfg)]
    return PipelineModule(
        layers, num_stages=num_stages, loss_fn=bert_mlm_loss_head,
        partition_method=partition_method,
        activation_checkpoint_interval=activation_checkpoint_interval)


def split_bert_batch(batch):
    """dict batch → (input_ids, masked_lm_labels) for the pipeline."""
    return batch["input_ids"], batch["masked_lm_labels"]
