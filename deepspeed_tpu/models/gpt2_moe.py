"""GPT-2 Mixture-of-Experts flavor — expert parallelism over the mesh.

Expert parallelism is absent from the reference snapshot (SURVEY.md §2.4
lists EP/MoE as not present in v0.3.2); this model fills that modern slot
the way DeepSpeed-MoE later does — alternating dense/MoE transformer
blocks, top-1/2 token routing with capacity, experts sharded over the
data-parallel group (ep ⊆ dp) — but as placement on one compiled program
rather than explicit expert process groups: the expert dim of the stacked
MoE weights carries ``P('data', ...)`` (see moe/layer.py) and the
dispatch/combine all_to_alls are inserted by GSPMD.

The per-layer loop is heterogeneous (dense and MoE blocks alternate), so
blocks run unrolled by default; ``scan_groups=True`` instead scans over
homogeneous groups of ``moe_layer_freq`` blocks (freq-1 dense + 1 MoE) —
one compiled group body, compile time O(1) in depth, bit-identical math
and RNG streams to the unrolled path.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..moe.layer import MoEConfig, init_moe_params, moe_ffn, moe_param_specs
from ..parallel.mesh import MODEL_AXIS
from ..runtime.module import TrainModule
from .gpt2 import (GPT2Config, _dropout, _layer_norm, gpt2_attn_sublayer,
                   gpt2_ffn)


@dataclasses.dataclass(frozen=True)
class GPT2MoEConfig(GPT2Config):
    n_experts: int = 8
    moe_top_k: int = 1
    moe_layer_freq: int = 2           # every freq-th block is MoE
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    aux_loss_weight: float = 1e-2
    router_z_loss_weight: float = 0.0
    router_jitter: float = 0.0
    moe_dispatch_impl: str = "einsum"  # see MoEConfig.dispatch_impl
    # the dense/MoE block alternation makes the per-LAYER loop
    # heterogeneous, so GPT2Config's scan_layers is not supported; the
    # depth-scalable equivalent is scan_groups: lax.scan over homogeneous
    # groups of moe_layer_freq blocks (freq-1 dense + 1 MoE) — one
    # compiled group body, compile time O(1) in depth
    scan_layers: bool = False
    scan_groups: bool = False
    stream_scan: bool = False        # fetch ONE group's params per scan
                                     # tick (requires scan_groups) — pair
                                     # with zero_optimization.
                                     # param_streaming so device param
                                     # bytes ~ one group

    def __post_init__(self):
        if self.scan_layers:
            raise ValueError(
                "GPT2MoEModel always unrolls its heterogeneous layer "
                "loop; scan_layers=True is not supported")
        if self.stream_scan and not self.scan_groups:
            raise ValueError(
                "stream_scan requires scan_groups=True (the streaming "
                "fetch rides the group scan)")
        if self.moe_layer_freq < 1:
            raise ValueError(
                f"moe_layer_freq must be >= 1, got {self.moe_layer_freq}")
        if not any(self.is_moe_layer(i) for i in range(self.n_layer)):
            raise ValueError(
                f"GPT2MoEConfig with n_layer={self.n_layer}, "
                f"moe_layer_freq={self.moe_layer_freq} yields zero MoE "
                "layers — use GPT2Config/GPT2Model for a dense model")
        if self.scan_groups:
            if self.n_layer % self.moe_layer_freq != 0:
                raise ValueError(
                    f"scan_groups needs n_layer ({self.n_layer}) divisible "
                    f"by moe_layer_freq ({self.moe_layer_freq}) — the scan "
                    "body is one homogeneous group")
            # the scan body hardcodes MoE-last-in-group; bind that to
            # is_moe_layer so an overridden placement cannot silently
            # diverge from the unrolled path
            freq = self.moe_layer_freq
            expect = [g * freq + freq - 1
                      for g in range(self.n_layer // freq)]
            if self.moe_layers != expect:
                raise ValueError(
                    f"scan_groups assumes MoE on the last block of each "
                    f"group (layers {expect}), but is_moe_layer yields "
                    f"{self.moe_layers} — use the unrolled path")
        self.moe_cfg()  # validate the routing knobs at config time

    def moe_cfg(self) -> MoEConfig:
        return MoEConfig(
            n_experts=self.n_experts, d_model=self.d_model,
            d_ff=4 * self.d_model, top_k=self.moe_top_k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            aux_loss_weight=self.aux_loss_weight,
            z_loss_weight=self.router_z_loss_weight,
            router_jitter=self.router_jitter,
            dispatch_impl=self.moe_dispatch_impl)

    def is_moe_layer(self, i: int) -> bool:
        # MoE on the last block of each freq-group (layer 1, 3, ... for
        # freq=2) — DeepSpeed-MoE's alternating placement.
        return (i % self.moe_layer_freq) == self.moe_layer_freq - 1

    @property
    def moe_layers(self):
        return [i for i in range(self.n_layer) if self.is_moe_layer(i)]

    @property
    def num_params(self) -> int:
        """Accurate MoE count (overrides the dense formula): each MoE
        block swaps the dense FFN for E experts plus the router."""
        d, L, E = self.d_model, self.n_layer, self.n_experts
        n_moe = len(self.moe_layers)
        attn_per_block = (4 * d            # ln1/ln2 scales+biases
                          + d * 3 * d + 3 * d
                          + d * d + d)
        dense_ffn = d * 4 * d + 4 * d + 4 * d * d + d
        moe_ffn_params = d * E + E * (d * 4 * d + 4 * d
                                      + 4 * d * d + d)
        return (self.vocab_size * d + self.n_positions * d + 2 * d
                + L * attn_per_block
                + (L - n_moe) * dense_ffn + n_moe * moe_ffn_params)


class GPT2MoEModel(TrainModule):
    """Causal LM where alternate blocks use a top-k routed expert FFN."""

    def __init__(self, config: GPT2MoEConfig):
        self.config = config

    # ---------------- init ----------------
    def init(self, rng) -> Dict[str, Any]:
        cfg = self.config
        d, L = cfg.d_model, cfg.n_layer
        keys = jax.random.split(rng, 8)
        std = 0.02
        resid_std = std / jnp.sqrt(2.0 * L)

        def norm(key, shape, s=std):
            return jax.random.normal(key, shape, jnp.float32) * s

        # attention sublayer params for ALL blocks, stacked [L, ...]
        attn = {
            "ln1_scale": jnp.ones((L, d), jnp.float32),
            "ln1_bias": jnp.zeros((L, d), jnp.float32),
            "qkv_w": norm(keys[2], (L, d, 3, d)),
            "qkv_b": jnp.zeros((L, 3, d), jnp.float32),
            "out_w": norm(keys[3], (L, d, d), resid_std),
            "out_b": jnp.zeros((L, d), jnp.float32),
            "ln2_scale": jnp.ones((L, d), jnp.float32),
            "ln2_bias": jnp.zeros((L, d), jnp.float32),
        }
        # dense FFN params for the non-MoE blocks, stacked [L_dense, ...]
        Ld = L - len(cfg.moe_layers)
        dense = {
            "fc_w": norm(keys[4], (Ld, d, 4 * d)),
            "fc_b": jnp.zeros((Ld, 4 * d), jnp.float32),
            "proj_w": norm(keys[5], (Ld, 4 * d, d), resid_std),
            "proj_b": jnp.zeros((Ld, d), jnp.float32),
        }
        # MoE params stacked over the MoE layers [L_moe, E, ...]
        # (__post_init__ guarantees at least one MoE layer)
        mcfg = cfg.moe_cfg()
        mkeys = jax.random.split(keys[6], len(cfg.moe_layers))
        moe_leaves = [init_moe_params(k, mcfg, std=std, out_std=resid_std)
                      for k in mkeys]
        moe = jax.tree.map(lambda *ls: jnp.stack(ls), *moe_leaves)
        return {
            "wte": norm(keys[0], (cfg.vocab_size, d)),
            "wpe": norm(keys[1], (cfg.n_positions, d)),
            "ln_f_scale": jnp.ones((d,), jnp.float32),
            "ln_f_bias": jnp.zeros((d,), jnp.float32),
            "attn": attn,
            "dense_ffn": dense,
            "moe": moe,
        }

    # ---------------- EP/TP declaration ----------------
    def param_partition_specs(self, params) -> Dict[str, Any]:
        m = MODEL_AXIS
        return {
            "wte": P(m, None),
            "wpe": P(),
            "ln_f_scale": P(),
            "ln_f_bias": P(),
            "attn": {
                "ln1_scale": P(), "ln1_bias": P(),
                "qkv_w": P(None, None, None, m),
                "qkv_b": P(None, None, m),
                "out_w": P(None, m, None),
                "out_b": P(),
                "ln2_scale": P(), "ln2_bias": P(),
            },
            "dense_ffn": {
                "fc_w": P(None, None, m),
                "fc_b": P(None, m),
                "proj_w": P(None, m, None),
                "proj_b": P(),
            },
            "moe": moe_param_specs(tp_axis=m, stacked=True),
        }

    # ---------------- forward ----------------
    def apply(self, params, tokens: jnp.ndarray, rng, train: bool = True):
        """tokens [B, T] → (logits [B, T, vocab], total weighted aux)."""
        cfg = self.config
        B, T = tokens.shape
        if T > cfg.n_positions:
            raise ValueError(
                f"sequence length {T} exceeds n_positions={cfg.n_positions}")
        x = params["wte"][tokens] + params["wpe"][:T][None]
        x = _dropout(x, cfg.embd_dropout if train else 0.0,
                     jax.random.fold_in(rng, 997))

        mcfg = cfg.moe_cfg()
        drop = cfg.dropout if train else 0.0

        def dense_block(x, ap, dp, lrng):
            r_attn, r_ffn = jax.random.split(lrng)
            x = gpt2_attn_sublayer(cfg, ap, x, r_attn, train)
            h = _layer_norm(x, ap["ln2_scale"], ap["ln2_bias"])
            y = gpt2_ffn(dp, h)
            return x + _dropout(y, drop, jax.random.fold_in(r_ffn, 1))

        def moe_block(x, ap, mp, lrng):
            r_attn, r_ffn = jax.random.split(lrng)
            x = gpt2_attn_sublayer(cfg, ap, x, r_attn, train)
            h = _layer_norm(x, ap["ln2_scale"], ap["ln2_bias"])
            y, aux = moe_ffn(mcfg, mp, h, r_ffn, train)
            return x + _dropout(y, drop, jax.random.fold_in(r_ffn, 1)), aux

        aux0 = jnp.zeros((), jnp.float32)
        if cfg.scan_groups and cfg.stream_scan:
            # Param-streaming form of the group scan: the stacks stay
            # scan CONSTANTS (host-resident under zero_optimization.
            # param_streaming) and the body fetches group g's rows with
            # an explicit transfer to device memory — inside the remat'd
            # body, so the backward re-fetches instead of keeping the
            # stacks alive (see GPT2Model's streaming scan for the
            # dense-model form).
            from .gpt2 import stream_fetch
            freq = cfg.moe_layer_freq
            G = cfg.n_layer // freq
            specs = self.param_partition_specs(params)

            def group_body(carry, g):
                x, aux = carry
                ag = stream_fetch(params["attn"], specs["attn"],
                                  g * freq, rows=freq)
                dg = stream_fetch(params["dense_ffn"], specs["dense_ffn"],
                                  g * (freq - 1), rows=freq - 1)
                mg = stream_fetch(params["moe"], specs["moe"], g)
                for j in range(freq - 1):
                    apj = jax.tree.map(lambda a, j=j: a[j], ag)
                    dpj = jax.tree.map(lambda a, j=j: a[j], dg)
                    x = dense_block(
                        x, apj, dpj, jax.random.fold_in(rng, g * freq + j))
                apm = jax.tree.map(lambda a: a[freq - 1], ag)
                x, a = moe_block(
                    x, apm, mg,
                    jax.random.fold_in(rng, g * freq + freq - 1))
                return (x, aux + a), None

            if cfg.remat == "block":
                group_body = jax.checkpoint(group_body)
            (x, aux_total), _ = jax.lax.scan(
                group_body, (x, aux0), jnp.arange(G))
        elif cfg.scan_groups:
            # One compiled group body regardless of depth: the layer loop
            # scans over groups of ``freq`` blocks (freq-1 dense + 1 MoE,
            # the fixed pattern is_moe_layer defines), with the stored
            # [L, ...] / [L_dense, ...] stacks reshaped to per-group
            # leading dims.  Same math and RNG streams as the unrolled
            # path (layer i = g*freq + j keys identically); remat='block'
            # checkpoints the whole group.
            freq = cfg.moe_layer_freq
            G = cfg.n_layer // freq

            def regroup(tree_, sub):
                return jax.tree.map(
                    lambda a: a.reshape((G, sub) + a.shape[1:]), tree_)

            attn_g = regroup(params["attn"], freq)
            dense_g = regroup(params["dense_ffn"], freq - 1)

            def group_body(carry, xs):
                x, aux = carry
                ag, dg, mg, g = xs
                for j in range(freq - 1):
                    apj = jax.tree.map(lambda a, j=j: a[j], ag)
                    dpj = jax.tree.map(lambda a, j=j: a[j], dg)
                    x = dense_block(
                        x, apj, dpj, jax.random.fold_in(rng, g * freq + j))
                apm = jax.tree.map(lambda a: a[freq - 1], ag)
                x, a = moe_block(
                    x, apm, mg,
                    jax.random.fold_in(rng, g * freq + freq - 1))
                return (x, aux + a), None

            if cfg.remat == "block":
                group_body = jax.checkpoint(group_body)
            (x, aux_total), _ = jax.lax.scan(
                group_body, (x, aux0),
                (attn_g, dense_g, params["moe"], jnp.arange(G)))
        else:
            if cfg.remat == "block":
                dense_block = jax.checkpoint(dense_block)
                moe_block = jax.checkpoint(moe_block)
            aux_total = aux0
            d_idx = m_idx = 0
            for i in range(cfg.n_layer):
                lrng = jax.random.fold_in(rng, i)
                ap = jax.tree.map(lambda a, i=i: a[i], params["attn"])
                if cfg.is_moe_layer(i):
                    mp = jax.tree.map(
                        lambda a, j=m_idx: a[j], params["moe"])
                    x, aux = moe_block(x, ap, mp, lrng)
                    aux_total = aux_total + aux
                    m_idx += 1
                else:
                    dp = jax.tree.map(
                        lambda a, j=d_idx: a[j], params["dense_ffn"])
                    x = dense_block(x, ap, dp, lrng)
                    d_idx += 1

        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        logits = x @ params["wte"].astype(x.dtype).T
        return logits, aux_total

    def streaming_param_spec(self, params):
        """The stacked attn/dense-FFN/MoE leaves stream (one group per
        scan tick); embeddings/final LN stay device-resident.  Requires
        the group-scan form with explicit per-group fetch
        (``stream_scan``)."""
        if not (self.config.scan_groups and self.config.stream_scan):
            return None
        stacked = {"attn", "dense_ffn", "moe"}
        return {
            k: jax.tree.map(lambda _: k in stacked, v)
            for k, v in params.items()
        }

    def loss_fn(self, params, batch, rng, train: bool = True):
        tokens = batch["input_ids"] if isinstance(batch, dict) else batch
        logits, aux = self.apply(params, tokens[:, :-1], rng, train)
        targets = tokens[:, 1:]
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll) + aux
