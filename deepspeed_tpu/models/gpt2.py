"""GPT-2 model family — the flagship decoder LM, TPU-first.

Fills the role of the Megatron-GPT2 integration models the reference trains
in its perf suite (reference: tests/model/Megatron_GPT2/run_perf_test.py:18-60
pins 1.5B/4B/8B/20B configs; DeepSpeedExamples provides the model).  Design
is idiomatic JAX rather than a torch port:

  - parameters for all layers are STACKED on a leading layer axis and the
    blocks run under ``lax.scan`` — one compiled block regardless of depth
    (fast compile, XLA pipelines the layer loop);
  - tensor parallelism is declared, not coded: ``param_partition_specs``
    marks qkv/mlp weights on the ``model`` mesh axis (Megatron column/row
    split — column-parallel matmuls shard the output feature dim, row-
    parallel shard the input dim so XLA inserts exactly one psum per block,
    the same comm pattern Megatron hand-codes);
  - remat: ``jax.checkpoint`` around each block body when
    ``remat='block'`` (the activation-checkpointing feature slot,
    reference deepspeed/runtime/activation_checkpointing/checkpointing.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import causal_attention
from ..parallel.mesh import MODEL_AXIS
from ..runtime.module import TrainModule


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    d_model: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    embd_dropout: float = 0.0
    remat: Optional[str] = "block"   # None | 'block'
    attn_impl: str = "flash"         # 'flash' (Pallas) | 'dense' |
                                     # 'ring' | 'ulysses' (seq-parallel)
    scan_layers: bool = True         # False: unroll (≈25% faster on TPU —
                                     # XLA optimizes across layer bounds —
                                     # at the cost of depth-linear compile)
    stream_scan: bool = False        # fetch ONE layer's params per scan
                                     # tick with an explicit memory-space
                                     # transfer — pair with the engine's
                                     # zero_optimization.param_streaming
                                     # (host-resident block params) so
                                     # device param bytes ~ one layer

    @property
    def d_head(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    @property
    def num_params(self) -> int:
        d, L, V, Tmax = self.d_model, self.n_layer, self.vocab_size, self.n_positions
        per_block = (4 * d  # ln scales/biases
                     + d * 3 * d + 3 * d      # qkv
                     + d * d + d              # attn out
                     + d * 4 * d + 4 * d      # fc
                     + 4 * d * d + d)         # proj
        return V * d + Tmax * d + L * per_block + 2 * d


# canned sizes (GPT-2 paper / Megatron perf ladder)
GPT2_SMALL = GPT2Config(d_model=768, n_layer=12, n_head=12)          # 124M
GPT2_MEDIUM = GPT2Config(d_model=1024, n_layer=24, n_head=16)        # 350M
GPT2_LARGE = GPT2Config(d_model=1280, n_layer=36, n_head=20)         # 774M
GPT2_XL = GPT2Config(d_model=1600, n_layer=48, n_head=25)            # 1.5B


class GPT2Model(TrainModule):
    """Causal LM with tied input/output embeddings and next-token loss."""

    def __init__(self, config: GPT2Config):
        self.config = config

    # ---------------- init ----------------
    def init(self, rng) -> Dict[str, Any]:
        cfg = self.config
        d, L = cfg.d_model, cfg.n_layer
        keys = jax.random.split(rng, 8)
        std = 0.02
        resid_std = std / jnp.sqrt(2.0 * L)

        def norm(key, shape, s=std):
            return (jax.random.normal(key, shape, jnp.float32) * s)

        params = {
            "wte": norm(keys[0], (cfg.vocab_size, d)),
            "wpe": norm(keys[1], (cfg.n_positions, d)),
            "ln_f_scale": jnp.ones((d,), jnp.float32),
            "ln_f_bias": jnp.zeros((d,), jnp.float32),
            "blocks": {
                "ln1_scale": jnp.ones((L, d), jnp.float32),
                "ln1_bias": jnp.zeros((L, d), jnp.float32),
                # [L, d, 3, d] (not [L, d, 3d]): the q/k/v boundary lives
                # on its own unsharded dim so the TP 'model' shard on the
                # feature dim never straddles it — the fused-[3d] layout
                # forced GSPMD halo collective-permutes at every q/k/v
                # split (same values: reshape of the fused layout).
                "qkv_w": norm(keys[2], (L, d, 3, d)),
                "qkv_b": jnp.zeros((L, 3, d), jnp.float32),
                "out_w": norm(keys[3], (L, d, d), resid_std),
                "out_b": jnp.zeros((L, d), jnp.float32),
                "ln2_scale": jnp.ones((L, d), jnp.float32),
                "ln2_bias": jnp.zeros((L, d), jnp.float32),
                "fc_w": norm(keys[4], (L, d, 4 * d)),
                "fc_b": jnp.zeros((L, 4 * d), jnp.float32),
                "proj_w": norm(keys[5], (L, 4 * d, d), resid_std),
                "proj_b": jnp.zeros((L, d), jnp.float32),
            },
        }
        return params

    # ---------------- TP declaration ----------------
    def param_partition_specs(self, params) -> Dict[str, Any]:
        """Megatron column/row parallel layout on the ``model`` axis."""
        m = MODEL_AXIS
        return {
            "wte": P(m, None),          # vocab-sharded embedding
            "wpe": P(),                 # small, replicate
            "ln_f_scale": P(),
            "ln_f_bias": P(),
            "blocks": {
                "ln1_scale": P(), "ln1_bias": P(),
                "qkv_w": P(None, None, None, m),  # column parallel (per-
                "qkv_b": P(None, None, m),        # q/k/v feature shards)
                "out_w": P(None, m, None),   # row parallel
                "out_b": P(),
                "ln2_scale": P(), "ln2_bias": P(),
                "fc_w": P(None, None, m),    # column parallel
                "fc_b": P(None, m),
                "proj_w": P(None, m, None),  # row parallel
                "proj_b": P(),
            },
        }

    # ---------------- forward ----------------
    def _block(self, bp, x, rng, train: bool):
        """One transformer block; bp leaves have the layer axis removed."""
        return gpt2_block_forward(self.config, bp, x, rng, train)

    def apply(self, params, tokens: jnp.ndarray, rng,
              train: bool = True) -> jnp.ndarray:
        """tokens [B, T] int32 → logits [B, T, vocab]."""
        cfg = self.config
        B, T = tokens.shape
        if T > cfg.n_positions:
            raise ValueError(
                f"sequence length {T} exceeds n_positions={cfg.n_positions}")
        x = params["wte"][tokens] + params["wpe"][:T][None]
        x = _dropout(x, cfg.embd_dropout if train else 0.0,
                     jax.random.fold_in(rng, 997))

        block_params = params["blocks"]

        def body(carry, xs):
            x = carry
            bp, i = xs
            lrng = jax.random.fold_in(rng, i)
            return self._block(bp, x, lrng, train), None

        body_fn = body
        if cfg.remat == "block":
            body_fn = jax.checkpoint(body)

        if cfg.scan_layers and cfg.stream_scan:
            # Param-streaming form: block params stay a scan CONSTANT
            # (host-resident under zero_optimization.param_streaming) and
            # the body fetches layer i's slice with an explicit transfer
            # to device memory.  The fetch sits INSIDE the remat'd body,
            # so the backward pass re-fetches each layer instead of
            # keeping the stack alive — device param bytes ~ one layer in
            # both directions.  The transfer's transpose moves the layer
            # grads back toward the stack's (host) memory space, so the
            # accumulated grad stack does not claim HBM either.
            fetch = _layer_fetcher(
                self.param_partition_specs(params)["blocks"])

            def body_stream(carry, i):
                return body(carry, (fetch(block_params, i), i))

            if cfg.remat == "block":
                body_stream = jax.checkpoint(body_stream)
            x, _ = jax.lax.scan(body_stream, x, jnp.arange(cfg.n_layer))
        elif cfg.scan_layers:
            layer_idx = jnp.arange(cfg.n_layer)
            x, _ = jax.lax.scan(body_fn, x, (block_params, layer_idx))
        else:
            for i in range(cfg.n_layer):
                bp = jax.tree.map(lambda a, i=i: a[i], block_params)
                x, _ = body_fn(x, (bp, jnp.asarray(i)))

        x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
        logits = x @ params["wte"].astype(x.dtype).T
        return logits

    def loss_fn(self, params, batch, rng, train: bool = True):
        tokens = batch["input_ids"] if isinstance(batch, dict) else batch
        logits = self.apply(params, tokens[:, :-1], rng, train)
        targets = tokens[:, 1:]
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    # ---------------- serving entry points ----------------
    def prefill(self, params, tokens):
        """Inference forward that also returns every layer's K/V (the
        serving cache fill) — see ``gpt2_prefill``."""
        return gpt2_prefill(self.config, params, tokens)

    def decode_step(self, params, tokens, k_cache, v_cache, lengths,
                    active, impl: Optional[str] = None):
        """One masked decode tick over the slot KV cache — see
        ``gpt2_decode_step``."""
        return gpt2_decode_step(self.config, params, tokens, k_cache,
                                v_cache, lengths, active, impl=impl)

    def prefill_paged(self, params, tokens, delta_len, prefix_len,
                      page_row, k_pool, v_pool, k_scale=None,
                      v_scale=None, lora=None, adapter_slots=None,
                      lora_scale: float = 1.0):
        """Delta-aware prefill into a paged KV pool — see
        ``gpt2_prefill_paged``."""
        return gpt2_prefill_paged(self.config, params, tokens,
                                  delta_len, prefix_len, page_row,
                                  k_pool, v_pool, k_scale=k_scale,
                                  v_scale=v_scale, lora=lora,
                                  adapter_slots=adapter_slots,
                                  lora_scale=lora_scale)

    def decode_step_paged(self, params, tokens, k_pool, v_pool,
                          page_table, lengths, active,
                          impl: Optional[str] = None, k_scale=None,
                          v_scale=None, lora=None, adapter_slots=None,
                          lora_scale: float = 1.0):
        """One masked decode tick over the paged KV pool — see
        ``gpt2_decode_step_paged``."""
        return gpt2_decode_step_paged(self.config, params, tokens,
                                      k_pool, v_pool, page_table,
                                      lengths, active, impl=impl,
                                      k_scale=k_scale, v_scale=v_scale,
                                      lora=lora,
                                      adapter_slots=adapter_slots,
                                      lora_scale=lora_scale)

    def verify_step(self, params, tokens, k_cache, v_cache, lengths,
                    active, impl: Optional[str] = None):
        """Score W speculative tokens per slot in one widened decode
        pass — see ``gpt2_verify_step``."""
        return gpt2_verify_step(self.config, params, tokens, k_cache,
                                v_cache, lengths, active, impl=impl)

    def verify_step_paged(self, params, tokens, k_pool, v_pool,
                          page_table, lengths, active,
                          impl: Optional[str] = None, k_scale=None,
                          v_scale=None, lora=None, adapter_slots=None,
                          lora_scale: float = 1.0):
        """The paged twin of ``verify_step`` — see
        ``gpt2_verify_step_paged``."""
        return gpt2_verify_step_paged(self.config, params, tokens,
                                      k_pool, v_pool, page_table,
                                      lengths, active, impl=impl,
                                      k_scale=k_scale, v_scale=v_scale,
                                      lora=lora,
                                      adapter_slots=adapter_slots,
                                      lora_scale=lora_scale)

    # ---------------- param-streaming declaration ----------------
    def streaming_param_spec(self, params):
        """The stacked block leaves stream (one layer per scan tick);
        embeddings/final LN stay device-resident.  Requires the scan form
        with explicit per-layer fetch (``stream_scan``) so the engine's
        host placement actually bounds device bytes."""
        if not (self.config.scan_layers and self.config.stream_scan):
            return None
        return {
            k: jax.tree.map(lambda _: k == "blocks", v)
            for k, v in params.items()
        }


_DEVICE_MEMORY_KIND: Optional[str] = None


def _device_memory_kind() -> str:
    """The backend's default (device/HBM) memory kind — the fetch target
    for streamed layer slices.  'device' on TPU and on the CPU test
    backend; resolved once, outside any trace."""
    global _DEVICE_MEMORY_KIND
    if _DEVICE_MEMORY_KIND is None:
        try:
            _DEVICE_MEMORY_KIND = jax.local_devices()[0].default_memory().kind
        except Exception:
            _DEVICE_MEMORY_KIND = "device"
    return _DEVICE_MEMORY_KIND


def stream_fetch(tree, specs_tree, index, rows=None):
    """Fetch the streaming slice of every leaf's leading (layer) axis and
    move it into device memory with the leaf's own TP sharding (leading
    dim dropped for a single squeezed row when ``rows`` is None, kept at
    length ``rows`` otherwise).  Uses the engine's ambient mesh
    (``jax.set_mesh``); with no mesh set (eager unit use) the fetch
    degrades to a plain index.  Shared by the GPT-2 layer scan and the
    MoE group scan."""
    am = jax.sharding.get_abstract_mesh()
    has_mesh = am is not None and bool(dict(getattr(am, "shape", {})))
    kind = _device_memory_kind() if has_mesh else None

    def one(a, spec):
        if rows is None:
            w = jax.lax.dynamic_index_in_dim(a, index, 0, keepdims=False)
            sp = P(*tuple(spec)[1:])
        else:
            w = jax.lax.dynamic_slice_in_dim(a, index, rows, 0)
            sp = P(*((None,) + tuple(spec)[1:]))
        if not has_mesh:
            return w
        return jax.device_put(
            w, jax.sharding.NamedSharding(am, sp, memory_kind=kind))

    return jax.tree.map(one, tree, specs_tree)


def _layer_fetcher(block_specs):
    """Per-layer fetch for GPT-2's streaming scan (see stream_fetch)."""
    def fetch(block_params, i):
        return stream_fetch(block_params, block_specs, i)
    return fetch


def gpt2_block_forward(cfg: GPT2Config, bp, x, rng, train: bool):
    """One pre-LN transformer block over unstacked per-layer params — the
    single source of the block math, shared by the scan-over-layers model,
    the pipeline flavor (models/gpt2_pipe.py), and the MoE flavor
    (models/gpt2_moe.py, which swaps the FFN sublayer)."""
    r_attn, r3 = jax.random.split(rng)
    drop = cfg.dropout if train else 0.0
    x = gpt2_attn_sublayer(cfg, bp, x, r_attn, train)
    h = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    h = gpt2_ffn(bp, h)
    return x + _dropout(h, drop, r3)


def _wscale(y, bp, name: str):
    """Fused weight dequant (serving.quantization.weights='int8',
    docs/serving.md): a quantized tree carries an ``<name>_scale``
    sibling per matmul weight, and because the scale is per OUTPUT
    channel, ``x · (w8 · s) == (x · w8) · s`` — one multiply on the
    matmul output, never a dequantized weight matrix.  Trees without
    scales (every training path, the default serving config) take the
    no-op branch: their trace is byte-identical to the pre-quant
    code."""
    s = bp.get(name + "_scale")
    return y if s is None else y * s.astype(y.dtype)


def _lora_delta(x, bp, name: str):
    """Heterogeneous batched LoRA delta (serving.lora, docs/serving.md
    "multi-tenant serving"): a lora-bound tree carries a
    ``<name>_lora`` sibling of PER-ROW gathered factors
    ``(A [B, d_in, r], B [B, r, *out], alpha/r)`` — each batch row's
    own tenant adapter, gathered by the traced adapter-slot table
    (:func:`_lora_bind`) — and the delta ``(x·A)·B · (alpha/r)`` is
    computed fused next to the base matmul (S-LoRA/Punica, PAPERS.md).
    Trees without lora entries (every training path, the default
    serving config) return None: their trace is byte-identical to the
    pre-lora code, the ``_wscale`` discipline applied to adapters."""
    lo = bp.get(name + "_lora")
    if lo is None:
        return None
    a, b, scale = lo
    u = jnp.einsum("btd,bdr->btr", x, a.astype(x.dtype))
    delta = jnp.einsum("btr,br...->bt...", u, b.astype(x.dtype))
    return delta * jnp.asarray(scale, x.dtype)


def _lora_bind(bp, lora_layer, adapter_slots, scale):
    """Bind one layer's adapter-slot pools into the block-param dict:
    gather every target's per-row factors by the TRACED int32
    ``adapter_slots`` (the PR 11 scalar-prefetch idiom applied to
    weights — slot 0 is the reserved zero adapter, so no-tenant rows
    compute a mathematically-zero delta through the SAME program).
    ``lora_layer`` is ``{target: (A [N, d_in, r], B [N, r, *out])}``;
    returns a shallow copy of ``bp`` with ``<target>_lora`` entries."""
    if lora_layer is None:
        return bp
    bp = dict(bp)
    for t in sorted(lora_layer):
        a, b = lora_layer[t]
        bp[t + "_lora"] = (a[adapter_slots], b[adapter_slots], scale)
    return bp


def gpt2_ffn(bp, h):
    """fc → gelu → proj over already-normalized input (dense FFN body,
    shared with the MoE flavor's dense blocks)."""
    y = _wscale(h @ bp["fc_w"].astype(h.dtype), bp, "fc_w") \
        + bp["fc_b"].astype(h.dtype)
    d = _lora_delta(h, bp, "fc_w")
    if d is not None:
        y = y + d
    h = jax.nn.gelu(y, approximate=True)
    z = _wscale(h @ bp["proj_w"].astype(h.dtype), bp, "proj_w") \
        + bp["proj_b"].astype(h.dtype)
    d = _lora_delta(h, bp, "proj_w")
    if d is not None:
        z = z + d
    return z


def gpt2_qkv_heads(cfg: GPT2Config, bp, x):
    """ln1 → fused qkv → per-head split, [B, H, T, Dh] each — the
    attention sublayer's input math, shared by the training sublayer and
    the serving prefill/decode paths (they must stay bit-identical or
    the decode cache silently diverges from the training forward)."""
    B, T, D = x.shape
    H, Dh = cfg.n_head, cfg.d_head
    h = _layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
    # contraction keeps q/k/v on a dedicated unsharded dim — slicing it is
    # local under TP (see the qkv_w layout note in GPT2Model.init)
    qkv = (_wscale(jnp.einsum("btd,dke->btke", h,
                              bp["qkv_w"].astype(h.dtype)), bp, "qkv_w")
           + bp["qkv_b"].astype(h.dtype))
    d = _lora_delta(h, bp, "qkv_w")                 # [B, T, 3, E]
    if d is not None:
        qkv = qkv + d
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

    def heads(t):
        return t.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)

    return heads(q), heads(k), heads(v)


def gpt2_attn_project(bp, x, attn, drop: float, rng):
    """heads → output projection → residual (the sublayer's tail,
    shared with the serving paths; ``rng`` may be None when drop=0)."""
    B, H, T, Dh = attn.shape
    attn = attn.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
    y = _wscale(attn @ bp["out_w"].astype(x.dtype), bp, "out_w") \
        + bp["out_b"].astype(x.dtype)
    d = _lora_delta(attn, bp, "out_w")
    if d is not None:
        y = y + d
    return x + _dropout(y, drop, rng)


def gpt2_attn_sublayer(cfg: GPT2Config, bp, x, rng, train: bool):
    """ln1 → attention → residual (the block minus its FFN sublayer)."""
    B, T, D = x.shape
    H, Dh = cfg.n_head, cfg.d_head
    r1, r2 = jax.random.split(rng)
    drop = cfg.dropout if train else 0.0

    q, k, v = gpt2_qkv_heads(cfg, bp, x)

    if cfg.attn_impl == "flash":
        # Pallas flash kernel (prob-dropout fused in-kernel).
        from ..ops.pallas.flash_attention import mha
        attn = mha(q, k, v,
                   dropout_rate=drop, dropout_rng=r1, causal=True)
    elif cfg.attn_impl == "dense":
        attn = causal_attention(q, k, v,
                                dropout_rate=drop, dropout_rng=r1)
    elif cfg.attn_impl in ("ring", "ulysses"):
        # sequence-parallel attention over the mesh's 'seq' axis: manual
        # shard_map on 'seq' only, data/model stay under GSPMD.  Requires
        # the engine to run under jax.set_mesh (it does) so the abstract
        # mesh is visible here.
        from jax.sharding import PartitionSpec as P
        from ..parallel.sequence import (SEQ_AXIS, ring_attention,
                                         ulysses_attention)
        am = jax.sharding.get_abstract_mesh()
        sp = dict(getattr(am, "shape", {})).get(SEQ_AXIS, 1)
        # Direct attribute access on purpose: if jax renames manual_axes
        # this guard must break loudly, not silently disable (a silent ()
        # default would let sp>1 run inside the 1-bit/CSR engines' manual
        # 'data' shard_map — exactly the partitioner crash / divergent-
        # collective deadlock this guard pre-empts).
        manual = set(am.manual_axes) if am is not None else set()
        if sp > 1 and not manual <= {"pipe"}:
            # Nesting under the pipeline's manual 'pipe' axis is
            # supported: the inner shard_map closes over only 'seq' and
            # the pipeline's uniform-stage body keeps the seq collectives
            # identical on every pipe rank (pipe/engine.py:
            # _uniform_stack_info).  Any OTHER manual context (the 1-bit
            # and CSR engines' shard_map over 'data', or 'seq' itself
            # already manual) has had no such hardening — fail with the
            # real story instead of a partitioner crash or a divergent
            # collective deadlock.
            raise NotImplementedError(
                "sequence-parallel attention cannot run inside a manual "
                f"SPMD program over axes {sorted(manual)}; sp composes "
                "with the plain dp/tp/ZeRO engines and (via the uniform-"
                "stage body) the pipeline engine — not the 1-bit or "
                "sparse-gradient engines")
        seed = (jax.random.bits(r1, (), jnp.uint32) if drop > 0.0
                else jnp.zeros((), jnp.uint32))
        if sp > 1:
            impl = (ring_attention if cfg.attn_impl == "ring"
                    else ulysses_attention)
            spec = P(None, None, SEQ_AXIS, None)
            # dropout mask is hashed from GLOBAL positions (the flash
            # kernel's hash), so the seed is a replicated scalar and the
            # realization is identical for any seq-shard count (incl.
            # the sp==1 fallback below)
            # the seq rank rides in as a P(seq)-sharded iota operand:
            # axis_index inside this shard_map would lower to a manual
            # computation over the complement axes, which re-binds 'pipe'
            # when nested inside the pipeline engine's manual region
            fn = jax.shard_map(
                lambda q, k, v, seed, rk: impl(
                    q, k, v, SEQ_AXIS, causal=True, dropout_rate=drop,
                    dropout_seed=seed, rank=rk),
                in_specs=(spec, spec, spec, P(), P(SEQ_AXIS)),
                out_specs=spec,
                axis_names={SEQ_AXIS}, check_vma=False)
            attn = fn(q, k, v, seed, jnp.arange(sp, dtype=jnp.int32))
        else:  # mesh has no seq shards: dense attention, same hash mask
            keep = None
            if drop > 0.0:
                from ..ops.pallas.flash_attention import dense_keep_mask
                keep = dense_keep_mask(B, H, T, T, seed, drop)
            attn = causal_attention(q, k, v,
                                    dropout_rate=drop, dropout_keep=keep)
    else:
        raise ValueError(
            f"attn_impl={cfg.attn_impl!r}: expected 'flash', 'dense', "
            "'ring', or 'ulysses'")
    return gpt2_attn_project(bp, x, attn, drop, r2)


# ---------------------------------------------------------------------------
# serving paths: prefill + step-decode over a slot KV cache
# (deepspeed_tpu/inference/ — docs/serving.md).  These REUSE the block
# helpers above (gpt2_qkv_heads / gpt2_attn_project / gpt2_ffn /
# _layer_norm) so a step-decoded token's logits match the training
# forward's logits at the same position: the prefill==decode parity
# tests (tests/test_inference.py) pin fp32 bitwise on the dense path.
# ---------------------------------------------------------------------------


def _decode_attn_impl(cfg: GPT2Config) -> str:
    """Map the training attention impl onto the decode kernel arm."""
    if cfg.attn_impl == "flash":
        return "pallas"
    if cfg.attn_impl == "dense":
        return "dense"
    raise NotImplementedError(
        f"attn_impl={cfg.attn_impl!r} has no serving decode path; serve "
        "with 'flash' or 'dense' (sequence-parallel attention shards the "
        "time axis the decode cache does not have)")


def gpt2_block_prefill(cfg: GPT2Config, bp, x):
    """One block at inference (train=False — every dropout is a no-op),
    additionally returning the per-head K/V for the serving cache."""
    q, k, v = gpt2_qkv_heads(cfg, bp, x)
    if cfg.attn_impl == "flash":
        from ..ops.pallas.flash_attention import flash_attention
        attn = flash_attention(q, k, v, causal=True)
    elif cfg.attn_impl == "dense":
        attn = causal_attention(q, k, v)
    else:
        _decode_attn_impl(cfg)  # raises with the real story
    x = gpt2_attn_project(bp, x, attn, 0.0, None)
    h = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    return x + gpt2_ffn(bp, h), (k, v)


def _cache_write(cache, new, pos, active):
    """Masked in-place write of one token's K (or V) rows into the slot
    cache: ``cache[s, :, pos[s]] = new[s]`` where ``active[s]``; inactive
    slots write their OLD value back (a pure no-op), so one static-shape
    program serves any admission/eviction mix.  cache [S, H, T, Dh],
    new [S, H, Dh], pos [S] int32 (clipped), active [S] bool."""
    S, H, T, Dh = cache.shape
    s_idx = jnp.arange(S)
    pos = jnp.clip(pos, 0, T - 1)
    old = cache[s_idx, :, pos]                          # [S, H, Dh]
    blended = jnp.where(active[:, None, None], new.astype(cache.dtype),
                        old)
    return cache.at[s_idx, :, pos].set(blended)


def gpt2_block_decode(cfg: GPT2Config, bp, x, k_cache, v_cache,
                      positions, att_len, active, impl: str):
    """One block for a single decode tick: x [S, 1, D] (one new token
    per slot); writes the token's K/V at ``positions`` (masked by
    ``active``) then attends over ``att_len`` live keys per slot."""
    q, k, v = gpt2_qkv_heads(cfg, bp, x)                # [S, H, 1, Dh]
    k_cache = _cache_write(k_cache, k[:, :, 0], positions, active)
    v_cache = _cache_write(v_cache, v[:, :, 0], positions, active)
    from ..ops.pallas.decode_attention import decode_attention
    attn = decode_attention(q[:, :, 0], k_cache, v_cache, att_len,
                            impl=impl)                  # [S, H, Dh]
    x = gpt2_attn_project(bp, x, attn[:, :, None, :], 0.0, None)
    h = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    return x + gpt2_ffn(bp, h), k_cache, v_cache


def gpt2_prefill(cfg: GPT2Config, params, tokens):
    """tokens [B, T] int32 → (logits [B, T, V], k, v [L, B, H, T, Dh]).

    The inference forward (train=False numerics of ``GPT2Model.apply``)
    that also materializes every layer's K/V for the serving cache.
    Causal masking means positions beyond a prompt's live length only
    contaminate THEIR OWN rows — the cache masks them by length."""
    B, T = tokens.shape
    if T > cfg.n_positions:
        raise ValueError(
            f"sequence length {T} exceeds n_positions={cfg.n_positions}")
    x = params["wte"][tokens] + params["wpe"][:T][None]
    block_params = params["blocks"]
    if cfg.scan_layers:
        def body(x, bp):
            return gpt2_block_prefill(cfg, bp, x)
        x, (ks, vs) = jax.lax.scan(body, x, block_params)
    else:
        ks_l, vs_l = [], []
        for i in range(cfg.n_layer):
            bp = jax.tree.map(lambda a, i=i: a[i], block_params)
            x, (kk, vv) = gpt2_block_prefill(cfg, bp, x)
            ks_l.append(kk)
            vs_l.append(vv)
        ks, vs = jnp.stack(ks_l), jnp.stack(vs_l)
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = x @ params["wte"].astype(x.dtype).T
    return logits, ks, vs


def gpt2_decode_step(cfg: GPT2Config, params, tokens, k_cache, v_cache,
                     lengths, active, impl: Optional[str] = None):
    """One decode tick for every slot at once (static shapes — the ONE
    compiled decode program of docs/serving.md).

    tokens [S] int32 — each slot's last emitted/prompt token;
    k_cache/v_cache [L, S, H, T, Dh]; lengths [S] int32 — live KV length
    BEFORE this token; active [S] bool — slots actually decoding this
    tick (free/finished slots compute masked no-ops).

    Returns (logits [S, V], k_cache, v_cache, new_lengths): logits for
    the NEXT token of each active slot; inactive slots' logits are
    garbage-but-finite and must be ignored by the caller."""
    if impl is None:
        impl = _decode_attn_impl(cfg)
    T = k_cache.shape[3]
    lengths = lengths.astype(jnp.int32)
    positions = jnp.clip(lengths, 0, min(T, cfg.n_positions) - 1)
    x = (params["wte"][tokens][:, None, :]
         + params["wpe"][positions][:, None, :])
    # live keys this tick INCLUDE the token being decoded; free slots
    # attend nothing (exact-zero attention rows)
    att_len = jnp.where(active, lengths + 1, 0).astype(jnp.int32)
    block_params = params["blocks"]
    if cfg.scan_layers:
        def body(x, xs):
            bp, kc, vc = xs
            x, kc, vc = gpt2_block_decode(cfg, bp, x, kc, vc, positions,
                                          att_len, active, impl)
            return x, (kc, vc)
        x, (k_cache, v_cache) = jax.lax.scan(
            body, x, (block_params, k_cache, v_cache))
    else:
        kc_l, vc_l = [], []
        for i in range(cfg.n_layer):
            bp = jax.tree.map(lambda a, i=i: a[i], block_params)
            x, kc, vc = gpt2_block_decode(cfg, bp, x, k_cache[i],
                                          v_cache[i], positions,
                                          att_len, active, impl)
            kc_l.append(kc)
            vc_l.append(vc)
        k_cache, v_cache = jnp.stack(kc_l), jnp.stack(vc_l)
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = (x @ params["wte"].astype(x.dtype).T)[:, 0]
    new_lengths = lengths + active.astype(jnp.int32)
    return logits, k_cache, v_cache, new_lengths


# ---------------------------------------------------------------------------
# speculative verify path (serving.speculate_k > 0, docs/serving.md):
# ONE widened decode pass scores W = k+1 new tokens per slot — the
# slot's pending token plus its k draft proposals — writing all W K/V
# rows (masked) and attending each query over its own causal window.
# Same block helpers, same masked-no-op contract as gpt2_decode_step;
# acceptance/rollback are the engine's (inference/speculative.py).
# ---------------------------------------------------------------------------


def _verify_rows(lengths, active, W: int, cap: int):
    """The per-row geometry every verify arm shares: absolute positions
    (clipped), write validity, and per-query attention lengths.

    Row ``i`` of slot ``s`` sits at absolute position ``lengths[s]+i``
    and attends ``lengths[s]+i+1`` keys.  Rows beyond ``cap`` (the
    cache stride / table capacity) are masked — their K/V write is a
    no-op and their output row is exact-zero garbage the engine's
    acceptance truncation discards (a kv_capacity finish is at most W
    tokens away)."""
    base = lengths.astype(jnp.int32)
    offs = jnp.arange(W, dtype=jnp.int32)[None, :]
    abs_pos = base[:, None] + offs                      # [S, W]
    row_valid = active[:, None] & (abs_pos < cap)
    positions = jnp.clip(abs_pos, 0, cap - 1)
    row_lens = jnp.where(row_valid, abs_pos + 1, 0).astype(jnp.int32)
    return positions, row_valid, row_lens


def gpt2_block_verify(cfg: GPT2Config, bp, x, k_cache, v_cache,
                      positions, row_valid, row_lens, impl: str):
    """One block of the verify pass: x [S, W, D] (W new tokens per
    slot); writes all W K/V rows (masked per row) then runs the
    multi-query decode attention."""
    q, k, v = gpt2_qkv_heads(cfg, bp, x)                # [S, H, W, Dh]
    W = x.shape[1]
    for i in range(W):                                  # static, W <= 9
        k_cache = _cache_write(k_cache, k[:, :, i], positions[:, i],
                               row_valid[:, i])
        v_cache = _cache_write(v_cache, v[:, :, i], positions[:, i],
                               row_valid[:, i])
    from ..ops.pallas.decode_attention import decode_attention_multi
    attn = decode_attention_multi(q, k_cache, v_cache, row_lens,
                                  impl=impl)            # [S, H, W, Dh]
    x = gpt2_attn_project(bp, x, attn, 0.0, None)
    h = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    return x + gpt2_ffn(bp, h), k_cache, v_cache


def gpt2_verify_step(cfg: GPT2Config, params, tokens, k_cache, v_cache,
                     lengths, active, impl: Optional[str] = None):
    """One speculative verify pass for every slot at once (static
    shapes — W = k+1 is baked into the program, everything else is
    traced, so the one-compiled-verify-program contract holds across
    arbitrary accepted-length mixes).

    tokens [S, W] int32 — per slot: its pending last token followed by
    its k draft proposals; k_cache/v_cache [L, S, H, T, Dh]; lengths
    [S] int32 — live KV length BEFORE this pass; active [S] bool.

    Returns ``(logits [S, W, V], k_cache, v_cache)``: ``logits[s, i]``
    scores the token AFTER ``tokens[s, i]`` (absolute position
    ``lengths[s] + i``).  Lengths are NOT advanced — how far the cache
    really moved is the acceptance decision, made by the caller
    (inference/speculative.py); un-accepted rows simply stay masked
    beyond the advanced length (the unpaged rollback is free)."""
    if impl is None:
        impl = _decode_attn_impl(cfg)
    S, W = tokens.shape
    T = k_cache.shape[3]
    cap = min(T, cfg.n_positions)
    positions, row_valid, row_lens = _verify_rows(lengths, active, W,
                                                  cap)
    x = params["wte"][tokens] + params["wpe"][positions]    # [S, W, D]
    block_params = params["blocks"]
    if cfg.scan_layers:
        def body(x, xs):
            bp, kc, vc = xs
            x, kc, vc = gpt2_block_verify(cfg, bp, x, kc, vc, positions,
                                          row_valid, row_lens, impl)
            return x, (kc, vc)
        x, (k_cache, v_cache) = jax.lax.scan(
            body, x, (block_params, k_cache, v_cache))
    else:
        kc_l, vc_l = [], []
        for i in range(cfg.n_layer):
            bp = jax.tree.map(lambda a, i=i: a[i], block_params)
            x, kc, vc = gpt2_block_verify(cfg, bp, x, k_cache[i],
                                          v_cache[i], positions,
                                          row_valid, row_lens, impl)
            kc_l.append(kc)
            vc_l.append(vc)
        k_cache, v_cache = jnp.stack(kc_l), jnp.stack(vc_l)
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = x @ params["wte"].astype(x.dtype).T            # [S, W, V]
    return logits, k_cache, v_cache


def gpt2_block_verify_paged(cfg: GPT2Config, bp, x, k_pool, v_pool,
                            page_table, positions, row_valid, row_lens,
                            impl: str, k_scale=None, v_scale=None):
    """One block of the PAGED verify pass: W masked page-routed writes
    (invalid rows to the scratch page) then the paged multi-query
    attention — quantizing each row on write and running the fused-
    dequant multi arm when the pool is int8."""
    q, k, v = gpt2_qkv_heads(cfg, bp, x)                # [S, H, W, Dh]
    W = x.shape[1]
    page_len = k_pool.shape[2]
    s_idx = jnp.arange(page_table.shape[0])
    for i in range(W):                                  # static, W <= 9
        pos = positions[:, i]
        page_ids = jnp.where(row_valid[:, i],
                             page_table[s_idx, pos // page_len], 0)
        offs = pos % page_len
        k_pool, k_scale = _paged_write(k_pool, k_scale, k[:, :, i],
                                       page_ids, offs, row_valid[:, i])
        v_pool, v_scale = _paged_write(v_pool, v_scale, v[:, :, i],
                                       page_ids, offs, row_valid[:, i])
    from ..ops.pallas.decode_attention import decode_attention_paged_multi
    attn = decode_attention_paged_multi(q, k_pool, v_pool, page_table,
                                        row_lens, impl=impl,
                                        k_scale=k_scale,
                                        v_scale=v_scale)
    x = gpt2_attn_project(bp, x, attn, 0.0, None)
    h = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    return (x + gpt2_ffn(bp, h), k_pool, v_pool, k_scale, v_scale)


def gpt2_verify_step_paged(cfg: GPT2Config, params, tokens, k_pool,
                           v_pool, page_table, lengths, active,
                           impl: Optional[str] = None,
                           k_scale=None, v_scale=None,
                           lora=None, adapter_slots=None,
                           lora_scale: float = 1.0):
    """The paged twin of ``gpt2_verify_step`` — same contract over the
    page pool; the engine must have allocated pages covering all W
    speculative rows before the pass (rollback frees the ones the
    acceptance didn't keep).  With the int8 pool's scale sidecars the
    return grows to (logits, k_pool, v_pool, k_scale, v_scale).
    ``lora``/``adapter_slots``/``lora_scale`` follow
    ``gpt2_decode_step_paged``'s multi-tenant contract."""
    if impl is None:
        impl = _decode_attn_impl(cfg)
    quant = k_scale is not None
    S, W = tokens.shape
    page_len = k_pool.shape[3]
    cap = min(page_table.shape[1] * page_len, cfg.n_positions)
    positions, row_valid, row_lens = _verify_rows(lengths, active, W,
                                                  cap)
    x = params["wte"][tokens] + params["wpe"][positions]
    block_params = params["blocks"]
    if cfg.scan_layers:
        def body(x, xs):
            bp, kc, vc, ks, vs = xs[:5]
            if lora is not None:
                bp = _lora_bind(bp, xs[5], adapter_slots, lora_scale)
            x, kc, vc, ks, vs = gpt2_block_verify_paged(
                cfg, bp, x, kc, vc, page_table, positions, row_valid,
                row_lens, impl, k_scale=ks, v_scale=vs)
            return x, (kc, vc, ks, vs)
        xs = (block_params, k_pool, v_pool, k_scale, v_scale)
        if lora is not None:
            xs = xs + (lora,)
        x, (k_pool, v_pool, k_scale, v_scale) = jax.lax.scan(
            body, x, xs)
    else:
        kc_l, vc_l, ks_l, vs_l = [], [], [], []
        for i in range(cfg.n_layer):
            bp = jax.tree.map(lambda a, i=i: a[i], block_params)
            if lora is not None:
                bp = _lora_bind(
                    bp, jax.tree.map(lambda a, i=i: a[i], lora),
                    adapter_slots, lora_scale)
            x, kc, vc, ks, vs = gpt2_block_verify_paged(
                cfg, bp, x, k_pool[i], v_pool[i], page_table, positions,
                row_valid, row_lens, impl,
                k_scale=None if k_scale is None else k_scale[i],
                v_scale=None if v_scale is None else v_scale[i])
            kc_l.append(kc)
            vc_l.append(vc)
            ks_l.append(ks)
            vs_l.append(vs)
        k_pool, v_pool = jnp.stack(kc_l), jnp.stack(vc_l)
        if quant:
            k_scale, v_scale = jnp.stack(ks_l), jnp.stack(vs_l)
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = x @ params["wte"].astype(x.dtype).T
    if quant:
        return logits, k_pool, v_pool, k_scale, v_scale
    return logits, k_pool, v_pool


# ---------------------------------------------------------------------------
# paged serving paths (serving.page_len > 0, docs/serving.md): the same
# block helpers over a flat page pool [P, H, page_len, Dh] addressed
# through per-slot int32 page tables.  Page 0 is the reserved scratch
# page: every MASKED write is routed there, so a scatter conflict can
# only be two no-ops colliding — an active slot's row is never racing
# a masked write.
# ---------------------------------------------------------------------------


def _paged_cache_write(pool, new, page_ids, offs, active):
    """Masked one-row-per-slot write into the page pool:
    ``pool[page_ids[s], :, offs[s]] = new[s]`` where ``active[s]``;
    inactive slots write their OLD value back at the scratch page.
    pool [P, H, page_len, Dh], new [S, H, Dh], page_ids/offs [S] int32
    (already routed to scratch for inactive slots), active [S] bool."""
    old = pool[page_ids, :, offs]                       # [S, H, Dh]
    blended = jnp.where(active[:, None, None], new.astype(pool.dtype),
                        old)
    return pool.at[page_ids, :, offs].set(blended)


def _paged_cache_write_quant(pool, scales, new, page_ids, offs, active):
    """The quantize-on-write twin of :func:`_paged_cache_write`
    (serving.quantization.kv='int8'): each fp row is quantized per
    (row, head) — symmetric absmax int8 + one fp32 scale
    (inference/quantize.py, the ONE quantization rule) — and both the
    int8 row and its scale land under the same mask, so an inactive
    slot's scale write is the same old-value no-op as its data write.
    pool int8 [P, H, page_len, Dh], scales fp32 [P, H, page_len]."""
    from ..inference.quantize import quantize_rows
    q8, s = quantize_rows(new)                          # [S,H,Dh]/[S,H]
    old = pool[page_ids, :, offs]
    old_s = scales[page_ids, :, offs]
    blended = jnp.where(active[:, None, None], q8, old)
    blended_s = jnp.where(active[:, None], s, old_s)
    return (pool.at[page_ids, :, offs].set(blended),
            scales.at[page_ids, :, offs].set(blended_s))


def _paged_write(pool, scales, new, page_ids, offs, active):
    """Dispatch one masked row write to the fp or quantized pool arm —
    ``scales`` None selects the pre-quant write, byte for byte."""
    if scales is None:
        return _paged_cache_write(pool, new, page_ids, offs, active), None
    return _paged_cache_write_quant(pool, scales, new, page_ids, offs,
                                    active)


def gpt2_block_decode_paged(cfg: GPT2Config, bp, x, k_pool, v_pool,
                            page_table, positions, att_len, active,
                            impl: str, k_scale=None, v_scale=None):
    """One block for a single paged decode tick: x [S, 1, D]; writes
    the token's K/V at ``positions`` into the slot's page (masked by
    ``active``, inactive routed to scratch) then attends over
    ``att_len`` live keys per slot through the page table.  With the
    int8 pool (``k_scale``/``v_scale`` [P, H, page_len]) the write
    quantizes per row and the attention runs the fused-dequant arm."""
    q, k, v = gpt2_qkv_heads(cfg, bp, x)                # [S, H, 1, Dh]
    page_len = k_pool.shape[2]
    s_idx = jnp.arange(page_table.shape[0])
    page_ids = jnp.where(active,
                         page_table[s_idx, positions // page_len], 0)
    offs = positions % page_len
    k_pool, k_scale = _paged_write(k_pool, k_scale, k[:, :, 0],
                                   page_ids, offs, active)
    v_pool, v_scale = _paged_write(v_pool, v_scale, v[:, :, 0],
                                   page_ids, offs, active)
    from ..ops.pallas.decode_attention import decode_attention_paged
    attn = decode_attention_paged(q[:, :, 0], k_pool, v_pool,
                                  page_table, att_len, impl=impl,
                                  k_scale=k_scale, v_scale=v_scale)
    x = gpt2_attn_project(bp, x, attn[:, :, None, :], 0.0, None)
    h = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    return (x + gpt2_ffn(bp, h), k_pool, v_pool, k_scale, v_scale)


def gpt2_decode_step_paged(cfg: GPT2Config, params, tokens, k_pool,
                           v_pool, page_table, lengths, active,
                           impl: Optional[str] = None,
                           k_scale=None, v_scale=None,
                           lora=None, adapter_slots=None,
                           lora_scale: float = 1.0):
    """One decode tick for every slot at once over the paged pool —
    the paged twin of ``gpt2_decode_step`` (same masked-no-op contract,
    same traced-operand zero-recompile contract; the page table is one
    more traced operand).

    tokens [S] int32; k_pool/v_pool [L, P, H, page_len, Dh];
    page_table [S, max_pages] int32 (dead entries = scratch page 0);
    lengths [S] int32 — live KV length BEFORE this token; active [S]
    bool.  Returns (logits [S, V], k_pool, v_pool, new_lengths).

    Quantized pool (serving.quantization.kv='int8'): pass the fp32
    scale sidecars ``k_scale``/``v_scale`` [L, P, H, page_len] — the
    return grows to (logits, k_pool, v_pool, k_scale, v_scale,
    new_lengths); they are one more scan carry, still traced, still
    zero-recompile.

    Multi-tenant LoRA (serving.lora, docs/serving.md): ``lora`` is the
    layer-stacked adapter-slot pools
    ``{target: (A [L, N, d_in, r], B [L, N, r, *out])}`` and
    ``adapter_slots`` [S] int32 maps each slot to its tenant's HBM
    adapter slot (0 = the reserved zero adapter).  Both are TRACED
    operands — tenant mixes change the table contents, never the
    program.  ``lora=None`` (the default) leaves the trace
    byte-identical to the pre-lora code."""
    if impl is None:
        impl = _decode_attn_impl(cfg)
    quant = k_scale is not None
    page_len = k_pool.shape[3]
    cap = page_table.shape[1] * page_len
    lengths = lengths.astype(jnp.int32)
    positions = jnp.clip(lengths, 0, min(cap, cfg.n_positions) - 1)
    x = (params["wte"][tokens][:, None, :]
         + params["wpe"][positions][:, None, :])
    att_len = jnp.where(active, lengths + 1, 0).astype(jnp.int32)
    block_params = params["blocks"]
    if cfg.scan_layers:
        def body(x, xs):
            bp, kc, vc, ks, vs = xs[:5]
            if lora is not None:
                bp = _lora_bind(bp, xs[5], adapter_slots, lora_scale)
            x, kc, vc, ks, vs = gpt2_block_decode_paged(
                cfg, bp, x, kc, vc, page_table, positions, att_len,
                active, impl, k_scale=ks, v_scale=vs)
            return x, (kc, vc, ks, vs)
        xs = (block_params, k_pool, v_pool, k_scale, v_scale)
        if lora is not None:
            xs = xs + (lora,)
        x, (k_pool, v_pool, k_scale, v_scale) = jax.lax.scan(
            body, x, xs)
    else:
        kc_l, vc_l, ks_l, vs_l = [], [], [], []
        for i in range(cfg.n_layer):
            bp = jax.tree.map(lambda a, i=i: a[i], block_params)
            if lora is not None:
                bp = _lora_bind(
                    bp, jax.tree.map(lambda a, i=i: a[i], lora),
                    adapter_slots, lora_scale)
            x, kc, vc, ks, vs = gpt2_block_decode_paged(
                cfg, bp, x, k_pool[i], v_pool[i], page_table,
                positions, att_len, active, impl,
                k_scale=None if k_scale is None else k_scale[i],
                v_scale=None if v_scale is None else v_scale[i])
            kc_l.append(kc)
            vc_l.append(vc)
            ks_l.append(ks)
            vs_l.append(vs)
        k_pool, v_pool = jnp.stack(kc_l), jnp.stack(vc_l)
        if quant:
            k_scale, v_scale = jnp.stack(ks_l), jnp.stack(vs_l)
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = (x @ params["wte"].astype(x.dtype).T)[:, 0]
    new_lengths = lengths + active.astype(jnp.int32)
    if quant:
        return logits, k_pool, v_pool, k_scale, v_scale, new_lengths
    return logits, k_pool, v_pool, new_lengths


def gpt2_block_prefill_paged(cfg: GPT2Config, bp, x, k_pool, v_pool,
                             page_row, prefix_len, delta_len,
                             k_scale=None, v_scale=None):
    """One block of the delta-aware paged prefill: compute the DELTA
    tokens' K/V (positions ``prefix_len + i``), scatter them into the
    slot's pages, then attend.

    Two attention arms under ``lax.cond`` on the TRACED ``prefix_len``:

    * ``prefix_len == 0`` (no cached prefix) — the model's OWN prefill
      attention (flash or dense, exactly ``gpt2_block_prefill``'s ops),
      so a paged prefill without a prefix hit is BITWISE identical to
      the pre-page prefill: the parity anchor of tests/test_paged_kv.py.
      With the int8 pool the attention still runs over the EXACT fp
      K/V (only the STORED rows are quantized — the standard KV-quant
      discipline: prefill computes full-precision, decode reads back
      dequantized; docs/serving.md tolerance tiers).
    * ``prefix_len > 0`` — dense attention over the pool gathered
      through ``page_row`` (dequantized on the quant arm): delta query
      ``i`` (absolute position ``prefix_len+i``) attends every key at
      absolute position ``<= prefix_len+i`` — the cached prefix plus
      the causal delta.
    """
    q, k, v = gpt2_qkv_heads(cfg, bp, x)                # [1, H, Tq, Dh]
    Tq = x.shape[1]
    page_len = k_pool.shape[2]
    cap = page_row.shape[0] * page_len
    abs_pos = prefix_len + jnp.arange(Tq, dtype=jnp.int32)
    valid = jnp.arange(Tq) < delta_len
    # masked rows route to the scratch page: a clipped dead position
    # must never collide with a live row's (page, off) target
    abs_clip = jnp.clip(abs_pos, 0, cap - 1)
    page_ids = jnp.where(valid, page_row[abs_clip // page_len], 0)
    offs = abs_clip % page_len
    kn = k[0].transpose(1, 0, 2)                        # [Tq, H, Dh]
    vn = v[0].transpose(1, 0, 2)
    k_pool, k_scale = _paged_write(k_pool, k_scale, kn, page_ids, offs,
                                   valid)
    v_pool, v_scale = _paged_write(v_pool, v_scale, vn, page_ids, offs,
                                   valid)

    def _self_arm(_):
        # the pre-page prefill attention, op for op
        if cfg.attn_impl == "flash":
            from ..ops.pallas.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=True)
        return causal_attention(q, k, v)

    def _gather_arm(_):
        from ..ops.pallas.decode_attention import (_default_scale,
                                                   dequantize_paged,
                                                   paged_gather)
        if k_scale is not None:
            kg = dequantize_paged(k_pool, k_scale, page_row[None])[0]
            vg = dequantize_paged(v_pool, v_scale, page_row[None])[0]
        else:
            kg = paged_gather(k_pool, page_row[None])[0]  # [H, T', Dh]
            vg = paged_gather(v_pool, page_row[None])[0]
        scale = _default_scale(cfg.d_head)
        s = jnp.einsum("htd,hsd->hts", q[0], kg.astype(q.dtype),
                       preferred_element_type=jnp.float32) * scale
        key_pos = jnp.arange(kg.shape[1], dtype=jnp.int32)
        ok = key_pos[None, :] <= abs_pos[:, None]       # [Tq, T']
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
        s = jnp.where(ok[None], s, neg)
        probs = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("hts,hsd->htd", probs,
                          vg.astype(q.dtype))[None]

    attn = jax.lax.cond(prefix_len == 0, _self_arm, _gather_arm,
                        operand=None)
    x = gpt2_attn_project(bp, x, attn, 0.0, None)
    h = _layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    return (x + gpt2_ffn(bp, h), k_pool, v_pool, k_scale, v_scale)


def gpt2_prefill_paged(cfg: GPT2Config, params, tokens, delta_len,
                       prefix_len, page_row, k_pool, v_pool,
                       k_scale=None, v_scale=None,
                       lora=None, adapter_slots=None,
                       lora_scale: float = 1.0):
    """Delta-aware prefill into the paged pool (ONE compiled program
    for full prefills AND prefix-hit deltas — ``prefix_len``,
    ``delta_len`` and ``page_row`` are all traced).

    tokens [1, Tq] int32 — the DELTA tokens (prompt minus the cached
    prefix) right-padded to the static prefill bucket; delta_len /
    prefix_len scalars; page_row [max_pages] int32 — the slot's FULL
    table (shared prefix pages + freshly allocated delta pages, dead
    entries = scratch); k_pool/v_pool [L, P, H, page_len, Dh].

    Returns (logits [1, Tq, V], k_pool, v_pool): logits[0, i] scores
    the token after absolute position ``prefix_len + i`` — the first
    generated token reads ``logits[0, delta_len - 1]``.  Padding rows
    produce garbage-but-finite logits and never contaminate live rows
    (their K/V scatter is masked to the scratch page).

    Quantized pool: pass ``k_scale``/``v_scale`` [L, P, H, page_len];
    the return grows to (logits, k_pool, v_pool, k_scale, v_scale).

    Multi-tenant LoRA: ``adapter_slots`` is the requesting tenant's
    HBM adapter slot — a TRACED scalar (or [1]) int32, one slot per
    prefill — gathered from the same layer-stacked ``lora`` pools as
    the decode tick (``gpt2_decode_step_paged``'s contract)."""
    B, Tq = tokens.shape
    if Tq > cfg.n_positions:
        raise ValueError(
            f"sequence length {Tq} exceeds n_positions={cfg.n_positions}")
    quant = k_scale is not None
    prefix_len = jnp.asarray(prefix_len, jnp.int32)
    delta_len = jnp.asarray(delta_len, jnp.int32)
    pos = jnp.clip(prefix_len + jnp.arange(Tq, dtype=jnp.int32), 0,
                   cfg.n_positions - 1)
    x = params["wte"][tokens] + params["wpe"][pos][None]
    if lora is not None:
        # one tenant per prefill: a length-1 slot table so the batched
        # per-row gather (`_lora_delta`) is the SAME einsum as decode
        adapter_slots = jnp.atleast_1d(
            jnp.asarray(adapter_slots, jnp.int32))
    block_params = params["blocks"]
    if cfg.scan_layers:
        def body(x, xs):
            bp, kc, vc, ks, vs = xs[:5]
            if lora is not None:
                bp = _lora_bind(bp, xs[5], adapter_slots, lora_scale)
            x, kc, vc, ks, vs = gpt2_block_prefill_paged(
                cfg, bp, x, kc, vc, page_row, prefix_len, delta_len,
                k_scale=ks, v_scale=vs)
            return x, (kc, vc, ks, vs)
        xs = (block_params, k_pool, v_pool, k_scale, v_scale)
        if lora is not None:
            xs = xs + (lora,)
        x, (k_pool, v_pool, k_scale, v_scale) = jax.lax.scan(
            body, x, xs)
    else:
        kc_l, vc_l, ks_l, vs_l = [], [], [], []
        for i in range(cfg.n_layer):
            bp = jax.tree.map(lambda a, i=i: a[i], block_params)
            if lora is not None:
                bp = _lora_bind(
                    bp, jax.tree.map(lambda a, i=i: a[i], lora),
                    adapter_slots, lora_scale)
            x, kc, vc, ks, vs = gpt2_block_prefill_paged(
                cfg, bp, x, k_pool[i], v_pool[i], page_row, prefix_len,
                delta_len,
                k_scale=None if k_scale is None else k_scale[i],
                v_scale=None if v_scale is None else v_scale[i])
            kc_l.append(kc)
            vc_l.append(vc)
            ks_l.append(ks)
            vs_l.append(vs)
        k_pool, v_pool = jnp.stack(kc_l), jnp.stack(vc_l)
        if quant:
            k_scale, v_scale = jnp.stack(ks_l), jnp.stack(vs_l)
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = x @ params["wte"].astype(x.dtype).T
    if quant:
        return logits, k_pool, v_pool, k_scale, v_scale
    return logits, k_pool, v_pool


def _layer_norm(x, scale, bias, eps: float = 1e-5):
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) +
            bias.astype(jnp.float32)).astype(dt)


def _dropout(x, rate: float, rng):
    if rate <= 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)
