"""Mixture-of-Experts FFN with expert parallelism over the ``data`` axis.

The reference (DeepSpeed v0.3.2) predates DeepSpeed-MoE — SURVEY.md §2.4
records expert parallelism as absent — so, like sequence parallelism
(parallel/sequence.py), this fills the modern feature slot the way the
framework's later versions do, designed TPU-first rather than ported:

  - routing, dispatch, and combine are dense one-hot einsums (the GShard
    formulation): no scatter/gather, no dynamic shapes — every op tiles
    onto the MXU and the dispatch/combine "communication" lowers to XLA
    all_to_alls when the expert dim is sharded;
  - expert parallelism is a *placement decision*, exactly like ZeRO and
    Megatron TP elsewhere in this codebase: expert-stacked weights
    ``[E, d, f]`` declare ``P('data', ...)`` on the expert dim
    (``moe_param_specs``) and GSPMD partitions the expert compute over the
    data-parallel group — the same ep⊆dp mapping DeepSpeed-MoE uses for
    its expert groups;
  - expert weights can ALSO shard their feature dim over ``model``
    (column/row-parallel experts), composing EP × TP in one spec;
  - capacity is static (``ceil(top_k · cf · tokens / E)``): overflow
    tokens are dropped (their combine weight is zero) and flow through
    the residual connection, the standard Switch/GShard contract.

Gating runs in fp32 regardless of compute dtype; the auxiliary
load-balancing loss (Switch: ``E · Σ_e fraction_routed_e · mean_prob_e``)
and the router z-loss are returned for the model to fold into its total
loss.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int
    d_model: int
    d_ff: int
    top_k: int = 1                    # 1 = Switch, 2 = GShard
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    aux_loss_weight: float = 1e-2
    z_loss_weight: float = 0.0
    router_jitter: float = 0.0        # multiplicative input noise, train only

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {self.top_k}")
        if self.n_experts < 1:
            raise ValueError(f"n_experts must be >= 1, got {self.n_experts}")
        if self.n_experts < self.top_k:
            # top_k > n_experts would double-assign tokens to expert 0 with
            # half gates (probs2 is all-zero after masking, argmax re-picks
            # 0) — a silent half-weighting, not a meaningful routing.
            raise ValueError(
                f"n_experts ({self.n_experts}) must be >= top_k "
                f"({self.top_k})")

    def capacity(self, tokens_per_group: int, train: bool) -> int:
        cf = self.capacity_factor if train else self.eval_capacity_factor
        c = math.ceil(self.top_k * cf * tokens_per_group / self.n_experts)
        return max(1, min(tokens_per_group, c))


def init_moe_params(rng, cfg: MoEConfig, std: float = 0.02,
                    out_std: Optional[float] = None) -> Dict[str, Any]:
    """Expert-stacked FFN weights + router. ``out_std`` scales the output
    projection (models pass their residual-scaled std)."""
    E, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff
    k_g, k_i, k_o = jax.random.split(rng, 3)
    return {
        "wg": jax.random.normal(k_g, (d, E), jnp.float32) * std,
        "wi": jax.random.normal(k_i, (E, d, f), jnp.float32) * std,
        "bi": jnp.zeros((E, f), jnp.float32),
        "wo": jax.random.normal(k_o, (E, f, d), jnp.float32)
        * (std if out_std is None else out_std),
        "bo": jnp.zeros((E, d), jnp.float32),
    }


def moe_param_specs(ep_axis: str = DATA_AXIS,
                    tp_axis: Optional[str] = MODEL_AXIS,
                    stacked: bool = False) -> Dict[str, P]:
    """Placement: expert dim over ``ep_axis`` (expert parallelism), hidden
    feature dim over ``tp_axis`` (column/row-parallel experts).  With
    ``stacked`` the specs gain a leading ``None`` for a layer axis."""
    lead = (None,) if stacked else ()
    tp = tp_axis  # None disables the TP split
    return {
        "wg": P(*lead),                        # tiny; replicate
        "wi": P(*lead, ep_axis, None, tp),     # column parallel
        "bi": P(*lead, ep_axis, tp),
        "wo": P(*lead, ep_axis, tp, None),     # row parallel
        "bo": P(*lead, ep_axis, None),
    }


def _constrain(x, spec: P):
    """Sharding constraint that is a no-op when no mesh context is set
    (pure single-device unit tests) — the engine always runs its step
    under ``jax.set_mesh``, where the constraint binds."""
    mesh = jax.sharding.get_abstract_mesh()
    # Direct attribute access on purpose (mirrors gpt2.py's sp guard): if
    # jax renames manual_axes this must break loudly, not silently start
    # constraining inside manual computations.
    if mesh is None or not mesh.shape or mesh.manual_axes:
        return x
    return jax.lax.with_sharding_constraint(x, P(*spec))


def _top1_dispatch(probs, capacity: int):
    """probs [G,S,E] → (dispatch [G,S,E,C] {0,1}, combine [G,S,E,C])."""
    E = probs.shape[-1]
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, E, dtype=probs.dtype)          # [G,S,E]
    gate = jnp.sum(probs * mask, axis=-1)                     # [G,S]
    pos = jnp.cumsum(mask, axis=1) * mask - 1.0               # [G,S,E]
    keep = (pos >= 0) & (pos < capacity)
    dispatch = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=probs.dtype) \
        * (mask * keep)[..., None]                            # [G,S,E,C]
    combine = gate[..., None, None] * dispatch
    return dispatch, combine, mask


def _top2_dispatch(probs, capacity: int):
    """GShard top-2: second expert's gate renormalized against the first;
    its capacity positions come after all top-1 assignments."""
    E = probs.shape[-1]
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=probs.dtype)
    g1 = jnp.sum(probs * mask1, axis=-1)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    pos1 = jnp.cumsum(mask1, axis=1) * mask1 - 1.0
    # second choices queue behind every first-choice assignment in the group
    count1 = jnp.sum(mask1, axis=1, keepdims=True)            # [G,1,E]
    pos2 = (jnp.cumsum(mask2, axis=1) + count1) * mask2 - 1.0

    def one_hot_disp(pos, mask):
        keep = (pos >= 0) & (pos < capacity)
        return jax.nn.one_hot(
            pos.astype(jnp.int32), capacity, dtype=probs.dtype) \
            * (mask * keep)[..., None]

    d1 = one_hot_disp(pos1, mask1)
    d2 = one_hot_disp(pos2, mask2)
    dispatch = d1 + d2
    combine = g1[..., None, None] * d1 + g2[..., None, None] * d2
    return dispatch, combine, mask1


def moe_ffn(cfg: MoEConfig, mp: Dict[str, Any], x: jnp.ndarray, rng,
            train: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [G, S, d] → (y [G, S, d], weighted aux-loss scalar fp32).

    Dropped (over-capacity) tokens produce y=0 at their positions; the
    caller's residual connection carries them through unchanged.
    """
    G, S, d = x.shape
    E = cfg.n_experts
    C = cfg.capacity(S, train)
    x_gate = x.astype(jnp.float32)
    if train and cfg.router_jitter > 0.0:
        eps = cfg.router_jitter
        x_gate = x_gate * jax.random.uniform(
            jax.random.fold_in(rng, 11), x_gate.shape, jnp.float32,
            1.0 - eps, 1.0 + eps)
    logits = x_gate @ mp["wg"]                                # [G,S,E] fp32
    probs = jax.nn.softmax(logits, axis=-1)

    if cfg.top_k == 1:
        dispatch, combine, mask1 = _top1_dispatch(probs, C)
    else:
        dispatch, combine, mask1 = _top2_dispatch(probs, C)

    # Switch load-balance loss: E · Σ_e (fraction of tokens routed to e) ·
    # (mean router prob of e); 1.0 at perfect balance.  The returned term
    # is already weighted — the caller just adds it to its loss.
    density = jnp.mean(mask1, axis=(0, 1))
    density_proxy = jnp.mean(probs, axis=(0, 1))
    aux = cfg.aux_loss_weight * E * jnp.sum(density * density_proxy)
    if cfg.z_loss_weight > 0.0:
        z = jax.scipy.special.logsumexp(logits, axis=-1)
        aux = aux + cfg.z_loss_weight * jnp.mean(z * z)

    dt = x.dtype
    dispatch = dispatch.astype(dt)
    combine = combine.astype(dt)
    # dispatch: tokens → per-expert capacity slots.  With the expert dim
    # sharded over ``data`` and the batch dim likewise, GSPMD lowers the
    # resharding below to an all_to_all over the data axis — the dispatch
    # communication DeepSpeed-MoE issues explicitly.
    ein = jnp.einsum("gsec,gsd->egcd", dispatch, x)
    ein = _constrain(ein, P(DATA_AXIS, None, None, None))
    h = jnp.einsum("egcd,edf->egcf", ein, mp["wi"].astype(dt))
    h = h + mp["bi"].astype(dt)[:, None, None, :]
    h = jax.nn.gelu(h, approximate=True)
    eo = jnp.einsum("egcf,efd->egcd", h, mp["wo"].astype(dt))
    eo = eo + mp["bo"].astype(dt)[:, None, None, :]
    eo = _constrain(eo, P(DATA_AXIS, None, None, None))
    y = jnp.einsum("gsec,egcd->gsd", combine, eo)             # combine a2a
    y = _constrain(y, P(DATA_AXIS, None, None))
    return y, aux
