"""Mixture-of-Experts FFN with expert parallelism over the ``data`` axis.

The reference (DeepSpeed v0.3.2) predates DeepSpeed-MoE — SURVEY.md §2.4
records expert parallelism as absent — so, like sequence parallelism
(parallel/sequence.py), this fills the modern feature slot the way the
framework's later versions do, designed TPU-first rather than ported:

  - routing, dispatch, and combine are dense one-hot einsums (the GShard
    formulation): no scatter/gather, no dynamic shapes — every op tiles
    onto the MXU and the dispatch/combine "communication" lowers to XLA
    all_to_alls when the expert dim is sharded;
  - expert parallelism is a *placement decision*, exactly like ZeRO and
    Megatron TP elsewhere in this codebase: expert-stacked weights
    ``[E, d, f]`` declare ``P('data', ...)`` on the expert dim
    (``moe_param_specs``) and GSPMD partitions the expert compute over the
    data-parallel group — the same ep⊆dp mapping DeepSpeed-MoE uses for
    its expert groups;
  - expert weights can ALSO shard their feature dim over ``model``
    (column/row-parallel experts), composing EP × TP in one spec;
  - capacity is static (``ceil(top_k · cf · tokens / E)``): overflow
    tokens are dropped (their combine weight is zero) and flow through
    the residual connection, the standard Switch/GShard contract.

Gating runs in fp32 regardless of compute dtype; the auxiliary
load-balancing loss (Switch: ``E · Σ_e fraction_routed_e · mean_prob_e``)
and the router z-loss are returned for the model to fold into its total
loss.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int
    d_model: int
    d_ff: int
    top_k: int = 1                    # 1 = Switch, 2 = GShard
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    aux_loss_weight: float = 1e-2
    z_loss_weight: float = 0.0
    router_jitter: float = 0.0        # multiplicative input noise, train only
    dispatch_impl: str = "einsum"     # "einsum" (one-hot, MXU) | "scatter"

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {self.top_k}")
        if self.dispatch_impl not in ("einsum", "scatter"):
            raise ValueError(
                f"dispatch_impl must be 'einsum' or 'scatter', got "
                f"{self.dispatch_impl!r}")
        if self.n_experts < 1:
            raise ValueError(f"n_experts must be >= 1, got {self.n_experts}")
        if self.n_experts < self.top_k:
            # top_k > n_experts would double-assign tokens to expert 0 with
            # half gates (probs2 is all-zero after masking, argmax re-picks
            # 0) — a silent half-weighting, not a meaningful routing.
            raise ValueError(
                f"n_experts ({self.n_experts}) must be >= top_k "
                f"({self.top_k})")

    def capacity(self, tokens_per_group: int, train: bool) -> int:
        cf = self.capacity_factor if train else self.eval_capacity_factor
        c = math.ceil(self.top_k * cf * tokens_per_group / self.n_experts)
        return max(1, min(tokens_per_group, c))


def init_moe_params(rng, cfg: MoEConfig, std: float = 0.02,
                    out_std: Optional[float] = None) -> Dict[str, Any]:
    """Expert-stacked FFN weights + router. ``out_std`` scales the output
    projection (models pass their residual-scaled std)."""
    E, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff
    k_g, k_i, k_o = jax.random.split(rng, 3)
    return {
        "wg": jax.random.normal(k_g, (d, E), jnp.float32) * std,
        "wi": jax.random.normal(k_i, (E, d, f), jnp.float32) * std,
        "bi": jnp.zeros((E, f), jnp.float32),
        "wo": jax.random.normal(k_o, (E, f, d), jnp.float32)
        * (std if out_std is None else out_std),
        "bo": jnp.zeros((E, d), jnp.float32),
    }


def moe_param_specs(ep_axis: str = DATA_AXIS,
                    tp_axis: Optional[str] = MODEL_AXIS,
                    stacked: bool = False) -> Dict[str, P]:
    """Placement: expert dim over ``ep_axis`` (expert parallelism), hidden
    feature dim over ``tp_axis`` (column/row-parallel experts).  With
    ``stacked`` the specs gain a leading ``None`` for a layer axis."""
    lead = (None,) if stacked else ()
    tp = tp_axis  # None disables the TP split
    return {
        "wg": P(*lead),                        # tiny; replicate
        "wi": P(*lead, ep_axis, None, tp),     # column parallel
        "bi": P(*lead, ep_axis, tp),
        "wo": P(*lead, ep_axis, tp, None),     # row parallel
        "bo": P(*lead, ep_axis, None),
    }


def _constrain(x, spec: P):
    """Sharding constraint that is a no-op when no mesh context is set
    (pure single-device unit tests) — the engine always runs its step
    under ``jax.set_mesh``, where the constraint binds."""
    mesh = jax.sharding.get_abstract_mesh()
    # Direct attribute access on purpose (mirrors gpt2.py's sp guard): if
    # jax renames manual_axes this must break loudly, not silently start
    # constraining inside manual computations.
    if mesh is None or not mesh.shape or mesh.manual_axes:
        return x
    return jax.lax.with_sharding_constraint(x, P(*spec))


def _route(probs, top_k: int):
    """Routing choices from fp32 router probs [G,S,E]:
    ``[(idx [G,S], gate [G,S], mask [G,S,E]), ...]`` per choice.  The
    SINGLE source of the gate math for both dispatch implementations —
    GShard top-2 renormalizes the two gates against each other."""
    E = probs.shape[-1]
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=probs.dtype)
    g1 = jnp.sum(probs * mask1, axis=-1)
    if top_k == 1:
        return [(idx1, g1, mask1)]
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=probs.dtype)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    return [(idx1, g1 / denom, mask1), (idx2, g2 / denom, mask2)]


def _choice_positions(mask, base):
    """Per-(token, expert) arrival position [G,S,E] for one choice's
    one-hot mask; ``base`` [G,1,E] queues this choice behind all earlier
    choices' assignments (GShard order).  -1 at non-selected entries.
    The single source of the queueing math for both dispatch impls."""
    return (jnp.cumsum(mask, axis=1) + base) * mask - 1.0


def _einsum_dispatch(choices, capacity: int):
    """(dispatch [G,S,E,C] {0,1}, combine [G,S,E,C]) from the shared
    routing choices — the dense one-hot formulation (every op tiles onto
    the MXU; no scatter)."""
    dispatch = combine = None
    base = jnp.zeros_like(choices[0][2][:, :1, :])
    for _idx, gate, mask in choices:
        pos = _choice_positions(mask, base)
        base = base + jnp.sum(mask, axis=1, keepdims=True)
        keep = (pos >= 0) & (pos < capacity)
        d = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                           dtype=mask.dtype) * (mask * keep)[..., None]
        c = gate[..., None, None] * d
        dispatch = d if dispatch is None else dispatch + d
        combine = c if combine is None else combine + c
    return dispatch, combine


def _token_slots(mask: jnp.ndarray, base: jnp.ndarray) -> jnp.ndarray:
    """Per-token capacity slot from a one-hot choice mask [G,S,E]: the
    token's position in its expert's arrival order (``base`` [G,1,E]
    offsets second choices behind all first choices, GShard order).
    Returns [G,S] fp32; the slot at the selected expert is >= 0, so a
    max over E extracts it."""
    return jnp.max(_choice_positions(mask, base), axis=-1)


def _scatter_moe(cfg: MoEConfig, mp: Dict[str, Any], x: jnp.ndarray,
                 probs: jnp.ndarray, capacity: int, choices) -> jnp.ndarray:
    """Scatter/gather dispatch: O(S·d) data movement per token instead of
    the one-hot einsum's O(S·C·E·d) = O(S²·cf·k·d) MXU work per group.
    The einsum formulation's dispatch cost is independent of E (capacity
    shrinks as 1/E) but quadratic in tokens-per-group — at long S the
    dispatch einsum rivals the expert FFN itself (see bench_moe.py), which
    is when this path wins.  Slots are unique by construction (disjoint
    per-expert ranges; second choices queue behind all first choices), so
    scatter-add never actually collides."""
    G, S, d = x.shape
    E, C = cfg.n_experts, capacity
    dt = x.dtype
    base = jnp.zeros((G, 1, E), probs.dtype)
    slots = []
    for (idx, gate, mask) in choices:
        pos = _token_slots(mask, base)                       # [G,S]
        base = base + jnp.sum(mask, axis=1, keepdims=True)
        keep = pos < C
        slot = idx * C + jnp.minimum(pos, C - 1.0).astype(jnp.int32)
        slots.append((slot, keep, gate))

    group_off = (jnp.arange(G, dtype=jnp.int32) * (E * C))[:, None]
    xf = x.reshape(G * S, d)
    buf = jnp.zeros((G * E * C, d), dt)
    for slot, keep, _gate in slots:
        flat = (slot + group_off).reshape(-1)
        buf = buf.at[flat].add(xf * keep.reshape(-1, 1).astype(dt))

    ein = buf.reshape(G, E, C, d).transpose(1, 0, 2, 3)      # [E,G,C,d]
    ein = _constrain(ein, P(DATA_AXIS, None, None, None))
    h = jnp.einsum("egcd,edf->egcf", ein, mp["wi"].astype(dt))
    h = h + mp["bi"].astype(dt)[:, None, None, :]
    h = jax.nn.gelu(h, approximate=True)
    eo = jnp.einsum("egcf,efd->egcd", h, mp["wo"].astype(dt))
    eo = eo + mp["bo"].astype(dt)[:, None, None, :]
    eo = _constrain(eo, P(DATA_AXIS, None, None, None))
    eo_g = eo.transpose(1, 0, 2, 3).reshape(G, E * C, d)     # [G,E*C,d]
    y = jnp.zeros_like(x)
    for slot, keep, gate in slots:
        picked = jnp.take_along_axis(eo_g, slot[..., None], axis=1)
        y = y + picked * (gate * keep).astype(dt)[..., None]
    return _constrain(y, P(DATA_AXIS, None, None))


def moe_ffn(cfg: MoEConfig, mp: Dict[str, Any], x: jnp.ndarray, rng,
            train: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [G, S, d] → (y [G, S, d], weighted aux-loss scalar fp32).

    Dropped (over-capacity) tokens produce y=0 at their positions; the
    caller's residual connection carries them through unchanged.
    """
    G, S, d = x.shape
    E = cfg.n_experts
    C = cfg.capacity(S, train)
    x_gate = x.astype(jnp.float32)
    if train and cfg.router_jitter > 0.0:
        eps = cfg.router_jitter
        x_gate = x_gate * jax.random.uniform(
            jax.random.fold_in(rng, 11), x_gate.shape, jnp.float32,
            1.0 - eps, 1.0 + eps)
    logits = x_gate @ mp["wg"]                                # [G,S,E] fp32
    probs = jax.nn.softmax(logits, axis=-1)

    choices = _route(probs, cfg.top_k)
    mask1 = choices[0][2]
    if cfg.dispatch_impl == "einsum":
        dispatch, combine = _einsum_dispatch(choices, C)

    # Switch load-balance loss: E · Σ_e (fraction of tokens routed to e) ·
    # (mean router prob of e); 1.0 at perfect balance.  The returned term
    # is already weighted — the caller just adds it to its loss.
    density = jnp.mean(mask1, axis=(0, 1))
    density_proxy = jnp.mean(probs, axis=(0, 1))
    aux = cfg.aux_loss_weight * E * jnp.sum(density * density_proxy)
    if cfg.z_loss_weight > 0.0:
        z = jax.scipy.special.logsumexp(logits, axis=-1)
        aux = aux + cfg.z_loss_weight * jnp.mean(z * z)

    if cfg.dispatch_impl == "scatter":
        return _scatter_moe(cfg, mp, x, probs, C, choices), aux

    dt = x.dtype
    dispatch = dispatch.astype(dt)
    combine = combine.astype(dt)
    # dispatch: tokens → per-expert capacity slots.  With the expert dim
    # sharded over ``data`` and the batch dim likewise, GSPMD lowers the
    # resharding below to an all_to_all over the data axis — the dispatch
    # communication DeepSpeed-MoE issues explicitly.
    ein = jnp.einsum("gsec,gsd->egcd", dispatch, x)
    ein = _constrain(ein, P(DATA_AXIS, None, None, None))
    h = jnp.einsum("egcd,edf->egcf", ein, mp["wi"].astype(dt))
    h = h + mp["bi"].astype(dt)[:, None, None, :]
    h = jax.nn.gelu(h, approximate=True)
    eo = jnp.einsum("egcf,efd->egcd", h, mp["wo"].astype(dt))
    eo = eo + mp["bo"].astype(dt)[:, None, None, :]
    eo = _constrain(eo, P(DATA_AXIS, None, None, None))
    y = jnp.einsum("gsec,egcd->gsd", combine, eo)             # combine a2a
    y = _constrain(y, P(DATA_AXIS, None, None))
    return y, aux
