from .layer import (MoEConfig, init_moe_params, moe_param_specs, moe_ffn)

__all__ = ["MoEConfig", "init_moe_params", "moe_param_specs", "moe_ffn"]
