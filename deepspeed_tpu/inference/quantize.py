"""Int8 quantization for the serving plane (docs/serving.md,
"quantized serving").

Two independent arms behind ``serving.quantization``:

**Weights** (LLM.int8, Dettmers et al. 2022 — PAPERS.md): one-shot
post-load symmetric per-OUTPUT-CHANNEL absmax quantization of the
GPT-2 matmul weights (attn qkv/out, MLP fc/proj).  ``scale[c] =
absmax(w[:, c]) / 127`` over the contraction (input-feature) axis, so
dequant fuses into the serving matmuls as ``(x · w_int8) * scale`` —
one multiply per output element, never a dequantized weight matrix in
HBM.  The fp master copy stays on the host; device memory holds int8
weights + fp32 scale rows, so params HBM ~ halves vs fp16 (~quarters
vs the CPU oracle's fp32).  Embeddings, layer norms and biases stay in
the master dtype: they are gather/elementwise consumers, small, and
the tied-embedding logits matmul wants the full-precision table.

**KV rows** (KVQuant / KIVI per-head scaling, PAPERS.md): the paged
pool stores int8 K/V rows with a per-(page, head, row) fp32 scale —
``quantize_rows`` at write time inside the compiled programs,
dequantized fused into the decode kernels.  Per-ROW (per stored token,
per head) rather than one scalar per (page, head): decode appends one
row at a time into a live page, and a page-scalar scale would either
clip rows hotter than the page's first write or re-quantize the whole
page per append (unbounded double-rounding drift).  Per-row keeps
every write's error bounded by ``scale/2`` forever — the numeric-
bounds contract tests/test_quant_serve.py pins.

Everything here is pure jnp: ``quantize_rows`` runs on-trace inside
the serving programs; the weight path runs once at engine build.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

#: the GPT-2 block matmul weights the int8 arm covers; every one
#: stores input-features on axis 1 (after the stacked layer axis), so
#: the per-output-channel absmax always reduces axis 1.
QUANT_WEIGHT_KEYS = ("qkv_w", "out_w", "fc_w", "proj_w")
_CONTRACT_AXIS = 1
SCALE_SUFFIX = "_scale"


def quantize_channels(w: jnp.ndarray,
                      axis: int = _CONTRACT_AXIS
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-output-channel int8: reduce ``axis`` (the
    contraction axis, keepdims so the scale broadcasts back), scale =
    absmax/127 (all-zero channels get scale 1.0 — a harmless identity),
    values round-to-nearest into [-127, 127].  ``|q*scale - w| <=
    scale/2`` exactly: the absmax itself maps to ±127 with no clip."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis,
                     keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_channels(q: jnp.ndarray,
                        scale: jnp.ndarray) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale


def quantize_rows(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row (last-axis) symmetric int8 for KV rows: ``x [..., Dh]``
    -> ``(q int8 [..., Dh], scale fp32 [...])``.  On-trace (called
    inside the compiled write paths); all-zero rows get scale 1.0 so
    the scratch page stays exact zeros."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_rows(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_rows`: ``q [..., Dh] * scale [...]``
    broadcast over the row — the ONE dequant rule every consumer (the
    dense reference, the fused kernels, the prefill gather arm)
    shares."""
    return q.astype(jnp.float32) * scale[..., None]


def quantize_gpt2_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """One-shot post-load quantization of a GPT-2 param tree: each
    block matmul weight becomes int8 with an ``<name>_scale`` fp32
    sibling (keepdims over the contraction axis, so the serving
    matmuls multiply it straight onto their output).  Input tree is
    never mutated; non-covered leaves pass through unchanged.  Works
    on any GPT-2-family tree whose ``blocks`` stack layers on axis 0
    (the target and the speculative draft alike)."""
    blocks = dict(params["blocks"])
    for name in QUANT_WEIGHT_KEYS:
        q, scale = quantize_channels(blocks[name])
        blocks[name] = q
        blocks[name + SCALE_SUFFIX] = scale
    out = dict(params)
    out["blocks"] = blocks
    return out


def quantized_partition_specs(pspecs: Dict[str, Any]) -> Dict[str, Any]:
    """Partition specs matching :func:`quantize_gpt2_params`: each
    scale inherits its weight's spec with the contracted (now size-1)
    axis unsharded — the output-channel shard stays aligned with the
    Megatron column split, so a TP shard holds exactly the scales of
    the channels it computes."""
    blocks = dict(pspecs["blocks"])
    for name in QUANT_WEIGHT_KEYS:
        axes = list(tuple(blocks[name]))
        while len(axes) <= _CONTRACT_AXIS:
            axes.append(None)
        axes[_CONTRACT_AXIS] = None
        blocks[name + SCALE_SUFFIX] = P(*axes)
    out = dict(pspecs)
    out["blocks"] = blocks
    return out


def param_nbytes(tree) -> int:
    """Total bytes of every leaf — the ``serve_param_bytes`` source
    (device-resident logical bytes: int8 leaves count 1 byte/elem, the
    whole point of the weights arm)."""
    return int(sum(x.nbytes for x in jax.tree.leaves(tree)))
