"""Static-shape continuous batching: the host-side slot scheduler,
page allocator, and prefix cache.

Orca-style iteration-level scheduling (PAPERS.md) re-expressed in the
repo's static-shape idiom: the device never sees a batch-size change.
A fixed pool of ``slots`` decodes every tick; requests are ADMITTED
into free slots (a prefill writes their K/V rows in place) and EVICTED
the moment they finish (EOS / max_new_tokens / KV capacity), so a new
request starts decoding on the very next tick — no waiting for the
batch to drain, which is the whole continuous-batching win
(bench_serve.py measures it).

Eviction is pure host bookkeeping: the slot's ``lengths`` entry is
overwritten by the next admission and the decode program masks the
stale rows meanwhile.  The device-side mirror of this file is the
``active`` mask the engine passes into the one compiled decode program.

Paged mode (``serving.page_len > 0``, docs/serving.md) adds two more
host-only structures mirroring vLLM's block manager and SGLang's radix
cache (PAPERS.md):

  :class:`PagePool`     refcounted free-list allocator over the flat
                        device page pool (page 0 reserved as scratch).
                        Deque-backed — O(1) alloc/free at any pool size.
  :class:`PrefixCache`  chain-hashed shared prompt prefixes: full pages
                        key by a running digest, the last partial page
                        by its literal tokens under its parent digest.
                        Entries hold a pool ref; leaf-LRU eviction under
                        pool pressure, copy-on-write when a hitter must
                        append into a shared partial page.

Everything here is engine-thread-confined; the request queue in front
(a stages Channel) is the concurrent boundary.
"""
from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle record."""
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    submit_t: float = 0.0
    #: generated token ids (the first comes from the prefill logits)
    tokens: List[int] = dataclasses.field(default_factory=list)
    #: wall seconds per generated token (first = time-to-first-token)
    token_times: List[float] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None
    error: Optional[BaseException] = None
    slot: Optional[int] = None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    #: host-side decode bookkeeping (engine-internal)
    last_token: int = 0
    last_t: float = 0.0
    kv_len: int = 0
    #: causal trace (docs/observability.md, engine-internal): the
    #: request's TraceContext plus its open root/queue-wait span handles
    ctx: Optional[object] = None
    span: Optional[object] = None
    queue_span: Optional[object] = None
    #: latency attribution stamps: admission time (queue_wait ends) and
    #: the prefill's wall time — queue/prefill/decode components of the
    #: per-request completion record
    admit_t: float = 0.0
    prefill_s: float = 0.0
    #: paged mode (engine-internal): the slot's live page ids in table
    #: order, the prompt prefix length served from shared pages, and
    #: how many prompt tokens the prefill actually computed (the delta)
    pages: Optional[List[int]] = None
    shared_len: int = 0
    computed_len: int = 0
    #: speculative decoding (engine-internal, serving.speculate_k > 0):
    #: accepted draft tokens per verify pass — the per-request record
    #: of the uneven per-slot progress the masked slot machinery
    #: absorbs (docs/serving.md "speculative decoding")
    spec_accepted: List[int] = dataclasses.field(default_factory=list)
    #: chunked prefill (serving.prefill_chunk_len > 0): while True the
    #: slot is mid-prefill — decode ticks mask it out and step() feeds
    #: it one chunk at a time; chunk_pos = prompt tokens prefilled so
    #: far past shared_len
    prefilling: bool = False
    chunk_pos: int = 0
    #: KV-migration handoff (disaggregated fleet): finish without
    #: releasing the slot's pages — the replica loop exports them over
    #: the wire, then drops them explicitly
    detach_kv: bool = False
    #: tenant adapter id (0 = base model, no LoRA delta).  The engine
    #: resolves this to an HBM pool slot at admission and parks on
    #: pool-dry exactly like a pages-dry admission
    adapter_id: int = 0
    #: resolved HBM adapter-pool slot (0 = the reserved zero adapter);
    #: engine-owned, valid only while the request holds a batch slot
    adapter_slot: int = 0

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; raises its error if it
        failed (typed propagation — the original exception)."""
        if not self.done.wait(timeout=timeout):
            raise TimeoutError(
                f"request {self.rid} not finished after {timeout}s")
        if self.error is not None:
            raise self.error
        return list(self.tokens)


class SlotScheduler:
    """Free-list + active map over the fixed slot pool.  Host-only and
    engine-thread-confined; the request queue in front of it (a stages
    Channel) is the concurrent boundary."""

    def __init__(self, slots: int):
        self.slots = int(slots)
        # deque, not list: pop(0) on a list shifts the whole free list —
        # O(n) per admission, real money at fleet-scale pools
        self.free: deque = deque(range(self.slots))
        self.active: Dict[int, Request] = {}

    def has_free(self) -> bool:
        return bool(self.free)

    def admit(self, req: Request, now: Optional[float] = None) -> int:
        slot = self.free.popleft()
        req.slot = slot
        req.last_t = now if now is not None else time.perf_counter()
        self.active[slot] = req
        return slot

    def release(self, slot: int, reason: str) -> Request:
        req = self.active.pop(slot)
        self.free.append(slot)
        req.finish_reason = reason
        req.slot = None
        return req

    def finish_reason(self, req: Request, token: int,
                      max_len: int) -> Optional[str]:
        """Why this just-emitted token ends the request (None = keep
        decoding): EOS, the per-request generation budget, or the
        slot's KV capacity (the static-shape hard stop)."""
        if req.eos_id is not None and token == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_new_tokens:
            return "length"
        if req.kv_len >= max_len:
            return "kv_capacity"
        return None


# ---------------------------------------------------------------------------
# paged mode: the refcounted page allocator
# ---------------------------------------------------------------------------


#: the reserved scratch page — masked (inactive-slot) writes of the
#: decode/prefill programs land here, so write conflicts can only be
#: no-op-vs-no-op.  Never allocated, never freed, always a valid index.
SCRATCH_PAGE = 0


class PagePool:
    """Host-side free-list allocator over the flat device page pool.

    Pages are plain int ids into the ``[L, P, H, page_len, Dh]`` pool
    arrays; a page is storage for ``page_len`` KV rows of every layer.
    Refcounts make sharing safe: a page is held by the slot(s) whose
    page tables reference it plus (optionally) a :class:`PrefixCache`
    entry, and returns to the free deque only when the last holder
    derefs.  O(1) alloc/free — the free list is a deque, the same
    satellite as the slot scheduler's."""

    def __init__(self, pages: int):
        if pages < 2:
            raise ValueError(
                f"PagePool needs >= 2 pages (page {SCRATCH_PAGE} is the "
                f"reserved scratch page), got {pages}")
        self.pages = int(pages)
        self.free: deque = deque(range(1, self.pages))
        self.refs: Dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self.free)

    @property
    def used_count(self) -> int:
        """Allocated pages (excludes the scratch page)."""
        return self.pages - 1 - len(self.free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages with refcount 1 each, or None (and no
        side effects) when the pool can't satisfy the request — the
        caller's backpressure/eviction point."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self.free):
            return None
        out = [self.free.popleft() for _ in range(n)]
        for p in out:
            self.refs[p] = 1
        return out

    def ref(self, page: int) -> None:
        if page == SCRATCH_PAGE:
            raise ValueError("the scratch page is never refcounted")
        self.refs[page] += 1

    def deref(self, page: int) -> None:
        """Drop one hold; the last hold frees the page back to the
        deque.  Over-deref is a bookkeeping bug and raises."""
        if page not in self.refs:
            raise AssertionError(
                f"page {page} deref'd below zero (double free)")
        n = self.refs[page] - 1
        if n == 0:
            del self.refs[page]
            self.free.append(page)
        else:
            self.refs[page] = n


# ---------------------------------------------------------------------------
# prefix reuse: chain-hashed shared pages (RadixAttention, PAPERS.md)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _FullEntry:
    """A full shared page: ``page_len`` prompt tokens, keyed by the
    running chain digest (parent digest + this page's tokens), so a
    match at depth i implies every shallower page matched too."""
    page: int
    parent: str
    children: int = 0
    last_hit: int = 0


@dataclasses.dataclass
class _PartialEntry:
    """The last PARTIAL page of a cached prompt: ``tokens`` literal
    rows [0, m) of ``page``, keyed under the parent full-page digest.
    Always a leaf — a hitter that extends it copy-on-writes first.
    Rows >= m of the page belong to the registering request's later
    tokens/appends and are never read through this entry."""
    tokens: Tuple[int, ...]
    page: int
    parent: str
    last_hit: int = 0


class PrefixCache:
    """Shared prompt prefixes over pool pages.

    Only ``prompt[:-1]`` is cacheable — the last prompt token must
    always be computed so prefill has logits to emit the first
    generated token from (the vLLM rule).  Full pages chain-hash; the
    partial tail keys by its literal tokens under the parent digest.
    Every entry holds one pool ref on its page; ``evict()`` walks
    leaf-first LRU (an inner full page never outlives a cached child
    that chains through it) and is the allocator's pressure valve.
    """

    def __init__(self, page_len: int, pool: PagePool):
        self.page_len = int(page_len)
        self.pool = pool
        self.full: Dict[str, _FullEntry] = {}
        self.partials: Dict[str, Dict[Tuple[int, ...], _PartialEntry]] = {}
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.cow = 0
        self._clock = 0

    # -- internals -------------------------------------------------------
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _digest(parent: str, tokens: Sequence[int]) -> str:
        h = hashlib.sha1(parent.encode("ascii"))
        h.update(b"|")
        h.update(",".join(str(int(t)) for t in tokens).encode("ascii"))
        return h.hexdigest()

    @property
    def entries(self) -> int:
        return len(self.full) + sum(len(d) for d in self.partials.values())

    # -- lookup ----------------------------------------------------------
    def match(self, prompt: Sequence[int],
              namespace: str = "") -> Tuple[int, List[int], bool]:
        """Longest cached prefix of ``prompt`` (never the whole prompt:
        at least one token is left for the delta prefill).

        ``namespace`` partitions the cache: digests chain from it as the
        root parent, so two tenants with identical prompts but different
        adapters can never share KV pages (the LoRA delta makes their
        caches semantically different).  ``""`` keeps digests bitwise
        identical to the un-namespaced cache.

        Returns ``(shared_len, pages, cow)`` with one pool ref taken on
        every returned page (the caller owns them now — roll back with
        ``release`` if admission fails).  ``pages[i]`` covers positions
        ``[i*page_len, (i+1)*page_len)``; when ``cow`` is True the last
        entry is a shared PARTIAL page the caller must copy-on-write
        before its first append (``shared_len`` ends inside it)."""
        limit = len(prompt) - 1
        parent = namespace
        pages: List[int] = []
        pos = 0
        while pos + self.page_len <= limit:
            d = self._digest(parent, prompt[pos:pos + self.page_len])
            e = self.full.get(d)
            if e is None:
                break
            e.last_hit = self._tick()
            self.pool.ref(e.page)
            pages.append(e.page)
            parent = d
            pos += self.page_len
        cow = False
        best: Optional[_PartialEntry] = None
        remaining = prompt[pos:]
        for toks, pe in (self.partials.get(parent) or {}).items():
            m = len(toks)
            # m <= limit - pos keeps shared_len <= len(prompt)-1
            if m <= limit - pos and tuple(remaining[:m]) == toks \
                    and (best is None or m > len(best.tokens)):
                best = pe
        if best is not None:
            best.last_hit = self._tick()
            self.pool.ref(best.page)
            pages.append(best.page)
            pos += len(best.tokens)
            cow = True
        # stats are counted per ADMISSION (note_admission), not per
        # match call: a backpressure-parked request re-matches every
        # tick and must not inflate the hit ratio/token scalars
        return pos, pages, cow

    def note_admission(self, shared_len: int) -> None:
        """Count one successful admission's prefix outcome — the
        source of the ``serve_prefix_*`` flush scalars."""
        if shared_len > 0:
            self.hits += 1
            self.hit_tokens += shared_len
        else:
            self.misses += 1

    def release(self, pages: Sequence[int]) -> None:
        """Roll back the refs a failed admission took via ``match``."""
        for p in pages:
            self.pool.deref(p)

    # -- registration ----------------------------------------------------
    def insert(self, prompt: Sequence[int],
               pages: Sequence[int], namespace: str = "") -> int:
        """Register a just-prefilled prompt's pages: full pages of
        ``prompt[:-1]`` chain in as :class:`_FullEntry`, a nonempty
        partial tail as :class:`_PartialEntry`.  Pages already cached
        (the request's own prefix hit) are skipped; each NEW entry
        takes one pool ref on its page.  ``namespace`` must match the
        one used at :meth:`match` time.  Returns entries added."""
        limit = len(prompt) - 1
        parent = namespace
        added = 0
        pos = 0
        i = 0
        while pos + self.page_len <= limit:
            d = self._digest(parent, prompt[pos:pos + self.page_len])
            e = self.full.get(d)
            if e is None:
                self.pool.ref(pages[i])
                self.full[d] = _FullEntry(page=pages[i], parent=parent,
                                          last_hit=self._tick())
                if parent in self.full:
                    self.full[parent].children += 1
                added += 1
            parent = d
            pos += self.page_len
            i += 1
        tail = tuple(int(t) for t in prompt[pos:limit])
        if tail:
            bucket = self.partials.setdefault(parent, {})
            if tail not in bucket:
                self.pool.ref(pages[i])
                bucket[tail] = _PartialEntry(tokens=tail, page=pages[i],
                                             parent=parent,
                                             last_hit=self._tick())
                if parent in self.full:
                    self.full[parent].children += 1
                added += 1
        return added

    # -- eviction (the allocator's pressure valve) -----------------------
    def _evictable(self):
        for parent, bucket in self.partials.items():
            for toks, pe in bucket.items():
                yield pe.last_hit, ("partial", parent, toks)
        for d, fe in self.full.items():
            if fe.children == 0 and d not in self.partials:
                yield fe.last_hit, ("full", d, None)

    def drop_leaf(self, kind: str, key: str,
                  sub: Optional[Tuple[int, ...]]) -> int:
        """Remove one LEAF entry (a ``_evictable`` candidate) and deref
        its page; returns the page id.  The one dict-surgery path both
        eviction and the KV tier's park (inference/kv_tier.py) go
        through — the tier exports + CRC-stamps the page's bytes BEFORE
        calling this, so the pool ref is only released once the host
        copy is durable."""
        if kind == "partial":
            pe = self.partials[key].pop(sub)
            if not self.partials[key]:
                del self.partials[key]
            if pe.parent in self.full:
                self.full[pe.parent].children -= 1
            self.pool.deref(pe.page)
            return pe.page
        fe = self.full.pop(key)
        if fe.parent in self.full:
            self.full[fe.parent].children -= 1
        self.pool.deref(fe.page)
        return fe.page

    def evict(self, need_free: int) -> int:
        """Drop least-recently-hit LEAF entries until the pool's free
        count reaches ``need_free`` (or nothing evictable remains).
        Dropping an entry derefs its page — the page is actually freed
        only if no live slot still reads it.  Returns entries evicted.
        Leaf-first keeps every cached chain reachable: an inner page is
        only evictable once nothing chains through it."""
        evicted = 0
        while self.pool.free_count < need_free:
            # min(), not sorted(): this runs on the admission/append
            # hot path — O(E) per freed page, never a full resort
            cand = min(self._evictable(), default=None)
            if cand is None:
                break
            _, (kind, key, sub) = cand
            self.drop_leaf(kind, key, sub)
            evicted += 1
        return evicted

    def clear(self) -> int:
        """Drop every entry (engine shutdown): deref all cached pages."""
        n = 0
        for fe in self.full.values():
            self.pool.deref(fe.page)
            n += 1
        for bucket in self.partials.values():
            for pe in bucket.values():
                self.pool.deref(pe.page)
                n += 1
        self.full.clear()
        self.partials.clear()
        return n
