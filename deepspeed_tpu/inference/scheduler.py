"""Static-shape continuous batching: the host-side slot scheduler.

Orca-style iteration-level scheduling (PAPERS.md) re-expressed in the
repo's static-shape idiom: the device never sees a batch-size change.
A fixed pool of ``slots`` decodes every tick; requests are ADMITTED
into free slots (a prefill writes their K/V rows in place) and EVICTED
the moment they finish (EOS / max_new_tokens / KV capacity), so a new
request starts decoding on the very next tick — no waiting for the
batch to drain, which is the whole continuous-batching win
(bench_serve.py measures it).

Eviction is pure host bookkeeping: the slot's ``lengths`` entry is
overwritten by the next admission and the decode program masks the
stale rows meanwhile.  The device-side mirror of this file is the
``active`` mask the engine passes into the one compiled decode program.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle record."""
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    submit_t: float = 0.0
    #: generated token ids (the first comes from the prefill logits)
    tokens: List[int] = dataclasses.field(default_factory=list)
    #: wall seconds per generated token (first = time-to-first-token)
    token_times: List[float] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None
    error: Optional[BaseException] = None
    slot: Optional[int] = None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    #: host-side decode bookkeeping (engine-internal)
    last_token: int = 0
    last_t: float = 0.0
    kv_len: int = 0
    #: causal trace (docs/observability.md, engine-internal): the
    #: request's TraceContext plus its open root/queue-wait span handles
    ctx: Optional[object] = None
    span: Optional[object] = None
    queue_span: Optional[object] = None
    #: latency attribution stamps: admission time (queue_wait ends) and
    #: the prefill's wall time — queue/prefill/decode components of the
    #: per-request completion record
    admit_t: float = 0.0
    prefill_s: float = 0.0

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request finishes; raises its error if it
        failed (typed propagation — the original exception)."""
        if not self.done.wait(timeout=timeout):
            raise TimeoutError(
                f"request {self.rid} not finished after {timeout}s")
        if self.error is not None:
            raise self.error
        return list(self.tokens)


class SlotScheduler:
    """Free-list + active map over the fixed slot pool.  Host-only and
    engine-thread-confined; the request queue in front of it (a stages
    Channel) is the concurrent boundary."""

    def __init__(self, slots: int):
        self.slots = int(slots)
        self.free: List[int] = list(range(self.slots))
        self.active: Dict[int, Request] = {}

    def has_free(self) -> bool:
        return bool(self.free)

    def admit(self, req: Request, now: Optional[float] = None) -> int:
        slot = self.free.pop(0)
        req.slot = slot
        req.last_t = now if now is not None else time.perf_counter()
        self.active[slot] = req
        return slot

    def release(self, slot: int, reason: str) -> Request:
        req = self.active.pop(slot)
        self.free.append(slot)
        req.finish_reason = reason
        req.slot = None
        return req

    def finish_reason(self, req: Request, token: int,
                      max_len: int) -> Optional[str]:
        """Why this just-emitted token ends the request (None = keep
        decoding): EOS, the per-request generation budget, or the
        slot's KV capacity (the static-shape hard stop)."""
        if req.eos_id is not None and token == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_new_tokens:
            return "length"
        if req.kv_len >= max_len:
            return "kv_capacity"
        return None
