"""Multi-tenant LoRA adapter plane: registry + paged HBM residency pool.

S-LoRA / Punica (PAPERS.md) re-expressed in this repo's idioms
(docs/serving.md "multi-tenant serving"): ONE base model serves
thousands of per-tenant low-rank adapters.  The adapters live in a
host-side registry; a small pool of HBM slots holds the hot ones, and
the compiled decode/prefill/verify programs gather each request's
adapter by a TRACED int32 slot table — the PR 11 scalar-prefetch
indirection applied to weights — so tenant mixes ride the SAME
compiled tick (``recompiles_total{program=decode_step}`` == 0).

The residency pool is managed exactly like KV pages
(:class:`~deepspeed_tpu.inference.scheduler.PagePool`): refcounted
slots, LRU eviction of cold tenants, park-on-dry admission.  Slot 0 is
the reserved ZERO adapter (all-zero A/B — the no-tenant arm computes a
mathematically-zero delta through the same gather), so requests with
and without adapters share one program too.

The cold path — host weights -> HBM slot — is one unit of work under a
``Stage("adapter_fetch")`` (runtime/stages.py, docs/stages.md): a
flaky fetch retries against the stage budget, exhaustion degrades to
the synchronous copy with one loud warning, and
``DS_STAGE_FAULT=adapter_fetch:fetch:<n>[+]`` chaos-tests the whole
path without touching the pool's bookkeeping.

Everything here is engine-thread-confined (the request Channel in
front of the engine is the concurrent boundary), mirroring
scheduler.py's contract.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime.stages import Stage

__all__ = [
    "LORA_TARGET_SHAPES", "AdapterRegistry", "AdapterPool",
    "adapter_param_shapes", "synth_adapter", "zero_adapter",
    "merge_adapter",
]

#: per-layer base-weight shapes of the four LoRA-able matmuls as
#: ``(d_in, out_dims)`` factories over the model width ``d`` —
#: the single source the pool allocator, the synthesizer, and the
#: dense-merge parity arm all read (models/gpt2.py owns the matching
#: einsums).
LORA_TARGET_SHAPES = {
    "qkv_w": lambda d: (d, (3, d)),
    "out_w": lambda d: (d, (d,)),
    "fc_w": lambda d: (d, (4 * d,)),
    "proj_w": lambda d: (4 * d, (d,)),
}


def adapter_param_shapes(n_layer: int, d_model: int, rank: int,
                         targets) -> Dict[str, Tuple[tuple, tuple]]:
    """``{target: (A shape, B shape)}`` for one adapter — layer-stacked
    to ride the same ``lax.scan`` xs as ``params['blocks']``:
    A ``[L, d_in, r]``, B ``[L, r, *out]``."""
    out = {}
    for t in targets:
        if t not in LORA_TARGET_SHAPES:
            raise ValueError(f"unknown lora target {t!r}; known: "
                             f"{sorted(LORA_TARGET_SHAPES)}")
        d_in, d_out = LORA_TARGET_SHAPES[t](d_model)
        out[t] = ((n_layer, d_in, rank), (n_layer, rank) + d_out)
    return out


def synth_adapter(adapter_id: int, shapes, dtype=np.float32,
                  std: float = 0.02) -> Dict[str, tuple]:
    """Deterministically synthesize one adapter's host weights from its
    id alone: ``{target: (A, B)}`` numpy arrays.  Every fleet replica
    derives the SAME weights for the same tenant id — the adapter twin
    of the shared-init-seed replica philosophy (docs/serving.md), so a
    re-routed tenant decodes identically without shipping weights over
    the wire.  Both factors are nonzero (unlike training-style zero-B
    init) so parity tests exercise a real delta."""
    if adapter_id <= 0:
        raise ValueError("adapter ids are positive (0 = no adapter)")
    weights = {}
    for i, t in enumerate(sorted(shapes)):
        a_shape, b_shape = shapes[t]
        rng = np.random.default_rng([int(adapter_id), i])
        a = rng.normal(0.0, std, a_shape).astype(dtype)
        b = rng.normal(0.0, std, b_shape).astype(dtype)
        weights[t] = (a, b)
    return weights


def zero_adapter(shapes, dtype=np.float32) -> Dict[str, tuple]:
    """The reserved slot-0 adapter: all-zero factors, so the no-tenant
    arm's gathered delta is mathematically zero through the shared
    program."""
    return {t: (np.zeros(a, dtype), np.zeros(b, dtype))
            for t, (a, b) in shapes.items()}


def merge_adapter(params, weights, scale: float):
    """Dense-merge ``W + scale * A @ B`` into a COPY of the base params
    — the parity/bench arm (one full merged model per tenant, the thing
    the heterogeneous batch makes unnecessary).  Host-side numpy."""
    import jax.numpy as jnp
    blocks = dict(params["blocks"])
    for t, (a, b) in weights.items():
        w = np.asarray(blocks[t], np.float32)
        # A [L, d_in, r] x B [L, r, *out] -> delta [L, d_in, *out]
        delta = np.einsum("ldr,lr...->ld...",
                          np.asarray(a, np.float32),
                          np.asarray(b, np.float32)) * scale
        blocks[t] = jnp.asarray((w + delta).astype(
            np.asarray(blocks[t]).dtype))
    out = dict(params)
    out["blocks"] = blocks
    return out


class AdapterRegistry:
    """The host tier: every known adapter's weights, capped at
    ``serving.lora.max_adapters``.  Unknown ids synthesize
    deterministically on first touch via ``make_weights`` (default
    :func:`synth_adapter` over ``shapes``) — register explicit weights
    with :meth:`register` for parity tests / real checkpoints."""

    def __init__(self, max_adapters: int, shapes,
                 make_weights: Optional[Callable[[int], dict]] = None):
        self.max_adapters = int(max_adapters)
        self.shapes = shapes
        self._make = make_weights or (
            lambda aid: synth_adapter(aid, shapes))
        self._host: "OrderedDict[int, dict]" = OrderedDict()

    def __len__(self):
        return len(self._host)

    def __contains__(self, adapter_id: int) -> bool:
        return int(adapter_id) in self._host

    def register(self, adapter_id: int, weights: dict) -> None:
        aid = int(adapter_id)
        if aid <= 0:
            raise ValueError("adapter ids are positive (0 = no adapter)")
        if aid not in self._host and len(self._host) >= self.max_adapters:
            raise RuntimeError(
                f"adapter registry full ({self.max_adapters}); raise "
                "serving.lora.max_adapters")
        for t, (a, b) in weights.items():
            a_shape, b_shape = self.shapes[t]
            if tuple(np.shape(a)) != a_shape or \
                    tuple(np.shape(b)) != b_shape:
                raise ValueError(
                    f"adapter {aid} target {t!r}: shapes "
                    f"{np.shape(a)}/{np.shape(b)} != {a_shape}/{b_shape}")
        self._host[aid] = {t: (np.asarray(a), np.asarray(b))
                           for t, (a, b) in weights.items()}

    def get(self, adapter_id: int) -> dict:
        """Host weights for ``adapter_id``, synthesizing (and caching)
        on first touch."""
        aid = int(adapter_id)
        got = self._host.get(aid)
        if got is None:
            self.register(aid, self._make(aid))
            got = self._host[aid]
        return got


class AdapterPool:
    """Refcounted LRU residency over ``slots`` HBM adapter slots
    (device indices 1..slots; 0 is the reserved zero adapter).

    The KV :class:`~deepspeed_tpu.inference.scheduler.PagePool`
    discipline applied to weights: ``acquire`` pins a tenant's slot for
    one request (cold tenants fetch host->HBM through the
    ``adapter_fetch`` stage, evicting the least-recently-used COLD
    resident when no slot is free), ``release`` unpins it; a refcount-0
    resident stays hot — the next acquire is a free hit — until
    eviction pressure reclaims it.  ``acquire`` on a dry pool (every
    slot pinned) returns None with NO side effects: the engine parks
    the request exactly like a pages-dry admission.

    ``upload(slot, weights)`` is the engine's device-copy closure (the
    jitted donated slot update); the pool never touches device arrays
    itself.  Counters are plain ints — the engine's ``_flush`` owns
    the telemetry registry (serve_adapter_{hits,faults}_total,
    serve_adapters_resident)."""

    def __init__(self, slots: int, registry: AdapterRegistry,
                 upload: Callable[[int, dict], None],
                 stage: Optional[Stage] = None):
        self.slots = int(slots)
        self.registry = registry
        self.upload = upload
        self.stage = stage or Stage(
            "adapter_fetch",
            fallback="synchronous host->HBM adapter copy (injection "
                     "plane bypassed)")
        self.free: deque = deque(range(1, self.slots + 1))
        self._slot_of: Dict[int, int] = {}     # adapter id -> slot
        self._adapter_in: Dict[int, int] = {}  # slot -> adapter id
        self._refs: Dict[int, int] = {}        # slot -> pin count
        #: refcount-0 residents in LRU order (oldest first) — the
        #: eviction candidates
        self._cold: "OrderedDict[int, int]" = OrderedDict()  # slot->aid
        self.hits = 0
        self.faults = 0
        self.evictions = 0

    # -- introspection ----------------------------------------------------
    def resident(self) -> int:
        """Resident adapters (pinned + cold), excluding slot 0."""
        return len(self._slot_of)

    def hot_ids(self) -> List[int]:
        """Resident adapter ids — the ``adapters_hot`` heartbeat gauge
        the FleetRouter's tenant affinity reads (inference/fleet.py)."""
        return sorted(self._slot_of)

    def slot_of(self, adapter_id: int) -> Optional[int]:
        return self._slot_of.get(int(adapter_id))

    def refs(self, adapter_id: int) -> int:
        slot = self._slot_of.get(int(adapter_id))
        return 0 if slot is None else self._refs.get(slot, 0)

    # -- the PagePool-shaped surface --------------------------------------
    def acquire(self, adapter_id: int) -> Optional[int]:
        """Pin ``adapter_id``'s slot for one request and return it.
        0 is the always-resident zero adapter (no refcounting).  A cold
        tenant fetches host->HBM (evicting the LRU cold resident when
        no slot is free); every slot pinned -> None, side-effect-free
        (the caller parks, exactly like a pages-dry admission)."""
        aid = int(adapter_id)
        if aid == 0:
            return 0
        slot = self._slot_of.get(aid)
        if slot is not None:                    # resident: hot hit
            if self._refs[slot] == 0:
                self._cold.pop(slot, None)
            self._refs[slot] += 1
            self.hits += 1
            return slot
        if self.free:
            slot = self.free.popleft()
        elif self._cold:                        # evict the LRU cold one
            slot, old = self._cold.popitem(last=False)
            del self._slot_of[old]
            del self._adapter_in[slot]
            self.evictions += 1
        else:
            return None                         # dry: every slot pinned
        try:
            weights = self.stage.call(
                "fetch",
                lambda: self._fetch(slot, aid),
                path=f"adapter={aid}")
        except BaseException:
            # non-transient (or degradation disabled): the slot must
            # not leak — put it back before the error propagates
            self.free.append(slot)
            raise
        del weights  # device copy done inside the stage unit
        self._slot_of[aid] = slot
        self._adapter_in[slot] = aid
        self._refs[slot] = 1
        self.faults += 1
        return slot

    def _fetch(self, slot: int, adapter_id: int):
        """One unit of adapter_fetch stage work: host weights (registry
        lookup / deterministic synthesis) + the device slot upload."""
        weights = self.registry.get(adapter_id)
        self.upload(slot, weights)
        return weights

    def release(self, adapter_id: int) -> None:
        """Unpin one acquire.  Refcount 0 keeps the adapter RESIDENT
        (cold, evictable) — the whole point of the pool: the tenant's
        next request is a free hit."""
        aid = int(adapter_id)
        if aid == 0:
            return
        slot = self._slot_of.get(aid)
        assert slot is not None, \
            f"adapter {aid} released but not resident (double free?)"
        refs = self._refs.get(slot, 0)
        assert refs > 0, \
            f"adapter {aid} slot {slot} deref'd below zero (double free)"
        self._refs[slot] = refs - 1
        if refs == 1:
            self._cold[slot] = aid              # newest cold = last out
