"""Serving fleet: router + replicated ServeEngines + SLO autoscaling
(docs/serving.md "serving fleet"; ROADMAP item 2).

One ``ServeEngine`` process is the ceiling on everything the serving
PRs bought: paged KV, speculation and int8 multiplied PER-CHIP
capacity, but aggregate throughput was still one process wide and a
single poison killed every in-flight user.  This module is the front
door over N of them:

  ``FleetRouter``   a jax-free router/supervisor (the
                    ``launcher/elastic.py`` idiom, shared machinery in
                    ``launcher/supervise.py``) that spawns N replica
                    subprocesses (``python -m
                    deepspeed_tpu.inference.replica`` — each an
                    ordinary ServeEngine on the stage runtime), admits
                    requests **join-shortest-queue** over each
                    replica's heartbeat gauges
                    (``telemetry/heartbeat.py`` payloads extended with
                    ``serve_active_slots``, request-queue depth,
                    ``serve_free_pages``), **fails over**
                    queued-but-unstarted requests when a replica dies
                    or poisons (requests whose tokens already started
                    streaming fail typed :class:`ReplicaFailure` — a
                    half-streamed answer must never be silently
                    retried into a duplicate; the replica's flight
                    recorder captures the corpse), and **autoscales**:
                    a queue-wait p99 breach of ``fleet.slo_p99_s``
                    sustained for ``scale_up_window_s`` spawns a
                    replica, sustained slack retires one, both clamped
                    to ``[min_replicas, max_replicas]`` with every
                    scale event resetting both hysteresis clocks (no
                    flapping inside a window).

Transport is the minimal length-prefixed socket protocol of
``inference/wire.py`` — the router imports stdlib + the heartbeat
reader + the shared supervision helpers, nothing that needs a working
accelerator runtime: it must keep routing when a replica's runtime is
the thing that is broken.

Supervision discipline (the elastic supervisor's, reused): replica
respawns back off exponentially, and ``fleet.max_restarts``
CONSECUTIVE replica failures without a single request completing in
between raise the typed :class:`FleetGiveUpError` (progress resets the
budget — a fleet serving for days must not die on an isolated blip),
with a ``flightrec_supervisor.json`` post-mortem next to the heartbeat
files for ``python -m deepspeed_tpu.telemetry diagnose <fleet_dir>``.

The router is single-threaded by design: every state change happens
inside :meth:`FleetRouter.poll` (called by ``run_until_idle`` /
``FleetRequest.result``), so the JSQ/failover/autoscale logic needs no
locks and stays deterministic under test — and JL007 (no stray daemon
threads) holds without exemptions.

Disaggregated prefill/decode (``fleet.roles``, docs/serving.md
"disaggregated fleet"): with a ``roles`` map the fleet specializes by
phase — admissions steer to ``prefill``/``mixed`` replicas, and when a
prefill-role replica finishes a request's prefill (one token,
``detach_kv``) its KV pages migrate over binary wire frames to a
``decode``/``mixed`` replica that adopts the request mid-stream.  The
router is the custody ledger: a request's KV blob is owned by exactly
one of {prefill replica, router, decode replica} at any instant, every
transition is an ``events.jsonl`` ``migration`` record, and a replica
death at ANY phase loses zero requests — prefill-phase deaths requeue
the request unstarted (its first token was never surrendered to the
caller), router-custody blobs re-dispatch to another decode replica,
and decode-phase deaths follow the existing started-request
:class:`ReplicaFailure` semantics.  Autoscaling splits per role:
prefill defends TTFT (``fleet.slo_ttft_s``, admission-wait signal),
decode defends TPOT (``fleet.slo_tpot_s``, the ``serve_tpot_p99_s``
heartbeat gauge + migration backlog), each with its own hysteresis
clocks.  Without ``roles`` every path below is byte-for-byte the
homogeneous fleet of PR 13.
"""
from __future__ import annotations

import dataclasses
import json
import os
import select
import socket
import subprocess
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from ..config.config import DeepSpeedFleetConfig
from ..launcher.supervise import (backoff_delay, dump_supervisor_flightrec,
                                  sweep_heartbeat_files,
                                  terminate_with_grace)
from ..telemetry.heartbeat import read_heartbeats
from ..utils.logging import logger
from .wire import (BinaryFrame, FrameReader, drain_socket,
                   send_binary_frame, send_frame)

#: scale-down hysteresis factor: slack means p99 under THIS fraction of
#: the SLO (or no waiters at all) — retiring at 0.99×SLO would flap
SLACK_FACTOR = 0.5

#: an accepted connection must say hello within this window or it is
#: dropped (a port scanner must not hold a router slot)
HELLO_TIMEOUT_S = 10.0

#: per-frame send/recv timeout on an attached replica socket — a peer
#: that can't take a submit frame for this long is hung, not busy
SOCK_TIMEOUT_S = 10.0

#: wall seconds between heartbeat-directory reads (beats refresh the
#: JSQ gauges and liveness; re-reading every poll would be fs spam)
HEARTBEAT_READ_INTERVAL_S = 0.2

#: wall seconds between metrics records in the fleet events.jsonl
#: (per-replica heartbeat_age_s + queue gauges)
METRICS_INTERVAL_S = 1.0


class FleetGiveUpError(RuntimeError):
    """The router is out of options: ``fleet.max_restarts`` consecutive
    replica failures with no completed request in between.  Carries the
    failure count and last reason so orchestrators can act on it."""

    def __init__(self, message: str, restarts: int = 0,
                 last_failure: str = ""):
        super().__init__(message)
        self.restarts = restarts
        self.last_failure = last_failure


class ReplicaFailure(RuntimeError):
    """A replica died (exit/poison/hang) mid-stream: the request's
    tokens had already started streaming, so failover would re-emit
    them as a duplicate answer — it fails typed instead.  Queued-but-
    unstarted requests on the same replica are failed over, never
    failed."""

    def __init__(self, message: str, replica: int = -1):
        super().__init__(message)
        self.replica = replica


class FleetClosedError(RuntimeError):
    """The router was closed with this request still in flight."""


@dataclasses.dataclass
class FleetRequest:
    """One generation request's router-side lifecycle record."""
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int]
    submit_t: float = 0.0
    tokens: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None
    error: Optional[BaseException] = None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    #: current replica assignment (None = queued at the router)
    replica: Optional[int] = None
    #: True once the first token frame arrived — the failover boundary:
    #: started requests fail typed, unstarted ones re-dispatch
    started: bool = False
    #: tenant LoRA adapter id (0 = base model) — steers tenant
    #: affinity in :meth:`FleetRouter._pick_replica` and rides the
    #: submit frame to the replica engine
    adapter_id: int = 0
    failovers: int = 0
    queue_wait_s: Optional[float] = None
    ttft_s: Optional[float] = None
    #: True once dispatched to a prefill-role replica with the migrate
    #: flag — this request will change replicas mid-stream.  The first
    #: token a PREFILL replica streams does NOT flip ``started``: until
    #: the decode replica takes custody, a death anywhere on the
    #: migration path requeues the request from scratch (the caller
    #: never saw the token, so there is no duplicate-answer hazard).
    migrated: bool = False
    prefill_replica: Optional[int] = None
    decode_replica: Optional[int] = None
    #: router-custody KV blob: (migrate_out header, [page payloads]) —
    #: held from blob completion until the decode replica streams, so a
    #: decode-replica death before its first token re-sends the blob
    _migration: Optional[tuple] = dataclasses.field(
        default=None, repr=False)
    _router: Optional["FleetRouter"] = dataclasses.field(
        default=None, repr=False)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Pump the (single-threaded) router until this request
        finishes; raises its error if it failed — the typed
        :class:`ReplicaFailure` / :class:`FleetClosedError` /
        replica-reported exception."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while not self.done.is_set():
            r = self._router
            if r is None or r._closed:
                if not self.done.wait(timeout=0.0):
                    raise FleetClosedError(
                        f"request {self.rid} abandoned: router closed")
                break
            r.poll(0.02)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"request {self.rid} not finished after {timeout}s")
        if self.error is not None:
            raise self.error
        return list(self.tokens)


class _Replica:
    """Router-side record of one replica incarnation.  States:
    ``starting`` (spawned, no hello yet) → ``ready`` (serving) →
    ``draining`` (retiring: no new work, finish what it holds) →
    removed.  A replica id is never reused — heartbeat files and
    telemetry dirs stay unambiguous across respawns."""

    def __init__(self, rid: int, proc, spawned_t: float,
                 role: str = "mixed"):
        self.id = rid
        self.proc = proc
        self.spawned_t = spawned_t
        self.role = role
        self.state = "starting"
        self.sock: Optional[socket.socket] = None
        self.reader: Optional[FrameReader] = None
        self.outstanding: "OrderedDict[int, FleetRequest]" = OrderedDict()
        #: in-flight migrate_out receptions: rid → {"header", "pages"}
        #: (custody still THIS replica's until the last page lands — a
        #: death mid-blob discards the partial and requeues the rid)
        self.migrating: Dict[int, dict] = {}
        self.shutdown_sent = False
        #: wall time the replica went ready — the staleness clock's
        #: floor for a replica whose beats never land (beat writes
        #: degrade silently by design: disk full, unwritable dir)
        self.ready_wall_t: Optional[float] = None


def _p99(vals: List[float]) -> Optional[float]:
    """Linear-interpolated p99 — the telemetry CLI's one percentile
    implementation (cli.py is itself pure stdlib, and the heartbeat
    import above already pulls the telemetry package, so this adds
    nothing to the router's import surface)."""
    from ..telemetry.cli import _percentile
    return _percentile(sorted(vals), 0.99)


class FleetRouter:
    """The serving fleet's front door — see the module docstring.

    ``config``    dict / path to a ds_config.json with a ``fleet``
                  block (plus the ``serving`` / ``fleet_model`` blocks
                  the replica entrypoint reads).  A dict is persisted
                  to ``<fleet_dir>/fleet_config.json`` so subprocess
                  replicas can load it.
    ``fleet_dir`` the fleet's shared directory: replica heartbeats,
                  the router's ``events.jsonl`` (per-request completion
                  records, scale events, per-replica
                  ``heartbeat_age_s{replica=...}`` metrics), per-
                  replica telemetry subdirs (``replica_<id>/`` — where
                  a poisoned replica's flight recorder lands), and the
                  give-up post-mortem.
    ``spawn_fn``  (replica_id, attempt) -> Popen-like handle — the test
                  seam (the elastic ``launch_fn`` idiom).  Default
                  spawns ``python -m deepspeed_tpu.inference.replica``
                  inheriting the router's environment (so
                  ``DS_STAGE_DELAY_S`` chaos specs reach every
                  replica).
    ``now_fn``    monotonic clock for queue-wait/autoscale timing (the
                  test seam for hysteresis-window tests).
    """

    def __init__(self, config, fleet_dir: str,
                 spawn_fn=None, now_fn=time.monotonic):
        if isinstance(config, str):
            self._config_path = config
            with open(config) as f:
                cfg_dict = json.load(f)
        elif isinstance(config, dict):
            cfg_dict = config
            self._config_path = os.path.join(fleet_dir,
                                             "fleet_config.json")
        else:
            raise TypeError(
                "FleetRouter config must be a dict or a path to a "
                f"ds_config.json, got {type(config).__name__}")
        self.cfg = DeepSpeedFleetConfig(cfg_dict)
        self.fleet_dir = fleet_dir
        os.makedirs(fleet_dir, exist_ok=True)
        if isinstance(config, dict):
            with open(self._config_path, "w") as f:
                json.dump(cfg_dict, f)
        self._now = now_fn
        self.spawn_fn = spawn_fn if spawn_fn is not None \
            else self._spawn_subprocess

        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("127.0.0.1", 0))
        self._listen.listen(16)
        self._listen.setblocking(False)
        self.addr = self._listen.getsockname()

        self.replicas: Dict[int, _Replica] = {}
        #: accepted connections awaiting their hello frame
        self._greeting: List[tuple] = []
        self._queue: deque = deque()          # unassigned FleetRequests
        self._reqs: Dict[int, FleetRequest] = {}
        self._next_rid = 0
        self._next_replica_id = 0
        #: (now_fn timestamp, queue_wait_s) admission samples — the SLO
        #: signal the autoscaler and the bench's p99 read
        self._wait_samples: deque = deque()
        self._breach_since: Optional[float] = None
        self._slack_since: Optional[float] = None
        #: router-custody requests awaiting a decode replica: the KV
        #: blob arrived in full but no decode/mixed replica could take
        #: it yet (or its decode replica died pre-stream)
        self._migrate_queue: deque = deque()
        #: per-role replica targets (disaggregated fleets only): the
        #: supervision floor AND the autoscaler's moving setpoint —
        #: scale-up bumps a role's target, scale-down lowers it (never
        #: below 1: a role's last replica wedges its whole phase)
        self._role_target: Dict[str, int] = (
            dict(self.cfg.roles) if self.cfg.roles else {})
        self._breach_since_role: Dict[str, float] = {}
        self._slack_since_role: Dict[str, float] = {}
        #: role handed to the NEXT spawn_fn call (the spawn_fn seam
        #: keeps its (replica_id, attempt) signature)
        self._spawn_role = "mixed"
        self.migrations = 0
        self._started_t: Optional[float] = None
        #: consecutive replica failures with no completed request in
        #: between (the give-up budget); ``restarts`` counts every
        #: failure episode over the router's lifetime (never reset)
        self._consec_failures = 0
        self.restarts = 0
        #: killed-but-not-yet-reaped replica processes: _fail_replica
        #: must never block the poll loop on a wedged process — it
        #: SIGKILLs and parks the corpse here for async reaping
        self._reaping: List[tuple] = []
        self._last_failure = ""
        self._next_spawn_t = 0.0
        self._beats: Dict[int, dict] = {}
        self._last_beats_read = 0.0
        self._last_metrics_write = 0.0
        self._closed = False
        self._gave_up = False
        #: bounded event ring for the give-up flight record
        self.events: deque = deque(maxlen=256)
        self._records = open(os.path.join(fleet_dir, "events.jsonl"),
                             "a", buffering=1)

    # -- records + events ------------------------------------------------
    def _record(self, kind: str, **fields) -> None:
        self.events.append({"t": time.time(), "kind": kind, **fields})
        try:
            rec = {"kind": kind, "t": time.time()}
            rec.update(fields)
            self._records.write(json.dumps(rec, default=repr) + "\n")
        except (OSError, ValueError):
            pass  # a full disk must not take the router down

    def _write_request_record(self, fr: FleetRequest) -> None:
        # arrival_s: submit time relative to the router's start — the
        # open-loop schedule, reconstructible from the ledger alone
        # (telemetry/goodput.py; readers tolerate pre-PR-17 records
        # without it)
        epoch = self._started_t if self._started_t is not None \
            else fr.submit_t
        self._record(
            "fleet_request", rid=fr.rid, replica=fr.replica,
            arrival_s=round(fr.submit_t - epoch, 6),
            tokens=len(fr.tokens), finish_reason=fr.finish_reason,
            error=repr(fr.error) if fr.error is not None else None,
            queue_wait_s=fr.queue_wait_s, ttft_s=fr.ttft_s,
            total_s=self._now() - fr.submit_t,
            failovers=fr.failovers, started=fr.started,
            migrated=fr.migrated, prefill_replica=fr.prefill_replica,
            decode_replica=fr.decode_replica)

    def _write_metrics(self) -> None:
        """Per-replica liveness made operator-visible: the same
        ``{"kind": "metrics"}`` record shape the telemetry hub writes,
        so ``summarize``'s liveness row and ``diagnose`` read fleet
        events.jsonl unchanged."""
        now_wall = time.time()
        metrics = []
        for rep in self.replicas.values():
            beat = self._beats.get(rep.id)
            age = (max(0.0, now_wall - float(beat.get("time", 0.0)))
                   if beat else None)
            metrics.append({
                "name": "heartbeat_age_s",
                "labels": {"replica": str(rep.id),
                           "host": f"replica_{rep.id}",
                           "state": rep.state,
                           "role": rep.role},
                "value": age})
        metrics.append({"name": "fleet_queue_depth", "labels": {},
                        "value": len(self._queue)
                        + len(self._migrate_queue)})
        metrics.append({"name": "fleet_live_replicas", "labels": {},
                        "value": len(self._live())})
        self._record("metrics", metrics=metrics)

    # -- spawn / probe ---------------------------------------------------
    def _spawn_subprocess(self, replica_id: int, attempt: int):
        """The production spawn: one ``inference.replica`` subprocess,
        env inherited (chaos specs, JAX_PLATFORMS), stdout/stderr to
        ``replica_<id>.log`` in the fleet dir."""
        log_path = os.path.join(self.fleet_dir,
                                f"replica_{replica_id}.log")
        cmd = [sys.executable, "-m", "deepspeed_tpu.inference.replica",
               "--router", f"{self.addr[0]}:{self.addr[1]}",
               "--replica-id", str(replica_id),
               "--fleet-dir", self.fleet_dir,
               "--config", self._config_path,
               "--role", self._spawn_role]
        with open(log_path, "ab") as log:
            return subprocess.Popen(cmd, stdout=log,
                                    stderr=subprocess.STDOUT)

    def _live(self) -> List[_Replica]:
        """Replicas that count toward the autoscale clamps: starting or
        serving (a draining replica is already on its way out)."""
        return [r for r in self.replicas.values()
                if r.state in ("starting", "ready")]

    def _spawn(self, reason: str,
               role: str = "mixed") -> Optional[_Replica]:
        now = self._now()
        if now < self._next_spawn_t:
            return None
        rid = self._next_replica_id
        self._next_replica_id += 1
        self._spawn_role = role
        try:
            # attempt = the current consecutive-failure count, so a
            # spawn_fn varying behavior by attempt (the test seam)
            # sees retries as retries
            proc = self.spawn_fn(rid, self._consec_failures)
        except Exception as e:
            self._note_replica_failure(f"spawn of replica {rid} "
                                       f"raised: {e!r}")
            return None
        rep = _Replica(rid, proc, now, role=role)
        self.replicas[rid] = rep
        self._record("spawn", replica=rid, reason=reason, role=role,
                     live=len(self._live()))
        logger.info("fleet: spawned replica %d (%s, %s), %d live", rid,
                    reason, role, len(self._live()))
        return rep

    def _role_deficit(self) -> Optional[str]:
        """First role (fixed order — deterministic) whose live count
        sits below its target; None when the fleet stands at width."""
        for role in ("prefill", "decode", "mixed"):
            tgt = self._role_target.get(role, 0)
            if tgt and sum(1 for r in self._live()
                           if r.role == role) < tgt:
                return role
        return None

    def start(self, wait_ready: bool = True) -> "FleetRouter":
        """Launch the configured initial replicas; with ``wait_ready``
        pump until every one said hello (spawn failures ride the
        backoff/give-up discipline inside :meth:`poll`)."""
        self._started_t = self._now()
        sweep_heartbeat_files(self.fleet_dir)
        if self.cfg.roles:
            for role in ("prefill", "decode", "mixed"):
                for _ in range(self._role_target.get(role, 0)):
                    self._spawn("initial", role)
        else:
            for _ in range(self.cfg.replicas):
                self._spawn("initial")
        while wait_ready and not self._closed:
            if self.cfg.roles:
                missing = self._role_deficit()
                if missing is not None:
                    self._spawn("initial", missing)
                elif all(r.state == "ready" for r in self._live()):
                    break
            elif len(self._live()) < self.cfg.replicas:
                # a failed initial spawn retries under the backoff/
                # give-up discipline until the configured width stands
                self._spawn("initial")
            elif all(r.state == "ready" for r in self._live()):
                break
            self.poll(0.05)
        return self

    # -- request intake --------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               adapter_id: int = 0) -> FleetRequest:
        if self._closed:
            raise RuntimeError("FleetRouter is closed")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if int(adapter_id) < 0:
            raise ValueError("adapter_id must be >= 0 (0 = base model)")
        self._next_rid += 1
        fr = FleetRequest(rid=self._next_rid, prompt=prompt,
                          max_new_tokens=int(max_new_tokens),
                          eos_id=eos_id, submit_t=self._now(),
                          adapter_id=int(adapter_id), _router=self)
        self._reqs[fr.rid] = fr
        self._queue.append(fr)
        self._record("fleet_submit", rid=fr.rid,
                     prompt_len=len(prompt))
        return fr

    # -- join-shortest-queue ---------------------------------------------
    def _replica_load(self, rep: _Replica) -> int:
        """A replica's load for JSQ: the router's own outstanding count
        (known synchronously) floored by the replica's last heartbeat
        gauges (queue depth + active slots — work the replica admitted
        before this router incarnation, or submitted by the frames
        still in flight)."""
        beat = self._beats.get(rep.id) or {}
        hb = (int(beat.get("serve_queue_depth") or 0)
              + int(beat.get("serve_active_slots") or 0))
        return max(len(rep.outstanding), hb)

    #: tenant affinity's bounded imbalance: a replica whose heartbeat
    #: shows the tenant's adapter already HBM-resident may win over the
    #: JSQ minimum only while its load is within this many requests of
    #: it — affinity saves cold-adapter faults but never starves JSQ
    ADAPTER_AFFINITY_SLACK = 2

    def _pick_replica(self, roles=None,
                      adapter_id: int = 0) -> Optional[_Replica]:
        """JSQ with DETERMINISTIC tie-breaking: equal loads go to the
        lowest replica id (tested — a tie must not depend on dict
        order).  ``roles`` restricts the candidate set (disaggregated
        steering); None considers every ready replica.

        ``adapter_id > 0`` adds tenant affinity on top of JSQ: among
        candidates advertising the adapter in their ``adapters_hot``
        heartbeat gauge, the least-loaded wins IF its load is within
        :data:`ADAPTER_AFFINITY_SLACK` of the JSQ minimum; otherwise
        pure JSQ (bounded imbalance — a hot tenant cannot pile onto
        one replica while the rest idle)."""
        best = None
        aff = None
        for rep in self.replicas.values():
            if rep.state != "ready":
                continue
            if roles is not None and rep.role not in roles:
                continue
            key = (self._replica_load(rep), rep.id)
            if best is None or key < best[0]:
                best = (key, rep)
            if adapter_id:
                hot = (self._beats.get(rep.id) or {}).get(
                    "adapters_hot") or ()
                if adapter_id in hot and (aff is None or key < aff[0]):
                    aff = (key, rep)
        if best is None:
            return None
        if aff is not None and \
                aff[0][0] <= best[0][0] + self.ADAPTER_AFFINITY_SLACK:
            return aff[1]
        return best[1]

    def _admission_roles(self):
        """Where new prompts go: prefill+mixed when the fleet has a
        prefill phase at all; otherwise any replica (a roles map
        without ``prefill`` is labels, not disaggregation)."""
        if self.cfg.roles and "prefill" in self.cfg.roles:
            return ("prefill", "mixed")
        return None

    def _dispatch(self) -> None:
        roles = self._admission_roles()
        while self._queue:
            rep = self._pick_replica(roles,
                                     adapter_id=self._queue[0].adapter_id)
            if rep is None:
                return
            fr = self._queue.popleft()
            fr.replica = rep.id
            # a prefill-only replica never decodes: flag the submit so
            # the replica runs ONE token with detach_kv and hands the
            # pages back for migration.  max_new_tokens == 1 requests
            # are already pure prefill — they serve in place.
            migrate = rep.role == "prefill" and fr.max_new_tokens > 1
            if migrate:
                fr.migrated = True
                fr.prefill_replica = rep.id
            rep.outstanding[fr.rid] = fr
            try:
                send_frame(rep.sock, {
                    "kind": "submit", "rid": fr.rid,
                    "prompt": fr.prompt,
                    "max_new_tokens": fr.max_new_tokens,
                    "eos_id": fr.eos_id,
                    **({"adapter_id": fr.adapter_id}
                       if fr.adapter_id else {}),
                    **({"migrate": True} if migrate else {})})
            except OSError as e:
                # the failover path requeues fr (it is unstarted by
                # construction — nothing was ever streamed back)
                self._fail_replica(rep, f"submit send to replica "
                                        f"{rep.id} failed: {e}")

    def _dispatch_migrations(self) -> None:
        """Hand router-custody KV blobs to decode/mixed replicas —
        header frame first, then the page frames, then custody flips to
        the decode replica (its death before streaming puts the blob
        right back here)."""
        while self._migrate_queue:
            rep = self._pick_replica(
                ("decode", "mixed"),
                adapter_id=self._migrate_queue[0].adapter_id)
            if rep is None:
                return
            fr = self._migrate_queue.popleft()
            hdr, pages = fr._migration
            fr.replica = rep.id
            fr.decode_replica = rep.id
            rep.outstanding[fr.rid] = fr
            try:
                send_frame(rep.sock, {
                    "kind": "migrate_in", "rid": fr.rid,
                    "prompt": fr.prompt,
                    "first_token": hdr.get("first_token"),
                    "kv_len": hdr.get("kv_len"),
                    "pages": len(pages),
                    "max_new_tokens": fr.max_new_tokens,
                    "eos_id": fr.eos_id,
                    **({"adapter_id": fr.adapter_id}
                       if fr.adapter_id else {})})
                for seq, payload in enumerate(pages):
                    send_binary_frame(rep.sock, {
                        "kind": "page", "rid": fr.rid, "seq": seq,
                        "leaves": hdr.get("leaves")}, payload)
            except OSError as e:
                # fr._migration is still set, so the failover path
                # returns it to the migrate queue, not the front door
                self._fail_replica(rep, f"migrate_in send to replica "
                                        f"{rep.id} failed: {e}")
                continue
            self.migrations += 1
            self._record("migration", rid=fr.rid, custody="decode",
                         src=fr.prefill_replica, dst=rep.id,
                         pages=len(pages),
                         bytes=sum(len(p) for p in pages))

    # -- frame handling --------------------------------------------------
    def _complete(self, fr: FleetRequest, rep: Optional[_Replica]) -> None:
        if rep is not None:
            rep.outstanding.pop(fr.rid, None)
        self._reqs.pop(fr.rid, None)
        self._write_request_record(fr)
        fr.done.set()

    def _handle_frame(self, rep: _Replica, frame: dict) -> None:
        kind = frame.get("kind")
        if kind == "hello":
            return  # duplicate hello — harmless
        rid = frame.get("rid")
        fr = rep.outstanding.get(rid)
        if fr is None:
            return  # finished/failed-over meanwhile — a late frame
        now = self._now()
        if kind == "admit":
            fr.queue_wait_s = now - fr.submit_t
            self._wait_samples.append((now, fr.queue_wait_s))
        elif kind == "token":
            toks = frame.get("toks") or []
            if toks:
                if fr.ttft_s is None:
                    fr.ttft_s = now - fr.submit_t
                # a PREFILL replica's token does not flip the failover
                # boundary: the caller hasn't seen it, so a death
                # anywhere before decode custody requeues cleanly
                if not fr.started and not (
                        fr.migrated and rep.id == fr.prefill_replica):
                    fr.started = True
                    fr._migration = None  # decode streaming: blob done
            fr.tokens.extend(int(t) for t in toks)
        elif kind == "migrate_out":
            # the prefill replica finished rid's prefill: its page
            # frames follow on this same socket.  Custody stays with
            # the replica until the LAST page lands.
            rep.migrating[rid] = {"header": frame, "pages": []}
        elif kind == "page":
            entry = rep.migrating.get(rid)
            if entry is not None and isinstance(frame, BinaryFrame):
                entry["pages"].append(frame.payload)
                if len(entry["pages"]) >= int(
                        entry["header"].get("pages", 0)):
                    self._take_custody(rep, fr, entry)
        elif kind == "done":
            fr.finish_reason = frame.get("reason")
            total = frame.get("tokens_total")
            if total is not None and total != len(fr.tokens):
                logger.warning(
                    "fleet: rid=%d stream length %d != replica total "
                    "%d", fr.rid, len(fr.tokens), total)
            self._complete(fr, rep)
            # progress: a completed request resets the give-up budget
            self._consec_failures = 0
        elif kind == "error":
            fr.error = RuntimeError(
                f"replica {rep.id} failed rid={rid}: "
                f"{frame.get('error')}")
            self._complete(fr, rep)

    def _take_custody(self, rep: _Replica, fr: FleetRequest,
                      entry: dict) -> None:
        """The last page of rid's KV blob landed: custody moves prefill
        replica → router.  The prefill replica is done with the rid
        (its pages are already released engine-side)."""
        hdr = entry["header"]
        rep.migrating.pop(fr.rid, None)
        rep.outstanding.pop(fr.rid, None)
        fr.replica = None
        if not fr.tokens and hdr.get("first_token") is not None:
            # belt-and-braces: the replica streams the first token as a
            # normal token frame before migrate_out, but the header
            # carries it too so a blob is self-contained
            fr.tokens.append(int(hdr["first_token"]))
        fr._migration = (hdr, entry["pages"])
        self._migrate_queue.append(fr)
        self._record("migration", rid=fr.rid, custody="router",
                     src=rep.id, pages=len(entry["pages"]),
                     bytes=sum(len(p) for p in entry["pages"]))

    def _pump_replicas(self) -> None:
        for rep in list(self.replicas.values()):
            if rep.sock is None:
                continue
            try:
                frames, closed = drain_socket(rep.sock, rep.reader)
            except Exception as e:
                self._fail_replica(rep, f"replica {rep.id} corrupt "
                                        f"stream: {e!r}")
                continue
            for frame in frames:
                self._handle_frame(rep, frame)
            if closed and rep.id in self.replicas:
                if rep.state == "draining" and not rep.outstanding:
                    self._finish_retire(rep)
                else:
                    self._fail_replica(
                        rep, f"replica {rep.id} connection closed")

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listen.accept()
            except (BlockingIOError, OSError):
                break
            sock.settimeout(SOCK_TIMEOUT_S)
            self._greeting.append((sock, FrameReader(), self._now()))
        still = []
        for sock, reader, t0 in self._greeting:
            try:
                frames, closed = drain_socket(sock, reader)
            except Exception:
                # a garbage connection (port scanner, corrupt framing)
                # fails ITSELF, never the router
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            hello = next((f for f in frames
                          if f.get("kind") == "hello"), None)
            if hello is not None:
                rep = self.replicas.get(hello.get("replica"))
                if rep is not None and rep.sock is None:
                    rep.sock = sock
                    rep.reader = reader
                    reader.pending.extend(
                        f for f in frames if f.get("kind") != "hello")
                    rep.state = "ready"
                    rep.ready_wall_t = time.time()
                    self._record("ready", replica=rep.id)
                    logger.info("fleet: replica %d ready", rep.id)
                else:
                    sock.close()  # unknown or duplicate — drop
                continue
            if closed or self._now() - t0 > HELLO_TIMEOUT_S:
                sock.close()
                continue
            still.append((sock, reader, t0))
        self._greeting = still

    # -- failure + failover ----------------------------------------------
    def _note_replica_failure(self, reason: str) -> None:
        self._consec_failures += 1
        self.restarts += 1
        self._last_failure = reason
        self._next_spawn_t = self._now() + backoff_delay(
            self.cfg.backoff_base_s, self.cfg.backoff_max_s,
            self._consec_failures)
        logger.warning("fleet: %s (consecutive failures: %d/%d)",
                       reason, self._consec_failures,
                       self.cfg.max_restarts)
        if self._consec_failures > self.cfg.max_restarts:
            self._give_up(reason)

    def _give_up(self, reason: str) -> None:
        msg = (f"fleet: giving up after {self._consec_failures} "
               f"consecutive replica failures with no completed "
               f"request (max_restarts={self.cfg.max_restarts}); "
               f"last failure: {reason}")
        self._gave_up = True
        self._record("give_up", error=msg)
        dump_supervisor_flightrec(
            self.fleet_dir, supervisor="fleet", reason="FleetGiveUpError:"
            " restart budget exhausted", error=msg,
            restarts=self._consec_failures,
            max_restarts=self.cfg.max_restarts,
            fallback="give up (typed FleetGiveUpError)",
            events=self.events,
            extra={"replicas": {str(r.id): r.state
                                for r in self.replicas.values()},
                   "queued": len(self._queue)})
        err = FleetGiveUpError(msg, restarts=self._consec_failures,
                               last_failure=reason)
        self.close(error=err)
        raise err

    def _fail_replica(self, rep: _Replica, reason: str) -> None:
        """A replica died/hung/poisoned: kill the remnant, typed-fail
        its MID-STREAM requests, fail over the queued-but-unstarted
        ones (front of the router queue, original order), and let the
        give-up budget decide whether the fleet survives."""
        if rep.id not in self.replicas:
            return
        del self.replicas[rep.id]
        if rep.sock is not None:
            try:
                rep.sock.close()
            except OSError:
                pass
        # SIGKILL, never SIGTERM+grace: this replica's work is already
        # declared lost, and a synchronous grace-wait here would freeze
        # the poll loop — stalling every HEALTHY replica's frames during
        # exactly the degraded window the SLO autoscaler defends.  The
        # corpse is reaped asynchronously by later polls.
        try:
            rep.proc.kill()
        except OSError:
            pass
        self._reaping.append((str(rep.id), rep.proc))
        failed_over = 0
        for fr in sorted(rep.outstanding.values(), key=lambda r: r.rid,
                         reverse=True):
            if fr.started:
                fr.error = ReplicaFailure(
                    f"replica {rep.id} died mid-stream "
                    f"({reason}) after {len(fr.tokens)} token(s)",
                    replica=rep.id)
                self._complete(fr, None)
            elif fr._migration is not None:
                # router custody: the decode replica died before it
                # streamed a token, but the KV blob is still ours —
                # re-dispatch it to another decode replica, losing
                # nothing and re-running nothing
                fr.replica = None
                fr.decode_replica = None
                fr.failovers += 1
                self._migrate_queue.append(fr)
                self._record("migration", rid=fr.rid, custody="router",
                             src=rep.id, requeued=True)
                failed_over += 1
            else:
                # reset to pre-dispatch state; rid order preserved at
                # the FRONT of the queue (they waited longest).  The
                # wait stamp resets too: an admitted-but-unstarted
                # request must stay visible to the oldest-wait wedge
                # detector until its NEW replica admits it.  A migrated
                # request dying in its PREFILL phase lands here: the
                # partial blob (if any) died with the replica and the
                # first token was never surrendered, so it restarts
                # from scratch — tokens and stamps cleared
                fr.replica = None
                fr.queue_wait_s = None
                fr.failovers += 1
                if fr.migrated:
                    fr.tokens.clear()
                    fr.ttft_s = None
                    fr.migrated = False
                    fr.prefill_replica = None
                    fr.decode_replica = None
                self._queue.appendleft(fr)
                failed_over += 1
        rep.outstanding.clear()
        rep.migrating.clear()
        self._record("replica_dead", replica=rep.id, reason=reason,
                     failed_over=failed_over,
                     live=len(self._live()))
        self._note_replica_failure(reason)

    def _reap(self) -> None:
        self._reaping = [(tag, p) for tag, p in self._reaping
                         if p.poll() is None]

    def _check_replicas(self) -> None:
        now = self._now()
        now_wall = time.time()
        for rep in list(self.replicas.values()):
            rc = rep.proc.poll()
            if rc is not None:
                if rep.state == "draining" and rc == 0:
                    self._finish_retire(rep)
                else:
                    self._fail_replica(
                        rep, f"replica {rep.id} exited rc={rc}")
                continue
            if rep.state == "starting" and \
                    now - rep.spawned_t > self.cfg.spawn_timeout_s:
                self._fail_replica(
                    rep, f"replica {rep.id} not ready within "
                         f"spawn_timeout_s="
                         f"{self.cfg.spawn_timeout_s:.0f}s")
                continue
            if rep.state in ("ready", "draining") \
                    and self.cfg.heartbeat_timeout_s:
                # draining replicas stay hang-detectable too: one that
                # wedges mid-drain still holds outstanding requests
                # nobody else would ever fail over
                beat = self._beats.get(rep.id)
                # no beat at all counts from readiness: a replica
                # whose beat writes silently fail must still be
                # hang-detectable, or its requests wedge forever
                last = (float(beat.get("time", 0.0)) if beat
                        else rep.ready_wall_t or now_wall)
                if now_wall - last > self.cfg.heartbeat_timeout_s:
                    self._fail_replica(
                        rep, f"replica {rep.id} missed heartbeats "
                             f"(> {self.cfg.heartbeat_timeout_s:.0f}s "
                             "stale; hung)")

    def _read_beats(self) -> None:
        now_wall = time.time()
        if now_wall - self._last_beats_read < HEARTBEAT_READ_INTERVAL_S:
            return
        self._last_beats_read = now_wall
        beats = read_heartbeats(self.fleet_dir)
        by_idx: Dict[int, dict] = {}
        for rec in beats.values():
            try:
                by_idx[int(rec.get("process_index"))] = rec
            except (TypeError, ValueError):
                continue
        self._beats = by_idx
        if now_wall - self._last_metrics_write >= METRICS_INTERVAL_S:
            self._last_metrics_write = now_wall
            self._write_metrics()

    # -- autoscaling -----------------------------------------------------
    def _oldest_wait(self) -> Optional[float]:
        """Age of the oldest request still waiting for ADMISSION —
        queued at the router or dispatched but unadmitted.  Without
        this a fully wedged fleet produces no admission samples at all
        and the sample-based p99 would read as healthy."""
        now = self._now()
        oldest = None
        for fr in self._queue:
            oldest = fr.submit_t if oldest is None \
                else min(oldest, fr.submit_t)
        for rep in self.replicas.values():
            for fr in rep.outstanding.values():
                if fr.queue_wait_s is None:
                    oldest = fr.submit_t if oldest is None \
                        else min(oldest, fr.submit_t)
        return None if oldest is None else now - oldest

    def queue_wait_p99(self, window_s: Optional[float] = None) -> \
            Optional[float]:
        """p99 of admission queue waits over the trailing window (the
        scale-up window by default) — the number the SLO defends and
        the bench reports."""
        now = self._now()
        w = window_s if window_s is not None \
            else self.cfg.scale_up_window_s
        return _p99([s for t, s in self._wait_samples
                     if now - t <= w])

    def _decode_tpot_p99(self) -> Optional[float]:
        """The decode phase's SLO signal: worst ``serve_tpot_p99_s``
        gauge any live decode/mixed replica last beat (a fleet is as
        slow as its slowest decode replica — averaging would hide one
        wedged member behind healthy peers)."""
        worst = None
        for rep in self.replicas.values():
            if rep.role not in ("decode", "mixed"):
                continue
            beat = self._beats.get(rep.id) or {}
            v = beat.get("serve_tpot_p99_s")
            if v is None:
                continue
            v = float(v)
            worst = v if worst is None else max(worst, v)
        return worst

    def _role_signals(self, role: str):
        """(breach, slack, detail) for one role.  Prefill defends TTFT
        through the admission-wait signal (queue waits ARE the TTFT
        budget a prompt burns before its first prefill step); decode
        defends TPOT through the replica-reported decode-latency gauge
        plus the migration backlog (blobs parked at the router mean
        decode capacity, not prefill, is the bottleneck)."""
        cfg = self.cfg
        if role == "decode":
            slo = cfg.slo_tpot_s or 0.0
            tpot = self._decode_tpot_p99()
            backlog = len(self._migrate_queue)
            breach = bool(backlog) or (
                bool(slo) and tpot is not None and tpot > slo)
            slack = not backlog and (
                not slo or tpot is None or tpot < slo * SLACK_FACTOR)
            return breach, slack, {"tpot_p99_s": tpot,
                                   "migrate_backlog": backlog,
                                   "slo_tpot_s": slo}
        slo = (cfg.slo_ttft_s or cfg.slo_p99_s) if role == "prefill" \
            else cfg.slo_p99_s
        p99_up = self.queue_wait_p99(cfg.scale_up_window_s)
        oldest = self._oldest_wait()
        breach = ((p99_up is not None and p99_up > slo)
                  or (oldest is not None and oldest > slo))
        p99_down = self.queue_wait_p99(cfg.scale_down_window_s)
        slack = (not self._queue
                 and (p99_down is None
                      or p99_down < slo * SLACK_FACTOR))
        return breach, slack, {"p99_s": p99_up, "oldest_wait_s": oldest,
                               "slo_s": slo}

    def _autoscale_roles(self) -> None:
        """Per-role scale decisions with per-role hysteresis clocks.
        The role targets are the supervision floor: a role running
        below its target respawns on supervision grounds alone, so a
        dead prefill replica comes back AS prefill (a fleet that
        backfilled roles arbitrarily would silently de-specialize)."""
        now = self._now()
        cfg = self.cfg
        keep = max(cfg.scale_up_window_s, cfg.scale_down_window_s)
        while self._wait_samples and \
                now - self._wait_samples[0][0] > keep:
            self._wait_samples.popleft()
        live = self._live()
        missing = self._role_deficit()
        if missing is not None:
            self._spawn("role floor", missing)
            self._breach_since_role.pop(missing, None)
            self._slack_since_role.pop(missing, None)
            return
        for role in ("prefill", "decode", "mixed"):
            if not self._role_target.get(role, 0):
                continue
            breach, slack, detail = self._role_signals(role)
            if breach:
                self._slack_since_role.pop(role, None)
                since = self._breach_since_role.get(role)
                if since is None:
                    self._breach_since_role[role] = now
                elif now - since >= cfg.scale_up_window_s \
                        and len(live) < cfg.max_replicas:
                    rep = self._spawn("slo_breach", role)
                    if rep is not None:
                        self._role_target[role] += 1
                        self._record("scale_up", replica=rep.id,
                                     role=role, live=len(self._live()),
                                     **detail)
                        self._breach_since_role.pop(role, None)
                        live = self._live()
                continue
            self._breach_since_role.pop(role, None)
            if not slack:
                self._slack_since_role.pop(role, None)
                continue
            since = self._slack_since_role.get(role)
            if since is None:
                self._slack_since_role[role] = now
                continue
            ready = [r for r in live
                     if r.state == "ready" and r.role == role]
            if now - since >= cfg.scale_down_window_s \
                    and len(live) > cfg.min_replicas \
                    and self._role_target[role] > 1 and ready:
                rep = max(ready, key=lambda r: r.id)
                rep.state = "draining"
                self._role_target[role] -= 1
                self._record("scale_down", replica=rep.id, role=role,
                             live=len(self._live()), **detail)
                logger.info("fleet: retiring %s replica %d (slack)",
                            role, rep.id)
                self._breach_since_role.pop(role, None)
                self._slack_since_role.pop(role, None)
                live = self._live()

    def _autoscale(self) -> None:
        if self.cfg.roles:
            self._autoscale_roles()
            return
        now = self._now()
        cfg = self.cfg
        keep = max(cfg.scale_up_window_s, cfg.scale_down_window_s)
        while self._wait_samples and \
                now - self._wait_samples[0][0] > keep:
            self._wait_samples.popleft()
        live = self._live()
        # min clamp first: a fleet below its floor respawns on
        # supervision grounds alone (subject to the failure backoff)
        if len(live) < cfg.min_replicas:
            self._spawn("min_replicas clamp")
            self._breach_since = None
            self._slack_since = None
            return
        p99_up = self.queue_wait_p99(cfg.scale_up_window_s)
        oldest = self._oldest_wait()
        breach = ((p99_up is not None and p99_up > cfg.slo_p99_s)
                  or (oldest is not None and oldest > cfg.slo_p99_s))
        if breach:
            self._slack_since = None
            if self._breach_since is None:
                self._breach_since = now
            elif now - self._breach_since >= cfg.scale_up_window_s \
                    and len(live) < cfg.max_replicas:
                rep = self._spawn("slo_breach")
                if rep is not None:
                    self._record(
                        "scale_up", replica=rep.id,
                        p99_s=p99_up, oldest_wait_s=oldest,
                        slo_p99_s=cfg.slo_p99_s, live=len(self._live()))
                    self._breach_since = None
                    self._slack_since = None
            return
        self._breach_since = None
        p99_down = self.queue_wait_p99(cfg.scale_down_window_s)
        slack = (not self._queue
                 and (p99_down is None
                      or p99_down < cfg.slo_p99_s * SLACK_FACTOR))
        if not slack:
            self._slack_since = None
            return
        if self._slack_since is None:
            self._slack_since = now
            return
        ready = [r for r in live if r.state == "ready"]
        if now - self._slack_since >= cfg.scale_down_window_s \
                and len(live) > cfg.min_replicas and ready:
            rep = max(ready, key=lambda r: r.id)
            rep.state = "draining"
            self._record("scale_down", replica=rep.id, p99_s=p99_down,
                         live=len(self._live()))
            logger.info("fleet: retiring replica %d (slack; p99=%s)",
                        rep.id, p99_down)
            self._breach_since = None
            self._slack_since = None

    def _finish_retire(self, rep: _Replica) -> None:
        if rep.id not in self.replicas:
            return
        del self.replicas[rep.id]
        if rep.sock is not None:
            try:
                rep.sock.close()
            except OSError:
                pass
        terminate_with_grace([(str(rep.id), rep.proc)],
                             self.cfg.term_grace_s)
        self._record("retired", replica=rep.id,
                     live=len(self._live()))

    def _drive_draining(self) -> None:
        for rep in self.replicas.values():
            if rep.state == "draining" and not rep.outstanding \
                    and not rep.shutdown_sent and rep.sock is not None:
                rep.shutdown_sent = True
                try:
                    send_frame(rep.sock, {"kind": "shutdown"})
                except OSError:
                    pass  # already dying; _check_replicas reaps it

    # -- the poll loop ---------------------------------------------------
    def poll(self, timeout: float = 0.0) -> None:
        """One router iteration: accept hellos, pump replica frames,
        reap exits/hangs (failover), dispatch the queue JSQ, drive
        draining retirees, autoscale — then block up to ``timeout``
        for socket activity.  Single-threaded: this IS the router."""
        if self._closed:
            raise RuntimeError("FleetRouter is closed")
        self._read_beats()
        self._accept()
        self._pump_replicas()
        self._check_replicas()
        self._reap()
        self._dispatch()
        self._dispatch_migrations()
        self._drive_draining()
        self._autoscale()
        if timeout > 0:
            socks = [self._listen] + [
                r.sock for r in self.replicas.values()
                if r.sock is not None]
            try:
                select.select(socks, [], [], timeout)
            except (OSError, ValueError):
                pass

    def idle(self) -> bool:
        return not self._queue and not self._migrate_queue and not any(
            r.outstanding for r in self.replicas.values())

    def run_until_idle(self, max_s: float = 300.0) -> None:
        deadline = time.monotonic() + max_s
        while not self.idle():
            self.poll(0.02)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet still busy after {max_s}s: "
                    f"{len(self._queue)} queued, "
                    f"{sum(len(r.outstanding) for r in self.replicas.values())}"
                    " outstanding")

    # -- chaos + shutdown ------------------------------------------------
    def kill_replica(self, replica_id: int) -> None:
        """Chaos hook (bench/tests): SIGKILL one replica — no warning,
        no drain, exactly the poison/preemption shape the failover path
        must absorb."""
        rep = self.replicas.get(replica_id)
        if rep is None:
            raise KeyError(f"no live replica {replica_id}")
        self._record("chaos_kill", replica=replica_id)
        try:
            rep.proc.kill()
        except OSError:
            pass

    def close(self, error: Optional[BaseException] = None) -> None:
        """Idempotent teardown: shutdown frames to the living, SIGTERM→
        grace→SIGKILL the rest, typed failure for every request still
        in flight (a waiter must never hang on a closed fleet)."""
        if self._closed:
            return
        self._closed = True
        err = error if error is not None else FleetClosedError(
            "FleetRouter closed with the request in flight")
        notified = False
        for rep in self.replicas.values():
            if rep.sock is not None:
                try:
                    send_frame(rep.sock, {"kind": "shutdown"})
                    notified = True
                except OSError:
                    pass
        if notified and error is None:
            # give notified replicas the grace window to drain and
            # exit 0 on their OWN (final telemetry flush, eng.close())
            # before any signal lands — terminate_with_grace SIGTERMs
            # immediately, which would make the graceful path dead code
            deadline = time.monotonic() + self.cfg.term_grace_s
            while time.monotonic() < deadline and any(
                    r.proc.poll() is None
                    for r in self.replicas.values()
                    if r.sock is not None):
                time.sleep(0.05)
        terminate_with_grace(
            [(str(r.id), r.proc) for r in self.replicas.values()]
            + self._reaping,
            self.cfg.term_grace_s)
        self._reaping.clear()
        for rep in self.replicas.values():
            if rep.sock is not None:
                try:
                    rep.sock.close()
                except OSError:
                    pass
            for fr in rep.outstanding.values():
                if not fr.done.is_set():
                    fr.error = err
                    self._write_request_record(fr)
                    fr.done.set()
            rep.outstanding.clear()
        for fr in list(self._queue) + list(self._migrate_queue):
            if not fr.done.is_set():
                fr.error = err
                self._write_request_record(fr)
                fr.done.set()
        self._queue.clear()
        self._migrate_queue.clear()
        self.replicas.clear()
        for sock, _, _ in self._greeting:
            try:
                sock.close()
            except OSError:
                pass
        self._greeting.clear()
        try:
            self._listen.close()
        except OSError:
            pass
        try:
            self._records.close()
        except OSError:
            pass
