"""KV tiering: park idle sessions' KV pages on host RAM and disk.

Conversational users who go idle for minutes dominate serving traffic,
yet their prefix-cache KV pages pin HBM forever.  This module is the
serving-side mirror of the ZeRO-Infinity offload discipline the
training engine already has (``runtime/disk_offload.py``): cold pages
spill HBM -> host RAM -> disk and stream back on session resume, at the
page granularity vLLM's swap plane and SGLang's radix cache establish.

The tier owns no device state.  It watches the :class:`PrefixCache`
for leaves that have sat idle for ``idle_park_ticks`` engine ticks,
exports each one's pool page to host bytes, CRC-stamps the copy, and
only THEN releases the pool ref (``PrefixCache.drop_leaf``) — a parked
page's bytes are durable before the pool can hand the page to anyone
else.  Over ``host_budget_pages`` the oldest host copies write back to
``disk_dir`` in PR 15's leaf-state file format verbatim (magic, JSON
section header, per-section CRC, tmp+rename under ``io_retry``); with
no disk tier they are dropped and the session recomputes on resume.

Resume continues the prefix-cache digest chain from where ``match``
stopped: each parked record whose digest matches the next page of the
prompt is fetched (disk read CRC-verifies before any byte re-enters
the pool; the host copy re-verifies at page-in), imported into a fresh
pool page, and handed to admission, which registers the pages back
into the :class:`PrefixCache` — resume IS a prefix-cache hit, and the
delta-aware prefill computes only the unfetched tail.

Robustness is the headline, and all of it rides the two Stages:

* ``kv_spill`` (points ``pageout``, ``write``) — transient failures
  retry up to the budget, then the stage DEGRADES with ONE loud
  warning and sessions simply stay HBM-resident (parking disabled).
* ``kv_fetch`` (points ``read``, ``pagein``) — any fetch failure drops
  the bad record and stops extending the match; the already-verified
  prefix is kept and the remaining tokens recompute from the prompt
  via the existing delta prefill.  A CRC flip raises the typed
  :class:`KVTierCorruptError` BEFORE the page re-enters the pool —
  never a poisoned stream, never a lost request.

``DS_STAGE_FAULT`` / ``DS_STAGE_DELAY_S`` chaos specs target all four
points (docs/stages.md contract table).
"""
from __future__ import annotations

import dataclasses
import json
import os
import struct
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..runtime.resilience import (CheckpointCorruptError, DEFAULT_RETRY,
                                  RetryPolicy, io_retry)
from ..runtime.stages import Stage
from ..utils.logging import logger
from .scheduler import PrefixCache

__all__ = ["KVTier", "KVTierCorruptError", "KVTierDiskStore"]

# PR 15's disk-tier magic, verbatim: a parked-page file and a leaf-state
# file are the same on-disk dialect (magic + little-endian u64 header
# length + JSON section header + CRC'd raw payload).
_MAGIC = b"DSDISK1\n"


class KVTierCorruptError(CheckpointCorruptError):
    """A parked KV page failed verification (bad magic/header, short
    read, CRC flip, size mismatch) — raised BEFORE any byte re-enters
    the pool.  Not an ``OSError``: ``Stage.call`` propagates it on the
    first hit instead of retrying, and the resume path catches it,
    drops the record, and falls back to recompute-from-prompt."""


class KVTierDiskStore:
    """Parked-page files in the disk tier.

    One file per parked page, in PR 15's leaf-state format: ``_MAGIC``,
    ``<Q`` header length, JSON header whose ``sections`` entry carries
    the payload's dtype/shape/CRC/offset, then the raw payload.  Writes
    go to ``<path>.tmp`` and rename into place (a crash mid-write can
    never leave a half-written file under the real name), optionally
    fsynced, all inside ``io_retry``.  Reads verify magic, header, and
    CRC and raise :class:`KVTierCorruptError` before returning bytes —
    a missing file is the same verdict (the record is unservable)."""

    def __init__(self, directory: str, fsync: bool = True,
                 retry: RetryPolicy = DEFAULT_RETRY):
        self.directory = str(directory)
        self.fsync = bool(fsync)
        self.retry = retry
        os.makedirs(self.directory, exist_ok=True)

    def path(self, name: str) -> str:
        return os.path.join(self.directory, f"kv_{name}.page")

    def write(self, name: str, payload: bytes) -> int:
        header = {
            "record": name,
            "sections": {
                "page": {"dtype": "uint8", "store_dtype": "uint8",
                         "shape": [len(payload)],
                         "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
                         "offset": 0, "nbytes": len(payload)},
            },
        }
        blob = json.dumps(header).encode("utf-8")
        path = self.path(name)
        tmp = path + ".tmp"

        def do_write():
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write(struct.pack("<Q", len(blob)))
                f.write(blob)
                f.write(payload)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.rename(tmp, path)

        io_retry(do_write, f"kv-tier write {path}", self.retry)
        return len(payload)

    def read(self, name: str) -> bytes:
        path = self.path(name)

        def do_read():
            with open(path, "rb") as f:
                magic = f.read(len(_MAGIC))
                if magic != _MAGIC:
                    raise KVTierCorruptError(
                        f"kv-tier page {path} has bad magic "
                        f"{magic!r} (expected {_MAGIC!r})")
                raw_len = f.read(8)
                if len(raw_len) != 8:
                    raise KVTierCorruptError(
                        f"kv-tier page {path} is truncated in its "
                        "header length")
                (hlen,) = struct.unpack("<Q", raw_len)
                try:
                    header = json.loads(f.read(hlen).decode("utf-8"))
                except (ValueError, UnicodeDecodeError) as e:
                    raise KVTierCorruptError(
                        f"kv-tier page {path} has an unparseable "
                        f"header: {e}") from e
                ent = (header.get("sections") or {}).get("page")
                if ent is None:
                    raise KVTierCorruptError(
                        f"kv-tier page {path} header has no 'page' "
                        "section")
                base = len(_MAGIC) + 8 + hlen
                f.seek(base + int(ent["offset"]))
                raw = f.read(int(ent["nbytes"]))
                if len(raw) != int(ent["nbytes"]):
                    raise KVTierCorruptError(
                        f"kv-tier page {path} is truncated: section "
                        f"'page' wanted {int(ent['nbytes'])} bytes, "
                        f"got {len(raw)}")
                got = zlib.crc32(raw) & 0xFFFFFFFF
                if got != int(ent["crc32"]):
                    raise KVTierCorruptError(
                        f"kv-tier page {path} failed its CRC check: "
                        f"stored {int(ent['crc32'])}, computed {got}")
                return raw

        try:
            return io_retry(do_read, f"kv-tier read {path}", self.retry)
        except FileNotFoundError as e:
            raise KVTierCorruptError(
                f"kv-tier parked page {path} is missing") from e

    def remove(self, name: str) -> None:
        """Best-effort unlink of a consumed record's file."""
        try:
            os.unlink(self.path(name))
        except OSError:
            pass


@dataclasses.dataclass
class _Parked:
    """One parked page.  ``payload`` is the host copy (``None`` once
    written back to disk or consumed); ``crc``/``nbytes`` stamp it the
    moment it leaves the pool and gate every re-entry."""
    kind: str                          # "full" | "partial"
    key: str                           # chain digest / parent digest
    tokens: Optional[Tuple[int, ...]]  # partial's literal tokens
    parent: str                        # parent digest (session chain)
    crc: int
    nbytes: int
    payload: Optional[bytes]
    stamp: int                         # park order, oldest spills first
    on_disk: bool = False
    dead: bool = False                 # consumed/dropped (lazy dequeue)

    def record_name(self) -> str:
        if self.kind == "partial":
            return PrefixCache._digest(self.key + "#p",
                                       self.tokens or ())
        return self.key


class KVTier:
    """Host/disk tier for cold prefix-cache KV pages.

    The engine calls :meth:`park_tick` once per tick (before
    admission, so freed pages are immediately allocatable) and
    :meth:`resume` from paged admission to extend a prefix-cache match
    with parked pages.  Everything else — budgets, write-back, CRC
    discipline, degradation — is internal.

    ``exporter(page) -> bytes`` and ``importer(page, payload)`` are the
    engine's device<->host seams (``_export_page_bytes`` /
    ``_import_page_bytes``); the tier never touches device arrays."""

    def __init__(self, *, page_len: int, pool, prefix: PrefixCache,
                 exporter: Callable[[int], bytes],
                 importer: Callable[[int, bytes], None],
                 idle_park_ticks: int, host_budget_pages: int = 256,
                 disk_dir: Optional[str] = None, fsync: bool = True,
                 max_failures: Optional[int] = None,
                 retry: RetryPolicy = DEFAULT_RETRY):
        self.page_len = int(page_len)
        self.pool = pool
        self.prefix = prefix
        self.exporter = exporter
        self.importer = importer
        self.idle_park_ticks = int(idle_park_ticks)
        self.host_budget_pages = int(host_budget_pages)
        self.disk = (KVTierDiskStore(disk_dir, fsync=fsync, retry=retry)
                     if disk_dir else None)
        self.spill_stage = Stage(
            "kv_spill", max_failures=max_failures,
            fallback="HBM-resident sessions (parking disabled)")
        self.fetch_stage = Stage(
            "kv_fetch", max_failures=max_failures,
            fallback="recompute-from-prompt resume")
        # parked-record index, keyed the same way the prefix cache is
        self._full: Dict[str, _Parked] = {}
        self._partials: Dict[str, Dict[Tuple[int, ...], _Parked]] = {}
        # host-residency accounting: deque in park order (lazy-skip of
        # dead/diskized records) + an exact resident-page count
        self._host: deque = deque()
        self._host_pages = 0
        # idleness tracking: (last_hit snapshot, tick it was taken)
        self._seen: Dict[Tuple[str, str, Optional[Tuple[int, ...]]],
                         Tuple[int, int]] = {}
        self._stamp = 0
        self._closed = False
        # counters (the engine's flush mirrors these into telemetry)
        self.spill_bytes = 0
        self.fetch_bytes = 0
        self.parked_pages_total = 0
        self.resumed_pages_total = 0
        self.resumed_sessions_total = 0
        self.corrupt_total = 0
        self.dropped_total = 0
        self.resume_s: deque = deque(maxlen=2048)

    # -- inventory -------------------------------------------------------
    @property
    def parked_pages(self) -> int:
        return len(self._full) + sum(len(b)
                                     for b in self._partials.values())

    @property
    def parked_sessions(self) -> int:
        """Parked chain TAILS — the sessions this tier is holding off
        HBM (a mid-chain full page whose child is also parked is one
        session, not two)."""
        parents = {r.parent for r in self._full.values()}
        n = sum(len(b) for b in self._partials.values())
        n += sum(1 for d in self._full
                 if d not in parents and d not in self._partials)
        return n

    def resume_p99_s(self) -> Optional[float]:
        if not self.resume_s:
            return None
        xs = sorted(self.resume_s)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    # -- park plane (kv_spill) -------------------------------------------
    def park_tick(self, tick: int) -> int:
        """Scan the prefix cache for leaves idle >= ``idle_park_ticks``
        engine ticks and park them.  Never raises: a failing record
        stays HBM-resident and the scan stops; persistent failures
        degrade ``kv_spill`` (ONE warning) and the tier goes dormant."""
        if self._closed or self.idle_park_ticks <= 0 \
                or self.spill_stage.degraded:
            return 0
        live = set()
        cands = []
        for last_hit, (kind, key, sub) in self.prefix._evictable():
            k = (kind, key, sub)
            live.add(k)
            prev = self._seen.get(k)
            if prev is None or prev[0] != last_hit:
                self._seen[k] = (last_hit, tick)
            elif tick - prev[1] >= self.idle_park_ticks:
                cands.append((kind, key, sub))
        for k in [k for k in self._seen if k not in live]:
            del self._seen[k]
        parked = 0
        for kind, key, sub in cands:
            if self._closed or self.spill_stage.degraded:
                break
            try:
                self._park_one(kind, key, sub)
            except Exception as e:
                # the entry is still fully HBM-resident (the pool ref
                # is only dropped after the host copy is stamped) —
                # log, leave it, stop this tick's scan
                logger.error(
                    "kv tier: parking a %s entry failed; the session "
                    "stays HBM-resident: %r", kind, e)
                break
            parked += 1
            self._seen.pop((kind, key, sub), None)
        return parked

    def _park_one(self, kind: str, key: str,
                  sub: Optional[Tuple[int, ...]]) -> None:
        if kind == "partial":
            entry = self.prefix.partials[key][sub]
            parent = key
        else:
            entry = self.prefix.full[key]
            parent = entry.parent
        page = int(entry.page)
        payload = self.spill_stage.call(
            "pageout", lambda: self.exporter(page), path=f"page={page}")
        rec = _Parked(kind=kind, key=key, tokens=sub, parent=parent,
                      crc=zlib.crc32(payload) & 0xFFFFFFFF,
                      nbytes=len(payload), payload=payload,
                      stamp=self._stamp)
        self._stamp += 1
        if kind == "partial":
            self._partials.setdefault(key, {})[sub] = rec
        else:
            self._full[key] = rec
        # the host copy is CRC-stamped — only NOW may the pool ref go
        self.prefix.drop_leaf(kind, key, sub)
        self._host.append(rec)
        self._host_pages += 1
        self.parked_pages_total += 1
        self.spill_bytes += len(payload)
        self._shed_host()

    def _shed_host(self) -> None:
        """Write the oldest host copies back to disk (or drop them,
        with no disk tier) until the host budget holds."""
        while self._host_pages > self.host_budget_pages:
            rec = self._host.popleft()
            if rec.dead or rec.payload is None:
                continue
            if self.disk is None:
                self._remove(rec)
                self.dropped_total += 1
                continue
            payload = rec.payload
            name = rec.record_name()
            try:
                self.spill_stage.call(
                    "write",
                    lambda: self.disk.write(name, payload),
                    path=self.disk.path(name))
            except Exception as e:
                # keep the host copy; a later tick (or drain) retries
                self._host.appendleft(rec)
                logger.error(
                    "kv tier: host->disk write-back failed; keeping "
                    "the host copy: %r", e)
                break
            rec.payload = None
            rec.on_disk = True
            self._host_pages -= 1

    def drain(self) -> int:
        """Write EVERY host-resident parked page to the disk tier —
        the close-time drain barrier.  No-op without a disk tier."""
        if self.disk is None:
            return 0
        n = 0
        for rec in list(self._host):
            if rec.dead or rec.payload is None:
                continue
            payload = rec.payload
            name = rec.record_name()
            try:
                self.spill_stage.call(
                    "write",
                    lambda: self.disk.write(name, payload),
                    path=self.disk.path(name))
            except Exception as e:
                logger.error(
                    "kv tier: drain write-back failed: %r", e)
                break
            rec.payload = None
            rec.on_disk = True
            self._host_pages -= 1
            n += 1
        return n

    # -- resume plane (kv_fetch) -----------------------------------------
    def resume(self, prompt: Sequence[int], namespace: str, pos: int,
               alloc: Callable[[int], Optional[List[int]]],
               ) -> Tuple[int, List[int]]:
        """Extend a prefix-cache match with parked pages.

        ``pos`` is where ``PrefixCache.match`` stopped (page-aligned,
        no COW tail).  Walks the digest chain forward: every parked
        full record matching the next page of ``prompt`` is fetched,
        verified, and imported into a fresh pool page; a parked partial
        tail extends the match mid-page (the page is private, so no COW
        is needed).  Consumed records leave the tier — admission's
        ``PrefixCache.insert`` re-registers the pages.

        Returns ``(new_pos, pages)``; the pages carry one pool ref each
        and belong to the caller.  Any tier failure (corrupt record,
        I/O error, pool dry) stops the extension with the verified
        prefix intact — the remaining tokens recompute via the delta
        prefill.  Never raises for tier-internal failures."""
        limit = len(prompt) - 1
        pages: List[int] = []
        if self._closed or not self.parked_pages:
            return pos, pages
        t0 = time.perf_counter()
        parent = namespace
        q = 0
        while q + self.page_len <= pos:
            parent = PrefixCache._digest(
                parent, prompt[q:q + self.page_len])
            q += self.page_len
        try:
            while pos + self.page_len <= limit:
                d = PrefixCache._digest(
                    parent, prompt[pos:pos + self.page_len])
                rec = self._full.get(d)
                if rec is None or rec.dead:
                    break
                if not self._fetch_into(rec, alloc, pages):
                    break
                parent = d
                pos += self.page_len
            bucket = self._partials.get(parent)
            if bucket:
                best: Optional[Tuple[Tuple[int, ...], _Parked]] = None
                remaining = prompt[pos:]
                for toks, rec in bucket.items():
                    m = len(toks)
                    if not rec.dead and m <= limit - pos \
                            and tuple(remaining[:m]) == toks \
                            and (best is None or m > len(best[0])):
                        best = (toks, rec)
                if best is not None \
                        and self._fetch_into(best[1], alloc, pages):
                    pos += len(best[0])
        except BaseException:
            # only a non-tier failure (device import crash, interrupt)
            # lands here; the fetched pages never reached the caller —
            # return their refs before re-raising
            for p in pages:
                self.pool.deref(p)
            raise
        if pages:
            self.resumed_pages_total += len(pages)
            self.resumed_sessions_total += 1
            self.resume_s.append(time.perf_counter() - t0)
        return pos, pages

    def _fetch_into(self, rec: _Parked,
                    alloc: Callable[[int], Optional[List[int]]],
                    pages: List[int]) -> bool:
        """Fetch ONE parked record into a fresh pool page; append the
        page to ``pages`` and consume the record on success.  Tier
        failures drop the record and return False (recompute covers
        it); non-tier failures propagate with the page ref returned."""
        got = alloc(1)
        if got is None:
            return False
        page = got[0]
        try:
            payload = rec.payload
            if payload is None:
                name = rec.record_name()
                payload = self.fetch_stage.call(
                    "read", lambda: self.disk.read(name),
                    path=self.disk.path(name))

            def _pagein():
                crc = zlib.crc32(payload) & 0xFFFFFFFF
                if len(payload) != rec.nbytes or crc != rec.crc:
                    raise KVTierCorruptError(
                        "parked page failed its host CRC check: "
                        f"stored ({rec.crc}, {rec.nbytes}B), got "
                        f"({crc}, {len(payload)}B)")
                self.importer(page, payload)

            self.fetch_stage.call("pagein", _pagein,
                                  path=f"page={page}")
        except KVTierCorruptError as e:
            self.pool.deref(page)
            self._remove(rec)
            self.corrupt_total += 1
            logger.error(
                "kv tier: parked page failed verification — dropping "
                "the record; resume falls back to recompute-from-"
                "prompt: %s", e)
            return False
        except OSError as e:
            self.pool.deref(page)
            self._remove(rec)
            logger.error(
                "kv tier: fetch failed (%r) — dropping the record; "
                "resume falls back to recompute-from-prompt", e)
            return False
        except BaseException:
            self.pool.deref(page)
            raise
        self.fetch_bytes += rec.nbytes
        self._remove(rec)
        pages.append(page)
        return True

    # -- record bookkeeping ----------------------------------------------
    def _remove(self, rec: _Parked) -> None:
        """Consume/drop one record: index removal, host accounting,
        best-effort disk cleanup.  Idempotent."""
        if rec.dead:
            return
        rec.dead = True
        if rec.payload is not None:
            rec.payload = None
            self._host_pages -= 1
        if rec.kind == "partial":
            bucket = self._partials.get(rec.key)
            if bucket is not None and bucket.get(rec.tokens) is rec:
                del bucket[rec.tokens]
                if not bucket:
                    del self._partials[rec.key]
        elif self._full.get(rec.key) is rec:
            del self._full[rec.key]
        if rec.on_disk and self.disk is not None:
            self.disk.remove(rec.record_name())

    # -- close plane -------------------------------------------------------
    def close_spill(self) -> None:
        """Stop parking (the ``kv_spill`` graph close) — resume keeps
        working on whatever is already parked."""
        self._closed = True

    def close(self) -> None:
        """Drop every parked record (the ``kv_fetch`` graph close).
        Records hold host/disk bytes only — no pool refs to return."""
        self._closed = True
        for rec in list(self._full.values()):
            self._remove(rec)
        for bucket in list(self._partials.values()):
            for rec in list(bucket.values()):
                self._remove(rec)
        self._full.clear()
        self._partials.clear()
        self._host.clear()
        self._host_pages = 0
        self._seen.clear()
