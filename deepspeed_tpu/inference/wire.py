"""Minimal length-prefixed socket protocol for the serving fleet
(docs/serving.md "serving fleet").

One frame = a 4-byte big-endian length prefix + a UTF-8 JSON object.
That is the ENTIRE protocol: the router stays import-light (stdlib
only, no serialization deps) and a replica stays an ordinary
``ServeEngine`` with a socket pump bolted on.  Frames are small host
bookkeeping (token ids, rids, gauges) — never tensors — so JSON's
overhead is noise next to a decode tick.

Frame kinds (the ``kind`` key):

  replica → router
    ``hello``     {replica, pid}            connection handshake
    ``admit``     {rid}                     the engine admitted rid —
                                            the router stamps queue
                                            wait NOW (the SLO signal)
    ``token``     {rid, toks: [int, ...]}   newly generated tokens
    ``done``      {rid, reason, tokens_total}
    ``error``     {rid, error}              per-request failure
    ``migrate_out`` {rid, first_token, kv_len, pages, ...}
                                            a prefill replica finished
                                            rid's prefill; ``pages``
                                            binary page frames follow
  router → replica
    ``submit``    {rid, prompt, max_new_tokens, eos_id[, migrate]}
    ``migrate_in``  {rid, prompt, first_token, kv_len, pages, ...}
                                            adopt rid mid-decode; the
                                            binary page frames follow
    ``shutdown``  {}                        drain in-flight, then exit 0

Binary page frames (disaggregated prefill/decode, docs/serving.md
"disaggregated fleet"): KV pages are tensors, so JSON is the wrong
envelope.  A binary frame sets the top bit of the 4-byte length prefix
and its body is ``[4-byte header length][JSON header][raw payload]
[4-byte CRC32]`` — the CRC covers everything before it, and a mismatch
raises :class:`WireError` (connection-fatal: a corrupt page must fail
the CONNECTION, never be silently adopted into a KV pool).  JSON and
binary frames interleave freely on one socket; :class:`FrameReader`
yields dicts for JSON frames and :class:`BinaryFrame` objects (which
quack like dicts for ``get``) for binary ones.

Framing is torn-read safe by construction: :class:`FrameReader`
buffers partial reads and yields only complete frames, so a
non-blocking pump can feed it whatever ``recv`` returned — including a
read torn mid page payload.  An oversized or non-JSON frame raises
:class:`WireError` — a corrupt stream must fail the CONNECTION (the
router's failover path), never silently resync.
"""
from __future__ import annotations

import json
import select
import socket
import struct
import zlib
from collections import deque
from typing import List, Tuple, Union

#: hard frame cap — a fleet frame is host bookkeeping or ONE bounded
#: KV page, so anything bigger is a corrupt length prefix, not a real
#: message
MAX_FRAME_BYTES = 16 << 20

_LEN = struct.Struct(">I")

#: top bit of the length prefix marks a BINARY frame (header + raw
#: payload + CRC32); clear = the original JSON frame.  The cap keeps
#: lengths below 2**31, so the bit is unambiguous.
BINARY_FLAG = 0x80000000


class WireError(RuntimeError):
    """Corrupt framing (oversized length, non-JSON payload, CRC
    mismatch on a binary page frame): the connection is unrecoverable —
    tear it down and fail over."""


class BinaryFrame:
    """One decoded binary frame: a JSON ``header`` dict riding a raw
    byte ``payload`` (a KV page on the migration path).  ``get``/
    ``kind`` delegate to the header so frame-dispatch loops written for
    JSON dicts handle both shapes."""

    __slots__ = ("header", "payload")

    def __init__(self, header: dict, payload: bytes):
        self.header = header
        self.payload = payload

    def get(self, key, default=None):
        return self.header.get(key, default)

    @property
    def kind(self):
        return self.header.get("kind")

    def __repr__(self):
        return (f"BinaryFrame({self.header!r}, "
                f"<{len(self.payload)} bytes>)")


def encode_frame(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise WireError(f"frame of {len(payload)} bytes exceeds the "
                        f"{MAX_FRAME_BYTES}-byte cap")
    return _LEN.pack(len(payload)) + payload


def encode_binary_frame(header: dict, payload: bytes) -> bytes:
    """One binary frame: flagged length prefix + [header length][JSON
    header][payload][CRC32 of everything before the CRC]."""
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    body = _LEN.pack(len(hdr)) + hdr + bytes(payload)
    body += _LEN.pack(zlib.crc32(body) & 0xFFFFFFFF)
    if len(body) > MAX_FRAME_BYTES:
        raise WireError(
            f"binary frame of {len(body)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap (a KV page is bounded — "
            "split the transfer per page)")
    return _LEN.pack(BINARY_FLAG | len(body)) + body


def send_binary_frame(sock: socket.socket, header: dict,
                      payload: bytes) -> None:
    sock.sendall(encode_binary_frame(header, payload))


def send_frame(sock: socket.socket, obj: dict) -> None:
    """Blocking send of one frame (``sendall`` — frames are small, and
    a partial write would corrupt the stream for every later frame)."""
    sock.sendall(encode_frame(obj))


class FrameReader:
    """Incremental decoder.  ``feed(data)`` buffers whatever a
    (possibly non-blocking) ``recv`` returned and returns the complete
    frames it closed over — zero, one, or many.  Frames a caller sets
    aside (e.g. everything after a ``hello`` during the handshake)
    ride ``pending`` until the next :func:`drain_socket`."""

    def __init__(self):
        self._buf = bytearray()
        self.pending: deque = deque()

    def feed(self, data: bytes) -> List[Union[dict, BinaryFrame]]:
        self._buf.extend(data)
        frames: List[Union[dict, BinaryFrame]] = []
        while True:
            if len(self._buf) < _LEN.size:
                return frames
            (raw,) = _LEN.unpack_from(self._buf)
            binary = bool(raw & BINARY_FLAG)
            n = raw & ~BINARY_FLAG
            if n > MAX_FRAME_BYTES:
                raise WireError(
                    f"frame length {n} exceeds the {MAX_FRAME_BYTES}-"
                    "byte cap (corrupt stream)")
            if len(self._buf) < _LEN.size + n:
                return frames
            payload = bytes(self._buf[_LEN.size:_LEN.size + n])
            del self._buf[:_LEN.size + n]
            frames.append(self._parse_binary(payload) if binary
                          else self._parse_json(payload))

    @staticmethod
    def _parse_json(payload: bytes) -> dict:
        try:
            obj = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise WireError(f"unparseable frame payload: {e}")
        if not isinstance(obj, dict):
            raise WireError(
                f"frame must be a JSON object, got "
                f"{type(obj).__name__}")
        return obj

    @staticmethod
    def _parse_binary(body: bytes) -> BinaryFrame:
        # body = [4-byte header len][JSON header][payload][CRC32]; the
        # CRC covers everything before it.  Any violation is a
        # connection-fatal WireError — a corrupt page must never be
        # silently adopted into a KV pool.
        if len(body) < 2 * _LEN.size:
            raise WireError(
                f"binary frame body of {len(body)} bytes is shorter "
                "than its fixed fields (corrupt stream)")
        (want,) = _LEN.unpack_from(body, len(body) - _LEN.size)
        got = zlib.crc32(body[:-_LEN.size]) & 0xFFFFFFFF
        if got != want:
            raise WireError(
                f"binary frame CRC mismatch: computed {got:#010x}, "
                f"frame says {want:#010x} (corrupt stream)")
        (hlen,) = _LEN.unpack_from(body)
        if _LEN.size + hlen > len(body) - _LEN.size:
            raise WireError(
                f"binary frame header length {hlen} overruns the "
                f"{len(body)}-byte body (corrupt stream)")
        header = FrameReader._parse_json(body[_LEN.size:_LEN.size + hlen])
        return BinaryFrame(header, body[_LEN.size + hlen:-_LEN.size])


def drain_socket(sock: socket.socket, reader: FrameReader) -> \
        Tuple[List[Union[dict, BinaryFrame]], bool]:
    """Non-blocking drain: every complete frame currently readable
    (including any the reader had pending), plus whether the peer
    CLOSED the connection (EOF).  Works on blocking sockets too — each
    ``recv`` is gated by a zero-timeout ``select``, so a drain never
    stalls a single-threaded pump loop."""
    frames: List[Union[dict, BinaryFrame]] = list(reader.pending)
    reader.pending.clear()
    closed = False
    while True:
        try:
            readable, _, _ = select.select([sock], [], [], 0)
        except (OSError, ValueError):
            closed = True
            break
        if not readable:
            break
        try:
            data = sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            break
        except OSError:
            closed = True
            break
        if not data:
            closed = True
            break
        frames.extend(reader.feed(data))
    return frames, closed
