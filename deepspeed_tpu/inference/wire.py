"""Minimal length-prefixed socket protocol for the serving fleet
(docs/serving.md "serving fleet").

One frame = a 4-byte big-endian length prefix + a UTF-8 JSON object.
That is the ENTIRE protocol: the router stays import-light (stdlib
only, no serialization deps) and a replica stays an ordinary
``ServeEngine`` with a socket pump bolted on.  Frames are small host
bookkeeping (token ids, rids, gauges) — never tensors — so JSON's
overhead is noise next to a decode tick.

Frame kinds (the ``kind`` key):

  replica → router
    ``hello``     {replica, pid}            connection handshake
    ``admit``     {rid}                     the engine admitted rid —
                                            the router stamps queue
                                            wait NOW (the SLO signal)
    ``token``     {rid, toks: [int, ...]}   newly generated tokens
    ``done``      {rid, reason, tokens_total}
    ``error``     {rid, error}              per-request failure
  router → replica
    ``submit``    {rid, prompt, max_new_tokens, eos_id}
    ``shutdown``  {}                        drain in-flight, then exit 0

Framing is torn-read safe by construction: :class:`FrameReader`
buffers partial reads and yields only complete frames, so a
non-blocking pump can feed it whatever ``recv`` returned.  An
oversized or non-JSON frame raises :class:`WireError` — a corrupt
stream must fail the CONNECTION (the router's failover path), never
silently resync.
"""
from __future__ import annotations

import json
import select
import socket
import struct
from collections import deque
from typing import List, Tuple

#: hard frame cap — a fleet frame is host bookkeeping, so anything
#: megabytes long is a corrupt length prefix, not a real message
MAX_FRAME_BYTES = 16 << 20

_LEN = struct.Struct(">I")


class WireError(RuntimeError):
    """Corrupt framing (oversized length, non-JSON payload): the
    connection is unrecoverable — tear it down and fail over."""


def encode_frame(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise WireError(f"frame of {len(payload)} bytes exceeds the "
                        f"{MAX_FRAME_BYTES}-byte cap")
    return _LEN.pack(len(payload)) + payload


def send_frame(sock: socket.socket, obj: dict) -> None:
    """Blocking send of one frame (``sendall`` — frames are small, and
    a partial write would corrupt the stream for every later frame)."""
    sock.sendall(encode_frame(obj))


class FrameReader:
    """Incremental decoder.  ``feed(data)`` buffers whatever a
    (possibly non-blocking) ``recv`` returned and returns the complete
    frames it closed over — zero, one, or many.  Frames a caller sets
    aside (e.g. everything after a ``hello`` during the handshake)
    ride ``pending`` until the next :func:`drain_socket`."""

    def __init__(self):
        self._buf = bytearray()
        self.pending: deque = deque()

    def feed(self, data: bytes) -> List[dict]:
        self._buf.extend(data)
        frames: List[dict] = []
        while True:
            if len(self._buf) < _LEN.size:
                return frames
            (n,) = _LEN.unpack_from(self._buf)
            if n > MAX_FRAME_BYTES:
                raise WireError(
                    f"frame length {n} exceeds the {MAX_FRAME_BYTES}-"
                    "byte cap (corrupt stream)")
            if len(self._buf) < _LEN.size + n:
                return frames
            payload = bytes(self._buf[_LEN.size:_LEN.size + n])
            del self._buf[:_LEN.size + n]
            try:
                obj = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, ValueError) as e:
                raise WireError(f"unparseable frame payload: {e}")
            if not isinstance(obj, dict):
                raise WireError(
                    f"frame must be a JSON object, got "
                    f"{type(obj).__name__}")
            frames.append(obj)


def drain_socket(sock: socket.socket,
                 reader: FrameReader) -> Tuple[List[dict], bool]:
    """Non-blocking drain: every complete frame currently readable
    (including any the reader had pending), plus whether the peer
    CLOSED the connection (EOF).  Works on blocking sockets too — each
    ``recv`` is gated by a zero-timeout ``select``, so a drain never
    stalls a single-threaded pump loop."""
    frames: List[dict] = list(reader.pending)
    reader.pending.clear()
    closed = False
    while True:
        try:
            readable, _, _ = select.select([sock], [], [], 0)
        except (OSError, ValueError):
            closed = True
            break
        if not readable:
            break
        try:
            data = sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            break
        except OSError:
            closed = True
            break
        if not data:
            closed = True
            break
        frames.extend(reader.feed(data))
    return frames, closed
