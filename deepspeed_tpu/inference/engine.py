"""ServeEngine — the KV-cached decode engine (docs/serving.md).

The serving half of the north star: requests stream through a bounded
queue into a FIXED pool of decode slots, and two compiled programs
serve every mix —

  ``prefill``      one request's prompt (right-padded to the static
                   ``serving.prefill_len`` bucket) → its K/V rows
                   written into the assigned slot + the first greedy
                   token.
  ``decode_step``  ONE masked tick for ALL slots at once: each active
                   slot's last token in, its next greedy token out, its
                   K/V appended in place.  Free/finished slots ride
                   along masked.  Static shapes by construction: the
                   request mix NEVER changes a program shape, so
                   ``recompiles_total{program=decode_step}`` stays 0
                   (asserted by tests/test_inference.py).

Admission/eviction are the continuous-batching moves (Orca, PAPERS.md):
a finished slot is refilled on the very next tick instead of waiting
for the batch to drain.  The KV cache rides the layouts of
``kv_cache.py`` — TP-sharded heads, DP-sharded slots/pages — via the
ordinary mesh plumbing.

Paged mode (``serving.page_len > 0`` — PagedAttention + RadixAttention,
PAPERS.md): KV storage becomes a flat pool of fixed-size pages and each
slot gets a host-owned int32 page table passed as a TRACED operand, so
a short request holds ``ceil(len/page_len)`` pages instead of a full
``max_seq_len`` stride — the pool, not the slot count, caps how many
users fit a chip (bench_serve.py --paged proves the multiple).  The
scheduler grows a refcounted page allocator (free-list alloc on
admission/append, free on eviction; ``kv_capacity`` finishes become
pool-exhaustion-aware and admission backpressures when even prefix-
cache eviction can't free enough pages) and, on top, PREFIX CACHING:
prompt prefixes hash to refcounted read-only shared pages, a divergent
append copy-on-writes the last partial page, and the prefill program
computes only the uncached delta — N requests sharing a system prompt
store and prefill it once.

Speculative decoding (``serving.speculate_k > 0`` — Leviathan et al.
2023, Chen et al. 2023, PAPERS.md): a small DRAFT model (the
``serving.draft`` config block; its own fixed-stride slot KV cache)
proposes k tokens per tick in one compiled propose program, and the
target scores all k+1 positions per slot in ONE widened
``verify_step`` program — the pass that used to buy one token now buys
``accepted + 1`` of them, so wall-clock per token scales with
1/mean-accepted-length (bench_serve.py --spec proves it on CPU).
Greedy acceptance emits exactly the non-speculative stream (the parity
bar); ``serving.temperature > 0`` switches to rejection-sampling
acceptance that recovers the target distribution
(inference/speculative.py).  Rollback: unpaged masks lengths back;
paged frees the pages only rejected speculation touched.  Accepted-
length variance makes per-slot progress uneven — exactly what the
masked slot machinery absorbs.

Quantized serving (``serving.quantization``, docs/serving.md): two
independently togglable int8 arms, both STATIC for the engine's life.
``weights='int8'`` quantizes the GPT-2 matmul weights per output
channel at build (LLM.int8, PAPERS.md) and fuses dequant into the
serving matmuls — the fp master never reaches the device, params HBM
~ halves (the ``serve_param_bytes`` plane measures it).
``kv='int8'`` stores the paged pool as int8 rows + per-row fp32 scale
sidecars, quantized on write inside the compiled programs and
dequantized fused in the decode kernels — ~2x more pages in the same
KV bytes (``bench_serve.py --quant`` proves the admitted-concurrency
multiple), composing multiplicatively with paging and making the
speculative draft plane nearly free.  Default off = every program
bitwise-unchanged.

Fault plane: the request queue is a stages.py :class:`Channel` and all
serving work runs under one :class:`Stage` record ("serve", points
``admit``/``step``), so poison/drain semantics, graceful degradation
(budget-exhausted → chaos-free direct serving) and the unified
``DS_STAGE_FAULT``/``DS_STAGE_DELAY_S`` spec apply unchanged — the
bench's A/B leg injects its synthetic per-tick device time through
exactly that knob.  In spec mode one delay unit buys one TARGET pass
(a whole verify block), not one token — docs/stages.md.
"""
from __future__ import annotations

import contextlib
import json
import time
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config.config import (DeepSpeedConfig, DeepSpeedServingConfig,
                             DeepSpeedStagesConfig,
                             DeepSpeedTelemetryConfig)
from ..config import constants as C
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, build_mesh
from ..runtime.engine_stages import wire_serve_stage_plane
from ..runtime.stages import Channel, Stage, injected_delay
from ..utils.logging import logger
from .kv_cache import (KVCacheSpec, PagedKVCacheSpec, cache_shardings,
                       init_cache, init_paged_cache,
                       paged_cache_shardings, shard_cache,
                       validate_cache_mesh, validate_paged_cache_mesh)
from .scheduler import PagePool, PrefixCache, Request, SlotScheduler
from .speculative import select_next_token, speculative_accept


class _ServeConfigView:
    """The three config blocks serving needs, from a dict / json path /
    full DeepSpeedConfig — without dragging in the training-only batch
    triangle."""

    def __init__(self, src):
        if isinstance(src, DeepSpeedConfig):
            self.serving = src.serving_config
            self.telemetry = src.telemetry_config
            self.stages = src.stages_config
            return
        if isinstance(src, str):
            with open(src) as f:
                src = json.load(f)
        pd = dict(src or {})
        self.serving = DeepSpeedServingConfig(pd)
        self.telemetry = DeepSpeedTelemetryConfig(pd)
        self.stages = DeepSpeedStagesConfig(pd)


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    from ..telemetry.cli import _percentile as p
    return p(sorted_vals, q)


class ServeEngine:
    """Continuous-batching greedy decode over a GPT-2-family model.

    ``model`` must expose the serving protocol (``GPT2Model`` and its
    flavors do): ``prefill(params, tokens) -> (logits, k, v)`` and
    ``decode_step(params, tokens, k, v, lengths, active, impl=...)``.
    Any decoder exposing that pair serves unchanged; encoder scoring
    (BERT) maps onto a prefill-only protocol adapter — noted as the
    follow-up in docs/serving.md.
    """

    def __init__(self, model, config=None, mesh=None, params=None,
                 seed: int = 0, draft_params=None):
        self.model = model
        cfg = _ServeConfigView(config)
        self.serving_config = cfg.serving
        mcfg = model.config
        if mesh is None:
            # serving default: one replica on one device; pass a
            # (data, model) mesh for DP/TP serving
            mesh = build_mesh(pp=1, dp=1, tp=1,
                              devices=jax.devices()[:1])
        self.mesh = mesh

        self.max_seq_len = (cfg.serving.max_seq_len
                            or int(mcfg.n_positions))
        self.prefill_len = cfg.serving.prefill_len or self.max_seq_len
        if self.max_seq_len > mcfg.n_positions:
            raise ValueError(
                f"serving.max_seq_len={self.max_seq_len} exceeds the "
                f"model's n_positions={mcfg.n_positions}")
        if self.prefill_len > self.max_seq_len:
            raise ValueError(
                f"serving.prefill_len={self.prefill_len} exceeds "
                f"max_seq_len={self.max_seq_len}")
        self.slots = cfg.serving.slots
        self.eos_id_default = (None if cfg.serving.eos_id < 0
                               else cfg.serving.eos_id)
        if cfg.serving.decode_impl == "auto":
            from ..models.gpt2 import _decode_attn_impl
            self.decode_impl = _decode_attn_impl(mcfg)
        else:
            self.decode_impl = cfg.serving.decode_impl
        #: draft-verify speculation (0 = off — the parity reference arm)
        self.spec_k = cfg.serving.speculate_k
        #: STATIC sampling temperature: it selects the compiled
        #: emission/acceptance arm for the engine's lifetime, so
        #: changing it can never recompile mid-serve
        self.temperature = cfg.serving.temperature
        #: quantized serving plane (docs/serving.md "quantized
        #: serving"): both arms are STATIC — they select compiled
        #: program shapes/dtypes for the engine's lifetime
        self.quant_weights = (
            cfg.serving.quantization["weights"] == "int8")
        self.quant_kv = cfg.serving.quantization["kv"] == "int8"
        self._rng_base = (jax.random.PRNGKey(seed ^ 0x5eed)
                          if self.temperature > 0 else None)
        self._rng_n = 0
        self._spec_proposed_n = 0
        self._spec_accepted_n = 0
        self._spec_passes = 0

        # -- params + cache, sharded over the mesh -----------------------
        if params is None:
            params = model.init(jax.random.PRNGKey(seed))
        pspecs = model.param_partition_specs(params)
        if pspecs is None:
            pspecs = jax.tree.map(lambda _: P(), params)
        if self.quant_weights:
            # one-shot post-load quantization (LLM.int8, PAPERS.md):
            # the fp master tree stays on the host — only int8 weights
            # + fp32 scale rows are placed on the mesh, so params HBM
            # ~ halves vs fp16 (collect_memory_stats / the
            # serve_param_bytes gauge are the measurement plane)
            from .quantize import (quantize_gpt2_params,
                                   quantized_partition_specs)
            params = quantize_gpt2_params(params)
            pspecs = quantized_partition_specs(pspecs)
        self._param_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), pspecs,
            is_leaf=lambda s: isinstance(s, P))
        self.params = jax.tree.map(jax.device_put, params,
                                   self._param_shardings)
        wte = params["wte"] if isinstance(params, dict) else None
        kv_dtype = wte.dtype if wte is not None else jnp.float32
        self.page_len = cfg.serving.page_len
        self.paged = self.page_len > 0
        #: chunked prefill (Sarathi-Serve, PAPERS.md; docs/serving.md
        #: "disaggregated fleet"): > 0 = prompts with a longer uncached
        #: delta admit immediately and prefill one chunk per step(),
        #: co-scheduled with decode ticks (config requires paged)
        self.prefill_chunk_len = (cfg.serving.prefill_chunk_len
                                  if self.paged else 0)
        if self.quant_kv and not self.paged:
            raise ValueError(
                "serving.quantization.kv='int8' requires a paged cache "
                "(serving.page_len > 0); the slot layout keeps the "
                "master dtype")
        if self.paged:
            self.max_pages = -(-self.max_seq_len // self.page_len)
            pages = cfg.serving.pages
            if pages == 0:
                # capacity-neutral auto-size: every slot can still reach
                # max_seq_len, plus the scratch page, rounded up to the
                # data width so the pool DP-shards evenly
                pages = 1 + self.slots * self.max_pages
                dp = mesh.shape.get(DATA_AXIS, 1)
                pages += (-pages) % dp
            self.cache_spec = PagedKVCacheSpec(
                layers=mcfg.n_layer, slots=self.slots,
                heads=mcfg.n_head, pages=pages, page_len=self.page_len,
                head_dim=mcfg.d_head, max_pages=self.max_pages,
                dtype=(jnp.int8 if self.quant_kv else kv_dtype),
                quant=self.quant_kv)
            validate_paged_cache_mesh(mesh, self.cache_spec)
            self._cache_shardings = paged_cache_shardings(
                mesh, quant=self.quant_kv)
            self.cache = shard_cache(init_paged_cache(self.cache_spec),
                                     mesh, self._cache_shardings)
            self.pool = PagePool(pages)
            self.prefix = (PrefixCache(self.page_len, self.pool)
                           if cfg.serving.prefix_cache else None)
            #: host-owned page tables, one row per slot; dead entries
            #: hold the scratch page (a valid index, masked data)
            self._table = np.zeros((self.slots, self.max_pages),
                                   np.int32)
        else:
            self.pool = None
            self.prefix = None
            self.cache_spec = KVCacheSpec(
                layers=mcfg.n_layer, slots=self.slots, heads=mcfg.n_head,
                max_len=self.max_seq_len, head_dim=mcfg.d_head,
                dtype=kv_dtype)
            validate_cache_mesh(mesh, self.cache_spec)
            self._cache_shardings = cache_shardings(mesh)
            self.cache = shard_cache(init_cache(self.cache_spec), mesh,
                                     self._cache_shardings)

        # -- multi-tenant LoRA adapter plane (serving.lora, docs/
        # serving.md "multi-tenant serving"; S-LoRA / Punica,
        # PAPERS.md): per-tenant low-rank adapters live in a host
        # registry; hbm_adapter_slots+1 device slots (0 = the reserved
        # zero adapter) hold the hot ones, refcounted + LRU-evicted
        # exactly like KV pages; the compiled programs gather each
        # slot's adapter by a TRACED int32 table, so tenant mixes ride
        # the same tick.  rank=0 (default): no pools, no extra
        # operands — every program bitwise-unchanged.
        lcfg = cfg.serving.lora
        self.lora_rank = int(lcfg["rank"])
        self.lora = self.lora_rank > 0
        self.lora_scale = (float(lcfg["alpha"]) / self.lora_rank
                           if self.lora else 1.0)
        self.adapters = None
        self.adapter_bytes = 0
        self._adapter_table = None
        self._adapter_hits_seen = 0
        self._adapter_faults_seen = 0
        if self.lora:
            from .adapters import (AdapterPool, AdapterRegistry,
                                   adapter_param_shapes)
            self.lora_targets = tuple(lcfg["targets"])
            n_aslots = int(lcfg["hbm_adapter_slots"])
            self._lora_shapes = adapter_param_shapes(
                mcfg.n_layer, mcfg.d_model, self.lora_rank,
                self.lora_targets)
            # TP layout mirrors the base matmuls' Megatron split
            # (models/gpt2.py param_partition_specs): column-parallel
            # targets shard B's output features, row-parallel targets
            # shard A's input features; the rank dim is tiny and stays
            # replicated.  Pool axes: A [L, N, d_in, r], B [L, N, r,
            # *out] with N = hbm_adapter_slots + 1.
            mx = MODEL_AXIS
            lora_specs = {
                "qkv_w": (P(), P(None, None, None, None, mx)),
                "out_w": (P(None, None, mx, None), P()),
                "fc_w": (P(), P(None, None, None, mx)),
                "proj_w": (P(None, None, mx, None), P()),
            }
            self._lora_shardings = {
                t: tuple(NamedSharding(mesh, s) for s in lora_specs[t])
                for t in self.lora_targets}
            pools = {}
            for t in self.lora_targets:
                a_shape, b_shape = self._lora_shapes[t]
                pa = jnp.zeros((a_shape[0], n_aslots + 1) + a_shape[1:],
                               kv_dtype)
                pb = jnp.zeros((b_shape[0], n_aslots + 1) + b_shape[1:],
                               kv_dtype)
                sa, sb = self._lora_shardings[t]
                pools[t] = (jax.device_put(pa, sa),
                            jax.device_put(pb, sb))
            self._lora_pools = pools
            self.adapter_bytes = sum(int(a.nbytes) + int(b.nbytes)
                                     for a, b in pools.values())

            # slot-traced donated upload: N uploads, one compiled
            # program (the _copy_fn discipline applied to weights)
            def adapter_upload_fn(pools, slot, new):
                out = {}
                for t in sorted(pools):
                    ap, bp = pools[t]
                    an, bn = new[t]
                    out[t] = (ap.at[:, slot].set(an.astype(ap.dtype)),
                              bp.at[:, slot].set(bn.astype(bp.dtype)))
                return out

            self._adapter_upload_fn = jax.jit(
                adapter_upload_fn, donate_argnums=(0,),
                out_shardings=self._lora_shardings)
            self.adapter_registry = AdapterRegistry(
                int(lcfg["max_adapters"]), self._lora_shapes)
            self.adapter_stage = Stage(
                "adapter_fetch",
                max_failures=cfg.stages.max_stage_failures,
                fallback="synchronous host->HBM adapter copy "
                         "(injection plane bypassed)")
            self.adapters = AdapterPool(
                n_aslots, self.adapter_registry, self._upload_adapter,
                stage=self.adapter_stage)
            #: host-owned per-slot adapter table — one more TRACED
            #: decode/verify operand (dead slots hold 0: the zero
            #: adapter's delta is mathematically zero)
            self._adapter_table = np.zeros((self.slots,), np.int32)

        # -- pallas interpret + ambient mesh scope (the engine idiom) ----
        from ..ops.pallas.runtime import (interpret_scope,
                                          mesh_wants_interpret)
        self._pallas_interpret = mesh_wants_interpret(mesh)

        def _step_scope():
            stack = contextlib.ExitStack()
            stack.enter_context(interpret_scope(self._pallas_interpret))
            if hasattr(jax, "set_mesh"):
                stack.enter_context(jax.set_mesh(self.mesh))
            else:
                stack.enter_context(self.mesh)
            return stack

        self._pallas_scope = _step_scope

        # -- compiled programs -------------------------------------------
        rep = NamedSharding(mesh, P())
        self._copy_fn = None
        self._page_out_fn = None
        self._page_in_fn = None
        self._set_len_fn = None
        # the one shared next-token rule (inference/speculative.py):
        # greedy at temperature 0 — bitwise the argmax these programs
        # used to inline — sampling otherwise.  Programs take a
        # trailing *rng operand only when the static temperature
        # demands one, so the 0-temperature programs are unchanged.
        temp = self.temperature

        if self.paged:
            quant_kv = self.quant_kv

            def cache_scales(cache):
                """The scale-sidecar kwargs of the model's paged entry
                points — empty on the fp pool, so those traces stay
                byte-identical to the pre-quant programs."""
                if not quant_kv:
                    return {}
                return {"k_scale": cache["k_scale"],
                        "v_scale": cache["v_scale"]}

            # multi-tenant lora threads (pools, slot-table) as two
            # extra TRACED operands ahead of the rng tail; lora off
            # leaves both signatures and traces byte-identical
            lora_on = self.lora
            lora_scale = self.lora_scale

            def split_lora(extra):
                """(lora kwargs, rng tail) of a program's *extra."""
                if not lora_on:
                    return {}, extra
                return ({"lora": extra[0], "adapter_slots": extra[1],
                         "lora_scale": lora_scale}, extra[2:])

            # delta-aware prefill over the page pool: page_row,
            # prefix_len and delta_len are TRACED, so one program
            # serves full prefills AND prefix-hit deltas
            def prefill_fn(params, cache, tokens, delta_len, prefix_len,
                           page_row, slot, *extra):
                lkw, rng = split_lora(extra)
                out = self.model.prefill_paged(
                    params, tokens, delta_len, prefix_len, page_row,
                    cache["k"], cache["v"], **lkw,
                    **cache_scales(cache))
                logits, kp, vp = out[0], out[1], out[2]
                total = jnp.reshape(prefix_len + delta_len,
                                    (1,)).astype(jnp.int32)
                lengths = jax.lax.dynamic_update_slice(
                    cache["lengths"], total, (slot,))
                last = jax.lax.dynamic_index_in_dim(
                    logits, delta_len - 1, axis=1, keepdims=False)[0]
                first_tok = select_next_token(last, temp,
                                              rng[0] if rng else None)
                newc = {"k": kp, "v": vp, "lengths": lengths}
                if quant_kv:
                    newc["k_scale"], newc["v_scale"] = out[3], out[4]
                return newc, first_tok

            def decode_fn(params, cache, tokens, active, page_table,
                          *extra):
                lkw, rng = split_lora(extra)
                out = self.model.decode_step_paged(
                    params, tokens, cache["k"], cache["v"], page_table,
                    cache["lengths"], active, impl=self.decode_impl,
                    **lkw, **cache_scales(cache))
                logits, k, v, new_len = out[0], out[1], out[2], out[-1]
                next_tok = select_next_token(logits, temp,
                                             rng[0] if rng else None)
                newc = {"k": k, "v": v, "lengths": new_len}
                if quant_kv:
                    newc["k_scale"], newc["v_scale"] = out[3], out[4]
                return newc, next_tok

            # copy-on-write: duplicate one page (src/dst traced — zero
            # recompiles no matter which pages diverge).  Every pool-
            # shaped leaf is copied — on the quantized cache that
            # includes the scale sidecars, or the COW'd page would
            # dequantize with the wrong scales.
            def copy_fn(cache, src, dst):
                out = dict(cache)
                for key in ("k", "v", "k_scale", "v_scale"):
                    if key not in cache:
                        continue
                    a = cache[key]
                    pg = jax.lax.dynamic_slice_in_dim(a, src, 1, axis=1)
                    out[key] = jax.lax.dynamic_update_slice_in_dim(
                        a, pg, dst, axis=1)
                return out

            self._copy_fn = jax.jit(copy_fn, donate_argnums=(0,),
                                    out_shardings=self._cache_shardings)

            # KV-page export/import (disaggregated fleet, docs/
            # serving.md): one page's pool rows out to the host / back
            # in, every pool-shaped leaf in _copy_fn's fixed order —
            # on the quantized cache that includes the scale sidecars,
            # or an imported page would dequantize with the wrong
            # scales.  The page index is TRACED like _copy_fn's
            # src/dst, so any page migrates on one compiled pair.
            def page_out_fn(cache, page):
                out = []
                for key in ("k", "v", "k_scale", "v_scale"):
                    if key not in cache:
                        continue
                    out.append(jax.lax.dynamic_slice_in_dim(
                        cache[key], page, 1, axis=1))
                return tuple(out)

            def page_in_fn(cache, page, *leaves):
                out = dict(cache)
                i = 0
                for key in ("k", "v", "k_scale", "v_scale"):
                    if key not in cache:
                        continue
                    out[key] = jax.lax.dynamic_update_slice_in_dim(
                        cache[key], leaves[i], page, axis=1)
                    i += 1
                return out

            # adoption rebuilds a migrated slot's cache length without
            # a prefill pass (slot + length traced)
            def set_len_fn(cache, slot, length):
                out = dict(cache)
                out["lengths"] = jax.lax.dynamic_update_slice(
                    cache["lengths"],
                    jnp.reshape(length, (1,)).astype(jnp.int32),
                    (slot,))
                return out

            # exported page slices are host-bound bytes: replicated
            # output (identity on one device) so every host sees the
            # full page, like the other pinned siblings
            self._page_out_fn = jax.jit(page_out_fn, out_shardings=rep)
            self._page_in_fn = jax.jit(
                page_in_fn, donate_argnums=(0,),
                out_shardings=self._cache_shardings)
            self._set_len_fn = jax.jit(
                set_len_fn, donate_argnums=(0,),
                out_shardings=self._cache_shardings)
        else:
            def prefill_fn(params, cache, tokens, length, slot, *rng):
                logits, ks, vs = self.model.prefill(params, tokens)
                new_k = ks[:, 0][:, None].astype(cache["k"].dtype)
                new_v = vs[:, 0][:, None].astype(cache["v"].dtype)
                start = (0, slot, 0, 0, 0)
                k_cache = jax.lax.dynamic_update_slice(cache["k"],
                                                       new_k, start)
                v_cache = jax.lax.dynamic_update_slice(cache["v"],
                                                       new_v, start)
                lengths = jax.lax.dynamic_update_slice(
                    cache["lengths"], length[None].astype(jnp.int32),
                    (slot,))
                last = jax.lax.dynamic_index_in_dim(
                    logits, length - 1, axis=1, keepdims=False)[0]
                first_tok = select_next_token(last, temp,
                                              rng[0] if rng else None)
                return ({"k": k_cache, "v": v_cache, "lengths": lengths},
                        first_tok)

            def decode_fn(params, cache, tokens, active, *rng):
                logits, k, v, new_len = self.model.decode_step(
                    params, tokens, cache["k"], cache["v"],
                    cache["lengths"], active, impl=self.decode_impl)
                next_tok = select_next_token(logits, temp,
                                             rng[0] if rng else None)
                return ({"k": k, "v": v, "lengths": new_len}, next_tok)

        self._prefill_fn = jax.jit(
            prefill_fn, donate_argnums=(1,),
            out_shardings=(self._cache_shardings, rep))
        self._decode_fn = jax.jit(
            decode_fn, donate_argnums=(1,),
            out_shardings=(self._cache_shardings, rep))
        if self.spec_k:
            self._build_spec_plane(cfg, mcfg, kv_dtype, draft_params,
                                   seed, rep)

        # -- fault plane: queue as a Channel, work under one Stage -------
        self.queue = Channel(capacity=cfg.serving.queue_capacity)
        self.scheduler = SlotScheduler(self.slots)
        self.stage = Stage(
            "serve", max_failures=cfg.stages.max_stage_failures,
            fallback="chaos-free direct serving (injection plane "
                     "bypassed)")
        # flight recorder: every stage event samples the request-queue
        # depth (and, paged, the pool's free pages; speculating, the
        # live accept ratio), so a dump shows the backlog + headroom +
        # speculation-health trajectory before a failure
        if self.paged or self.spec_k:
            self.stage.depth_fn = self._stage_depth
        else:
            self.stage.depth_fn = self.queue.qsize
        self.stage.on_degrade = lambda st: self.dump_flight_record(
            reason=f"stage {st.name!r} degraded to {st.fallback}")

        # -- KV tiering (docs/serving.md "KV tiering"): park idle
        # sessions' prefix-cache pages on host/disk and stream them
        # back on resume.  Off by default (idle_park_ticks=0) — the
        # engine is bitwise what it was without it.
        self.kv_tier = None
        kvt = cfg.serving.kv_tier
        if self.paged and self.prefix is not None \
                and kvt[C.SERVING_KV_TIER_IDLE_PARK_TICKS] > 0:
            from ..runtime.disk_offload import disk_fsync_enabled
            from .kv_tier import KVTier
            self.kv_tier = KVTier(
                page_len=self.page_len, pool=self.pool,
                prefix=self.prefix,
                exporter=self._export_page_bytes,
                importer=self._import_page_bytes,
                idle_park_ticks=kvt[C.SERVING_KV_TIER_IDLE_PARK_TICKS],
                host_budget_pages=kvt[
                    C.SERVING_KV_TIER_HOST_BUDGET_PAGES],
                disk_dir=kvt[C.SERVING_KV_TIER_DISK_DIR] or None,
                fsync=disk_fsync_enabled(kvt[C.SERVING_KV_TIER_FSYNC]),
                max_failures=cfg.stages.max_stage_failures)
        wire_serve_stage_plane(self)

        # -- memory planes (docs/serving.md "quantized serving"): the
        # device bytes the params and KV cache claim, from the param
        # tree + cache spec — the ONE accounting the serve_*_bytes
        # gauges, the summarize "serving memory" row and the bench's
        # fixed-KV-byte budgets read (no more hand-recomputed
        # bytes-per-element claims in bench legs)
        from .quantize import param_nbytes
        self.param_bytes = param_nbytes(self.params)
        self.kv_bytes = self.cache_spec.bytes
        if self.spec_k:
            self.param_bytes += param_nbytes(self.draft_params)
            self.kv_bytes += self.draft_cache_spec.bytes

        # -- telemetry ---------------------------------------------------
        self.telemetry = None
        if cfg.telemetry.enabled:
            import os
            from ..telemetry.hub import TelemetryHub
            out = cfg.telemetry.output_path or os.path.join(
                os.getcwd(), "telemetry")
            self.telemetry = TelemetryHub(
                out, trace=cfg.telemetry.trace,
                compile_events=cfg.telemetry.compile_events,
                memory=cfg.telemetry.memory,
                storm_threshold=cfg.telemetry.recompile_storm_threshold)
            self.telemetry.track_program("decode_step", self._decode_fn)
            self.telemetry.track_program("prefill", self._prefill_fn)
            if self._copy_fn is not None:
                self.telemetry.track_program("copy_page", self._copy_fn)
                self.telemetry.track_program("page_out",
                                             self._page_out_fn)
                self.telemetry.track_program("page_in", self._page_in_fn)
            if self.spec_k:
                self.telemetry.track_program("verify_step",
                                             self._verify_fn)
                self.telemetry.track_program("draft_propose",
                                             self._propose_fn)
                self.telemetry.track_program("draft_prefill",
                                             self._draft_prefill_fn)
            if self.lora:
                self.telemetry.track_program("adapter_upload",
                                             self._adapter_upload_fn)
            reg = self.telemetry.registry
            self._tokens_total = reg.counter(
                "serve_tokens_total", "generated tokens")
            self._requests_total = reg.counter(
                "serve_requests_total", "finished requests")
            self._requests_failed = reg.counter(
                "serve_requests_failed_total",
                "requests finished with an error")
            self._token_seconds = reg.histogram(
                "serve_token_seconds",
                "per-token latency (first token = time to first token)")
            self._ttft_hist = reg.histogram(
                "serve_ttft_seconds",
                "time to first token: submit -> first generated token "
                "(queue wait + prefill)")
            self._queue_wait_hist = reg.histogram(
                "serve_queue_wait_seconds",
                "submit -> slot admission wait (the Orca iteration-"
                "level scheduling number)")
            self._active_gauge = reg.gauge(
                "serve_active_slots", "slots decoding this tick")
            self._param_bytes_gauge = reg.gauge(
                "serve_param_bytes",
                "device bytes of the serving params (target + draft; "
                "int8 weights + scales under quantization)")
            self._param_bytes_gauge.set(self.param_bytes)
            self._kv_bytes_gauge = reg.gauge(
                "serve_kv_bytes",
                "device bytes of the KV cache from its spec (both "
                "layouts; incl. quant scale sidecars + draft cache)")
            self._kv_bytes_gauge.set(self.kv_bytes)
            if self.paged:
                self._pages_total_gauge = reg.gauge(
                    "serve_pages_total",
                    "allocatable KV pages (excludes the scratch page)")
                self._pages_total_gauge.set(self.cache_spec.pages - 1)
                self._free_pages_gauge = reg.gauge(
                    "serve_free_pages", "unallocated KV pages")
                self._free_pages_gauge.set(self.pool.free_count)
                self._prefix_hits = reg.counter(
                    "serve_prefix_hits_total",
                    "admissions that reused cached prefix pages")
                self._prefix_misses = reg.counter(
                    "serve_prefix_misses_total",
                    "admissions that found no cached prefix")
            if self.spec_k:
                self._spec_proposed = reg.counter(
                    "serve_spec_proposed_total",
                    "draft tokens proposed to the verify program")
                self._spec_accepted_ctr = reg.counter(
                    "serve_spec_accepted_total",
                    "accepted draft tokens actually emitted")
                self._spec_len_hist = reg.histogram(
                    "serve_spec_accepted_len",
                    "tokens emitted per verify pass (accepted draft "
                    "prefix + the bonus token)")
            if self.lora:
                self._adapters_resident_gauge = reg.gauge(
                    "serve_adapters_resident",
                    "tenant adapters resident in HBM pool slots "
                    "(pinned + cold-evictable; excludes the reserved "
                    "zero adapter)")
                self._adapter_hits_ctr = reg.counter(
                    "serve_adapter_hits_total",
                    "admissions whose adapter was already HBM-resident")
                self._adapter_faults_ctr = reg.counter(
                    "serve_adapter_faults_total",
                    "cold-adapter admissions that fetched host->HBM "
                    "(the adapter_fetch stage point)")

            if self.kv_tier is not None:
                self._kv_parked_gauge = reg.gauge(
                    "serve_kv_parked_sessions",
                    "idle sessions parked off HBM in the host/disk KV "
                    "tier (parked digest-chain tails)")
                self._kv_spill_ctr = reg.counter(
                    "serve_kv_spill_bytes_total",
                    "KV page bytes exported HBM -> host/disk by the "
                    "kv_spill stage")
                self._kv_fetch_ctr = reg.counter(
                    "serve_kv_fetch_bytes_total",
                    "parked KV page bytes streamed back on session "
                    "resume by the kv_fetch stage")
                self._kv_spill_seen = 0
                self._kv_fetch_seen = 0

            def _stage_counter(name, help, n):
                reg.counter(name, help).inc(n)

            self.stage.counter_fn = _stage_counter
            if self.lora:
                self.adapter_stage.counter_fn = _stage_counter
            if self.kv_tier is not None:
                self.kv_tier.spill_stage.counter_fn = _stage_counter
                self.kv_tier.fetch_stage.counter_fn = _stage_counter

        #: perf_counter epoch for the completion records' ``arrival_s``
        #: stamps — submit times made record-relative, so open-loop
        #: queueing is reconstructible from events.jsonl alone
        self._epoch_t = time.perf_counter()
        self._rid = 0
        self._ticks = 0
        self._closed = False
        #: requests popped from the queue but not yet admitted — the
        #: page-pool backpressure parking spot (head goes first, so
        #: admission order is preserved under exhaustion)
        self._pending: deque = deque()
        self._latencies: deque = deque(maxlen=8192)
        #: decode-phase (post-first-token) latencies only — the TPOT
        #: plane the per-role autoscaler reads off the heartbeat gauge
        self._tpot_lat: deque = deque(maxlen=2048)
        self._flush_every = cfg.serving.flush_interval_ticks
        self._last_flush_t = time.perf_counter()
        self._last_flush_tokens = 0
        self._tokens_seen = 0

    # -- speculative decoding: the draft plane --------------------------
    def _build_spec_plane(self, cfg, mcfg, kv_dtype, draft_params,
                          seed: int, rep) -> None:
        """Build the draft model + its slot KV cache + the three
        compiled spec programs (docs/serving.md "speculative
        decoding"): ``draft_prefill`` (mirror the prompt into the
        draft cache at admission), ``draft_propose`` (k+1 chained
        draft decode steps in ONE program — the extra step writes the
        last proposal's K/V so the draft cache stays aligned with the
        target on full acceptance), and ``verify_step`` (the widened
        target pass + acceptance, zero recompiles across any accepted-
        length mix).

        The draft always runs the fixed-stride SLOT cache, paged
        target or not: at draft scale a full stride is a rounding
        error next to the target pool, and it keeps the rollback a
        pure lengths mask."""
        from ..models.gpt2 import GPT2Config, GPT2Model, _decode_attn_impl
        from ..config import constants as C
        d = cfg.serving.draft
        draft_cfg = GPT2Config(
            vocab_size=mcfg.vocab_size, n_positions=mcfg.n_positions,
            d_model=d[C.SERVING_DRAFT_D_MODEL],
            n_layer=d[C.SERVING_DRAFT_N_LAYER],
            n_head=d[C.SERVING_DRAFT_N_HEAD],
            remat=None,
            attn_impl=d[C.SERVING_DRAFT_ATTN_IMPL] or mcfg.attn_impl)
        self.draft_config = draft_cfg
        self.draft_model = GPT2Model(draft_cfg)
        self._draft_impl = ("dense" if self.decode_impl == "dense"
                            else _decode_attn_impl(draft_cfg))
        if draft_params is None:
            draft_params = self.draft_model.init(
                jax.random.PRNGKey(seed + 1))
        dspecs = self.draft_model.param_partition_specs(draft_params)
        if dspecs is None:
            dspecs = jax.tree.map(lambda _: P(), draft_params)
        if self.quant_weights:
            # the draft rides the weights arm too (ISSUE: a quantized
            # draft is nearly free); its slot KV cache keeps the
            # master dtype — at draft scale the stride is a rounding
            # error and the rollback stays a pure lengths mask
            from .quantize import (quantize_gpt2_params,
                                   quantized_partition_specs)
            draft_params = quantize_gpt2_params(draft_params)
            dspecs = quantized_partition_specs(dspecs)
        dshard = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), dspecs,
            is_leaf=lambda s: isinstance(s, P))
        self.draft_params = jax.tree.map(jax.device_put, draft_params,
                                         dshard)
        dspec = KVCacheSpec(
            layers=draft_cfg.n_layer, slots=self.slots,
            heads=draft_cfg.n_head, max_len=self.max_seq_len,
            head_dim=draft_cfg.d_head, dtype=kv_dtype)
        validate_cache_mesh(self.mesh, dspec)
        self.draft_cache_spec = dspec
        self._draft_shardings = cache_shardings(self.mesh)
        self._draft_cache = shard_cache(init_cache(dspec), self.mesh,
                                        self._draft_shardings)

        temp = self.temperature
        k_spec = self.spec_k
        W = k_spec + 1

        def draft_prefill_fn(dparams, dcache, tokens, length, slot):
            _, ks, vs = self.draft_model.prefill(dparams, tokens)
            new_k = ks[:, 0][:, None].astype(dcache["k"].dtype)
            new_v = vs[:, 0][:, None].astype(dcache["v"].dtype)
            start = (0, slot, 0, 0, 0)
            k_cache = jax.lax.dynamic_update_slice(dcache["k"], new_k,
                                                   start)
            v_cache = jax.lax.dynamic_update_slice(dcache["v"], new_v,
                                                   start)
            lengths = jax.lax.dynamic_update_slice(
                dcache["lengths"], length[None].astype(jnp.int32),
                (slot,))
            return {"k": k_cache, "v": v_cache, "lengths": lengths}

        def propose_fn(dparams, dcache, cur, active, *rng):
            def body(carry, i):
                cache, tok = carry
                logits, kk, vv, nl = self.draft_model.decode_step(
                    dparams, tok, cache["k"], cache["v"],
                    cache["lengths"], active, impl=self._draft_impl)
                lg = logits.astype(jnp.float32)
                if temp > 0:
                    sk = jax.random.fold_in(rng[0], i)
                    nxt = select_next_token(lg, temp, sk)
                    out = (nxt, jax.nn.softmax(lg / temp, axis=-1))
                else:
                    nxt = select_next_token(lg)
                    out = nxt
                return ({"k": kk, "v": vv, "lengths": nl}, nxt), out
            (dcache, _), ys = jax.lax.scan(
                body, (dcache, cur.astype(jnp.int32)),
                jnp.arange(W, dtype=jnp.int32))
            if temp > 0:
                return (dcache, ys[0][:k_spec].T,
                        jnp.transpose(ys[1][:k_spec], (1, 0, 2)))
            return dcache, ys[:k_spec].T

        def verify_core(params, cache, cur, proposals, active,
                        page_table, qprobs, key, lora=None,
                        adapter_slots=None):
            tokens_w = jnp.concatenate(
                [cur[:, None].astype(jnp.int32),
                 proposals.astype(jnp.int32)], axis=1)
            newc = {}
            if self.paged:
                scales = ({"k_scale": cache["k_scale"],
                           "v_scale": cache["v_scale"]}
                          if self.quant_kv else {})
                lkw = ({"lora": lora, "adapter_slots": adapter_slots,
                        "lora_scale": self.lora_scale}
                       if lora is not None else {})
                out = self.model.verify_step_paged(
                    params, tokens_w, cache["k"], cache["v"],
                    page_table, cache["lengths"], active,
                    impl=self.decode_impl, **lkw, **scales)
                logits, kc, vc = out[0], out[1], out[2]
                if self.quant_kv:
                    newc["k_scale"], newc["v_scale"] = out[3], out[4]
            else:
                logits, kc, vc = self.model.verify_step(
                    params, tokens_w, cache["k"], cache["v"],
                    cache["lengths"], active, impl=self.decode_impl)
            out_tok, accepted = speculative_accept(
                logits.astype(jnp.float32), proposals, qprobs, temp,
                key)
            adv = jnp.where(active, accepted + 1, 0).astype(jnp.int32)
            new_len = jnp.minimum(cache["lengths"] + adv,
                                  jnp.int32(self.max_seq_len))
            newc.update({"k": kc, "v": vc, "lengths": new_len})
            return newc, out_tok, accepted

        if self.paged:
            lora_on = self.lora

            def verify_fn(params, cache, cur, proposals, active,
                          page_table, *s):
                lora, aslots = None, None
                if lora_on:
                    lora, aslots = s[0], s[1]
                    s = s[2:]
                return verify_core(params, cache, cur, proposals,
                                   active, page_table,
                                   s[0] if s else None,
                                   s[1] if s else None,
                                   lora=lora, adapter_slots=aslots)
        else:
            def verify_fn(params, cache, cur, proposals, active, *s):
                return verify_core(params, cache, cur, proposals,
                                   active, None, s[0] if s else None,
                                   s[1] if s else None)

        self._draft_prefill_fn = jax.jit(
            draft_prefill_fn, donate_argnums=(1,),
            out_shardings=self._draft_shardings)
        prop_out = ((self._draft_shardings, rep, rep) if temp > 0
                    else (self._draft_shardings, rep))
        self._propose_fn = jax.jit(propose_fn, donate_argnums=(1,),
                                   out_shardings=prop_out)
        self._verify_fn = jax.jit(
            verify_fn, donate_argnums=(1,),
            out_shardings=(self._cache_shardings, rep, rep))

    def _maybe_key(self):
        """One fresh PRNG key per sampling program call — an empty
        tuple at temperature 0, where no program takes one."""
        if self._rng_base is None:
            return ()
        self._rng_n += 1
        return (jax.random.fold_in(self._rng_base, self._rng_n),)

    # -- adapter plane (multi-tenant LoRA) ------------------------------
    def _upload_adapter(self, slot: int, weights) -> None:
        """Host->HBM copy of one adapter into pool slot `slot`.

        Runs through the jitted donated upload program so the pool
        arrays keep their shardings and the copy is a slot-traced
        `at[:, slot].set` — no recompile per (slot, tenant) pair.
        """
        new = {t: (jnp.asarray(weights[t][0]), jnp.asarray(weights[t][1]))
               for t in self.lora_targets}
        self._lora_pools = self._adapter_upload_fn(
            self._lora_pools, np.int32(slot), new)

    def register_adapter(self, adapter_id: int, weights=None):
        """Register a tenant adapter (host-side).  `weights=None`
        synthesizes deterministic factors from the adapter id, so every
        replica in a fleet derives identical weights for the same
        tenant without shipping bytes."""
        if not self.lora:
            raise ValueError("serving.lora.rank is 0 — adapters disabled")
        if weights is None:
            return self.adapter_registry.get(adapter_id)
        return self.adapter_registry.register(adapter_id, weights)

    def hot_adapters(self):
        """Adapter ids currently resident in HBM slots (for heartbeat
        affinity gauges)."""
        return self.adapters.hot_ids() if self.lora else []

    def _spec_ratio(self) -> float:
        """The live draft-acceptance ratio — ONE formula shared by the
        depth dict, the flight-record extras and the flush scalar."""
        return round(
            self._spec_accepted_n / max(self._spec_proposed_n, 1), 4)

    def _stage_depth(self):
        d: Dict[str, Any] = {"depth": self.queue.qsize()}
        if self.paged:
            d["free_pages"] = self.pool.free_count
        if self.spec_k:
            d["spec_accept_ratio"] = self._spec_ratio()
        return d

    # -- telemetry helpers ----------------------------------------------
    def _span(self, name: str, **args):
        if self.telemetry is None:
            return contextlib.nullcontext()
        return self.telemetry.span(name, cat="serve", **args)

    @property
    def _tracer(self):
        tel = self.telemetry
        return tel.tracer if tel is not None else None

    # -- per-request causal trace + completion record ---------------------
    def _begin_request_trace(self, req: Request) -> None:
        tr = self._tracer
        if tr is None:
            return
        from ..telemetry.tracing import TraceContext
        req.ctx = TraceContext.new()
        # root covers submit -> finish; queue_wait ends at admission.
        # ASYNC (b/e) events, not complete slices: concurrent requests
        # overlap without nesting, which the X per-thread call-stack
        # model mis-renders — async pairs match by (cat, id, name)
        req.span = tr.async_begin("serve/request", req.ctx.trace_id,
                                  cat="serve", rid=req.rid)
        req.queue_span = tr.async_begin("serve/queue_wait",
                                        req.ctx.trace_id, cat="serve",
                                        rid=req.rid)

    def _end_request_trace(self, req: Request, reason=None,
                           error=None) -> None:
        """Close the request's spans and terminate its flow — inside a
        ``serve/finish`` (or ``serve/error``) span so the arrowhead
        binds somewhere visible.  A failing request's trace ends with an
        error span, never a leaked open flow."""
        tr = self._tracer
        args = {}
        if reason is not None:
            args["reason"] = reason
        if error is not None:
            args["error"] = repr(error)
        if req.queue_span is not None:  # never admitted: close it now
            req.queue_span.end(**args)
            req.queue_span = None
        if tr is not None and req.ctx is not None:
            name = "serve/error" if error is not None else "serve/finish"
            with tr.span(name, cat="serve", rid=req.rid, **args):
                if req.admit_t:
                    # the flow starts at admission — a queued request
                    # failed before any flow existed to terminate
                    tr.flow_end("serve/request", req.ctx, cat="serve",
                                rid=req.rid)
            req.ctx = None
        if req.span is not None:
            req.span.end(**args)
            req.span = None

    def _write_request_record(self, req: Request) -> None:
        """One structured completion record per request in events.jsonl
        (``kind: serve_request``) — the offline source for the summarize
        queue/prefill/decode split and the diagnose post-mortem."""
        if self.telemetry is None:
            return
        decode = [float(t) for t in req.token_times[1:]]
        rec = {
            "rid": req.rid,
            "prompt_len": len(req.prompt),
            # submit time relative to the engine's epoch: the open-loop
            # arrival schedule, reconstructible offline (goodput.py);
            # readers tolerate its absence in pre-PR-17 artifacts
            "arrival_s": round(req.submit_t - self._epoch_t, 6),
            "tokens": len(req.tokens),
            "finish_reason": req.finish_reason,
            "error": repr(req.error) if req.error is not None else None,
            "total_s": time.perf_counter() - req.submit_t,
            "queue_wait_s": (req.admit_t - req.submit_t
                             if req.admit_t else None),
            "ttft_s": (float(req.token_times[0])
                       if req.token_times else None),
            "prefill_s": req.prefill_s if req.prefill_s else None,
            "decode_tokens": len(decode),
            "decode_s_sum": sum(decode),
            # bounded: a million-token request must not write a
            # million-float record (decode_tokens keeps the true count)
            "token_times_s": [round(t, 6) for t in decode[:512]],
        }
        if req.ctx is not None:
            rec["trace_id"] = req.ctx.trace_id
        self.telemetry.jsonl.write_event("serve_request", rec)

    def dump_flight_record(self, reason: str = "manual",
                           error=None):
        """Serve-side flight recorder: dump the ``serve`` stage's event
        ring (admissions, ticks, queue depths, failures) as
        ``flightrec_<tick>.json``.  Fired on poison and degradation;
        callable on demand.  Never raises."""
        if self.telemetry is None:
            return None
        try:
            extra = {"active_slots": len(self.scheduler.active),
                     "queued": self.queue.qsize()}
            if self.paged:
                extra["free_pages"] = self.pool.free_count
                extra["pending"] = len(self._pending)
            if self.spec_k:
                extra["spec_accept_ratio"] = self._spec_ratio()
            return self.telemetry.dump_flight_record(
                {"serve": self.stage}, self._ticks, reason, error=error,
                extra=extra)
        except Exception:
            logger.exception("serve flight-record dump failed "
                             "(reason=%r)", reason)
            return None

    def _count_token(self, latency_s: float):
        self._tokens_seen += 1
        self._latencies.append(latency_s)
        if self.telemetry is not None:
            self._tokens_total.inc()
            self._token_seconds.observe(latency_s)

    def _flush(self):
        """Materialize serving scalars as a telemetry sync event (the
        summarize CLI's 'serving' row reads exactly these)."""
        if self.telemetry is None:
            return
        now = time.perf_counter()
        dt = max(now - self._last_flush_t, 1e-9)
        toks = self._tokens_seen - self._last_flush_tokens
        lat = sorted(self._latencies)
        scalars = {"serve_tokens_per_s": toks / dt,
                   # static for the engine's life, but flushed as
                   # scalars so the offline summarize "serving memory"
                   # row needs only events.jsonl
                   "serve_param_bytes": float(self.param_bytes),
                   "serve_kv_bytes": float(self.kv_bytes)}
        p50 = _percentile(lat, 0.50)
        p99 = _percentile(lat, 0.99)
        if p50 is not None:
            scalars["serve_token_p50_s"] = p50
            scalars["serve_token_p99_s"] = p99
        tpot = self.tpot_p99()
        if tpot is not None:
            scalars["serve_tpot_p99_s"] = tpot
        if self.paged:
            usable = self.cache_spec.pages - 1
            scalars["serve_free_pages"] = float(self.pool.free_count)
            scalars["serve_page_utilization"] = (
                self.pool.used_count / usable if usable else 0.0)
            if self.prefix is not None:
                tot = self.prefix.hits + self.prefix.misses
                if tot:
                    scalars["serve_prefix_hit_ratio"] = \
                        self.prefix.hits / tot
                scalars["serve_prefix_hit_tokens"] = \
                    float(self.prefix.hit_tokens)
                scalars["serve_page_cow_total"] = float(self.prefix.cow)
        if self.spec_k and self._spec_passes:
            # cumulative over the run (like the prefix scalars): the
            # LAST flush is the run's answer — mean accepted length is
            # tokens-per-target-pass, the 1/MAL speedup denominator
            scalars["serve_spec_accept_ratio"] = self._spec_ratio()
            scalars["serve_spec_mean_accepted_len"] = (
                (self._spec_accepted_n + self._spec_passes)
                / self._spec_passes)
        if self.lora:
            pool = self.adapters
            scalars["serve_adapters_resident"] = float(pool.resident())
            scalars["serve_adapter_bytes"] = float(self.adapter_bytes)
            scalars["serve_adapter_hits_total"] = float(pool.hits)
            scalars["serve_adapter_faults_total"] = float(pool.faults)
            scalars["serve_adapter_evictions_total"] = \
                float(pool.evictions)
            self._adapters_resident_gauge.set(pool.resident())
            # counters advance by the pool's deltas since last flush —
            # cumulative scalars above stay the summarize source
            self._adapter_hits_ctr.inc(
                pool.hits - self._adapter_hits_seen)
            self._adapter_faults_ctr.inc(
                pool.faults - self._adapter_faults_seen)
            self._adapter_hits_seen = pool.hits
            self._adapter_faults_seen = pool.faults
        if self.kv_tier is not None:
            tier = self.kv_tier
            scalars["serve_kv_parked_sessions"] = \
                float(tier.parked_sessions)
            scalars["serve_kv_spill_bytes_total"] = \
                float(tier.spill_bytes)
            scalars["serve_kv_fetch_bytes_total"] = \
                float(tier.fetch_bytes)
            p99r = tier.resume_p99_s()
            if p99r is not None:
                scalars["serve_kv_resume_p99_s"] = p99r
            self._kv_parked_gauge.set(tier.parked_sessions)
            # same delta discipline as the adapter pool above: the
            # cumulative scalars stay the summarize source
            self._kv_spill_ctr.inc(
                tier.spill_bytes - self._kv_spill_seen)
            self._kv_fetch_ctr.inc(
                tier.fetch_bytes - self._kv_fetch_seen)
            self._kv_spill_seen = tier.spill_bytes
            self._kv_fetch_seen = tier.fetch_bytes
        self.telemetry.on_sync(step=self._ticks, scalars=scalars)
        self._last_flush_t = now
        self._last_flush_tokens = self._tokens_seen

    def tpot_p99(self) -> Optional[float]:
        """Decode-phase p99 latency per token (TPOT) over the recent
        window — the gauge a decode-role replica beats for the
        per-role autoscaler (docs/serving.md "disaggregated fleet")."""
        if not self._tpot_lat:
            return None
        return _percentile(sorted(self._tpot_lat), 0.99)

    # -- request intake ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               detach_kv: bool = False,
               adapter_id: int = 0) -> Request:
        """Enqueue one generation request (blocks on a full queue — the
        open-loop backpressure point).  Greedy decoding; the first
        generated token comes from the prefill logits.

        ``detach_kv`` (paged only) marks a KV-migration source: when
        the request finishes, its pages stay alive for
        :meth:`export_pages` instead of freeing — the disaggregated
        fleet's prefill leg (``release_detached`` frees them after the
        transfer).

        ``adapter_id`` selects the tenant's LoRA adapter (0 = base
        model).  Admission resolves it to an HBM pool slot, parking on
        pool-dry exactly like a pages-dry admission; requires a
        ``serving.lora`` block with ``rank > 0``."""
        if self._closed:
            raise RuntimeError("ServeEngine is closed")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.prefill_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the static "
                f"serving.prefill_len bucket ({self.prefill_len}); "
                "raise the bucket or truncate the prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.paged:
            need = -(-len(prompt) // self.page_len)
            usable = self.cache_spec.pages - 1
            if need > usable:
                raise ValueError(
                    f"prompt needs {need} KV pages but the pool only "
                    f"has {usable} allocatable pages "
                    f"(serving.pages={self.cache_spec.pages}, page 0 "
                    "reserved); it could never be admitted")
        if detach_kv and not self.paged:
            raise ValueError(
                "detach_kv (KV-migration handoff) requires the paged "
                "layout (serving.page_len > 0)")
        adapter_id = int(adapter_id)
        if adapter_id < 0:
            raise ValueError("adapter_id must be >= 0 (0 = base model)")
        if adapter_id > 0 and not self.lora:
            raise ValueError(
                f"adapter_id={adapter_id} but multi-tenant LoRA is off "
                "(set serving.lora.rank > 0)")
        self._rid += 1
        req = Request(rid=self._rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      eos_id=(self.eos_id_default if eos_id is None
                              else int(eos_id)),
                      submit_t=time.perf_counter())
        req.detach_kv = bool(detach_kv)
        req.adapter_id = adapter_id
        self._begin_request_trace(req)
        # Deliberate submission-side backpressure: submit() runs on the
        # CALLER's thread, and a full queue must block the caller (and a
        # closed one must reject) — that is the admission contract, and
        # the result is checked on the next line.
        # jaxlint: disable=JL008
        if not self.queue.put(req):
            err = self.queue.err
            rej = RuntimeError(
                "serve queue rejected the request (engine closed or "
                f"poisoned){': ' + repr(err) if err else ''}")
            req.error = rej
            self._end_request_trace(req, error=rej)
            raise rej
        return req

    def _pop_request(self) -> Optional[Request]:
        with self.queue.cond:
            if self.queue.items:
                item = self.queue.items.pop(0)
                self.queue.cond.notify_all()
                return item
            if self.queue.err is not None:
                raise self.queue.err
            return None

    # -- admission (prefill) ----------------------------------------------
    def _admit_one(self, req: Request) -> bool:
        """Admit one request (prefill + slot assignment).  Returns False
        when the paged pool can't hold it yet (backpressure — the
        request stays parked); True otherwise."""
        if self.paged:
            return self._admit_one_paged(req)
        return self._admit_one_slot(req)

    def _alloc_pages(self, n: int):
        """``n`` fresh pages, evicting least-recently-hit prefix-cache
        leaves under pressure (the eviction-ordered backpressure valve);
        None when the pool is dry even after eviction."""
        pages = self.pool.alloc(n)
        if pages is None and self.prefix is not None:
            if self.prefix.evict(n):
                pages = self.pool.alloc(n)
        return pages

    def _copy_page(self, src: int, dst: int) -> None:
        with self._span("serve/page_cow", src=src, dst=dst):
            with self._pallas_scope():
                self.cache = self._copy_fn(self.cache, np.int32(src),
                                           np.int32(dst))

    def _charge_prefill_delay(self, computed_tokens: int) -> None:
        """Paged arm of the injected-device-time model: the serve
        stage's ``DS_STAGE_DELAY_S`` unit is ONE PAGE of prefill
        compute.  ``stage.check`` already charged one unit at the admit
        boundary; charge the remaining ``ceil(computed/page_len) - 1``
        here, inside the prefill span — so a prefix-hit delta pays for
        its delta pages only and the bench's tracer-timestamp proof
        reads compute ∝ 1 template + K deltas (bench_serve.py)."""
        if self.stage.degraded:
            return
        d = injected_delay(self.stage.name)
        if d <= 0:
            return
        chunks = max(1, -(-computed_tokens // self.page_len))
        if chunks > 1:
            time.sleep(d * (chunks - 1))

    def _draft_prefill(self, req: Request,
                       slot: Optional[int] = None) -> None:
        """Mirror the admitted prompt into the DRAFT's slot cache so
        next tick's proposals start from the same history the target
        holds.  The prefill logits are discarded — the tick's first
        pending token is the TARGET's emission.  ``slot`` overrides
        the next-free-slot peek for requests already admitted (chunked
        prefill's final chunk, KV adoption)."""
        dtokens = np.zeros((1, self.prefill_len), np.int32)
        dtokens[0, :len(req.prompt)] = req.prompt
        with self._span("serve/draft_prefill", rid=req.rid):
            with self._pallas_scope():
                self._draft_cache = self._draft_prefill_fn(
                    self.draft_params, self._draft_cache, dtokens,
                    np.int32(len(req.prompt)),
                    np.int32(self.scheduler.free[0]
                             if slot is None else slot))

    def _admit_one_paged(self, req: Request) -> bool:
        total_pages = -(-len(req.prompt) // self.page_len)
        # tenant namespace: adapter A's KV pages must never be matched
        # by adapter B (or the base model) — the LoRA delta makes the
        # caches semantically different even for identical prompts.
        # "" keeps the no-lora digest chain bitwise unchanged.
        ns = f"adapter:{req.adapter_id}" if req.adapter_id else ""
        if self.prefix is not None:
            shared_len, spages, cow = self.prefix.match(req.prompt, ns)
        else:
            shared_len, spages, cow = 0, [], False
        tpages: List[int] = []
        if self.kv_tier is not None and not cow \
                and shared_len % self.page_len == 0 \
                and self.pool.free_count >= total_pages - len(spages):
            # session resume (docs/serving.md "KV tiering"): continue
            # the digest chain into the parked tier — fetched pages
            # extend the prefix-cache match and insert() below
            # re-registers them, so a resume IS a prefix hit.  Gated
            # on enough free pages for the whole admission so consumed
            # one-shot records are not spent on a request that then
            # parks; tier failures never raise out of resume — they
            # fall back to the recompute (delta prefill) path below.
            shared_len, tpages = self.kv_tier.resume(
                req.prompt, ns, shared_len, self._alloc_pages)
        need = total_pages - len(spages) - len(tpages) \
            + (1 if cow else 0)
        fresh = self._alloc_pages(need)
        if fresh is None:
            if self.prefix is not None:
                self.prefix.release(spages)
            for p in tpages:
                self.pool.deref(p)
            return False
        aslot = 0
        if self.lora and req.adapter_id:
            # resolve tenant -> HBM adapter slot AFTER the page alloc so
            # a pages-dry park never holds an adapter pin; pool-dry
            # parks the request exactly like a pages-dry admission
            try:
                got = self.adapters.acquire(req.adapter_id)
            except BaseException:
                for p in list(spages) + tpages + fresh:
                    self.pool.deref(p)
                raise
            if got is None:
                for p in list(spages) + tpages + fresh:
                    self.pool.deref(p)
                return False
            aslot = got
        held = list(spages) + tpages + fresh
        try:
            # queue wait ends HERE, before any device work: the COW
            # copy below (and its first-use compile) is compute and
            # must land in the prefill attribution, not as a spurious
            # queue-wait spike in the PR 9 latency split
            req.admit_t = time.perf_counter()
            if req.queue_span is not None:
                req.queue_span.end()
                req.queue_span = None
            if self.telemetry is not None:
                self._queue_wait_hist.observe(req.admit_t - req.submit_t)
            fi = 0
            if cow:
                # divergent append into a shared partial page: copy it
                # into a fresh page BEFORE the delta prefill writes its
                # remaining rows (the COW of docs/serving.md)
                self._copy_page(spages[-1], fresh[0])
                self.pool.deref(spages[-1])
                held.remove(spages[-1])
                row = spages[:-1] + fresh[:1]
                fi = 1
            else:
                row = list(spages) + tpages
            row.extend(fresh[fi:])
            delta = req.prompt[shared_len:]
            if self.prefill_chunk_len \
                    and len(delta) > self.prefill_chunk_len:
                # chunked prefill (Sarathi-Serve, PAPERS.md): admit
                # the slot NOW with zero device work — step() feeds
                # the delta one chunk per tick under the
                # prefill_chunk stage point, so in-flight decodes
                # never stall behind this prompt.  prefix.insert is
                # deferred to the final chunk: a mid-prefill page
                # must never be matched by a concurrent sharer.
                now = time.perf_counter()
                slot = self.scheduler.admit(req, now=now)
                if self.prefix is not None:
                    self.prefix.note_admission(shared_len)
                    if cow:
                        self.prefix.cow += 1
                    if self.telemetry is not None:
                        (self._prefix_hits if shared_len
                         else self._prefix_misses).inc()
                req.pages = row
                req.shared_len = shared_len
                req.computed_len = len(delta)
                req.kv_len = shared_len
                req.prefilling = True
                req.chunk_pos = 0
                self._table[slot, :] = 0
                self._table[slot, :len(row)] = row
                if self.lora:
                    req.adapter_slot = aslot
                    self._adapter_table[slot] = aslot
                return True
            tokens = np.zeros((1, self.prefill_len), np.int32)
            tokens[0, :len(delta)] = delta
            row_np = np.zeros((self.max_pages,), np.int32)
            row_np[:len(row)] = row
            with self._span("serve/prefill", rid=req.rid,
                            prompt_len=len(req.prompt),
                            computed=len(delta), shared=shared_len):
                tr = self._tracer
                if tr is not None and req.ctx is not None:
                    tr.flow_start("serve/request", req.ctx, cat="serve",
                                  rid=req.rid)
                self._charge_prefill_delay(len(delta))
                with self._pallas_scope():
                    self.cache, first = self._prefill_fn(
                        self.params, self.cache, tokens,
                        np.int32(len(delta)), np.int32(shared_len),
                        row_np, np.int32(self.scheduler.free[0]),
                        *((self._lora_pools, np.int32(aslot))
                          if self.lora else ()),
                        *self._maybe_key())
                first = int(np.asarray(jax.block_until_ready(first)))
            if self.spec_k:
                # the draft mirrors the FULL prompt (it has no prefix
                # cache — draft prefill is cheap by construction)
                self._draft_prefill(req)
        except BaseException:
            # roll back every page this admission still holds a ref on
            for p in held:
                self.pool.deref(p)
            if aslot:
                self.adapters.release(req.adapter_id)
            raise
        now = time.perf_counter()
        req.prefill_s = now - req.admit_t
        slot = self.scheduler.admit(req, now=now)
        if self.prefix is not None:
            # stats count SUCCESSFUL admissions only — neither a
            # parked request re-matching every tick nor a failed
            # prefill may inflate the hit ratio; the COW count's one
            # source of truth is prefix.cow (the flush scalar)
            self.prefix.note_admission(shared_len)
            if cow:
                self.prefix.cow += 1
            if self.telemetry is not None:
                (self._prefix_hits if shared_len
                 else self._prefix_misses).inc()
        req.pages = row
        req.shared_len = shared_len
        req.computed_len = len(delta)
        self._table[slot, :] = 0
        self._table[slot, :len(row)] = row
        if self.lora:
            req.adapter_slot = aslot
            self._adapter_table[slot] = aslot
        if self.prefix is not None:
            # register the freshly computed pages for future sharers
            # (full pages of prompt[:-1] + the partial tail)
            self.prefix.insert(req.prompt, row, ns)
        req.kv_len = len(req.prompt)
        req.tokens.append(first)
        req.token_times.append(now - req.submit_t)
        req.last_token = first
        self._count_token(now - req.submit_t)
        if self.telemetry is not None:
            self._ttft_hist.observe(now - req.submit_t)
        reason = self.scheduler.finish_reason(req, first,
                                              self.max_seq_len)
        if reason is not None:
            self._finish(slot, reason)
        return True

    def _admit_one_slot(self, req: Request) -> bool:
        tokens = np.zeros((1, self.prefill_len), np.int32)
        tokens[0, :len(req.prompt)] = req.prompt
        length = np.int32(len(req.prompt))
        req.admit_t = time.perf_counter()
        if req.queue_span is not None:
            # the queue_wait child span ends the moment a slot is ours
            req.queue_span.end()
            req.queue_span = None
        if self.telemetry is not None:
            self._queue_wait_hist.observe(req.admit_t - req.submit_t)
        with self._span("serve/prefill", rid=req.rid,
                        prompt_len=len(req.prompt)):
            tr = self._tracer
            if tr is not None and req.ctx is not None:
                # flow tail binds to this prefill span; each decode tick
                # the request rides emits a flow step
                tr.flow_start("serve/request", req.ctx, cat="serve",
                              rid=req.rid)
            with self._pallas_scope():
                self.cache, first = self._prefill_fn(
                    self.params, self.cache, tokens, length,
                    np.int32(self.scheduler.free[0]),
                    *self._maybe_key())
            first = int(np.asarray(jax.block_until_ready(first)))
        if self.spec_k:
            self._draft_prefill(req)
        now = time.perf_counter()
        req.prefill_s = now - req.admit_t
        slot = self.scheduler.admit(req, now=now)
        req.kv_len = len(req.prompt)
        req.tokens.append(first)
        req.token_times.append(now - req.submit_t)
        req.last_token = first
        self._count_token(now - req.submit_t)
        if self.telemetry is not None:
            # TTFT = queue wait + prefill (the first token comes out of
            # the prefill logits)
            self._ttft_hist.observe(now - req.submit_t)
        reason = self.scheduler.finish_reason(req, first,
                                              self.max_seq_len)
        if reason is not None:
            self._finish(slot, reason)
        return True

    def _admit(self) -> None:
        while self.scheduler.has_free():
            if self._pending:
                req = self._pending[0]
            else:
                req = self._pop_request()
                if req is None:
                    return
                self._pending.append(req)
            try:
                ok = self.stage.call(
                    "admit", lambda r=req: self._admit_one(r),
                    path=f"rid={req.rid}")
                if not ok:
                    # page-pool backpressure: the head request stays
                    # parked until eviction/release frees pages —
                    # admission order is preserved, the pool (not the
                    # slot count) is the binding constraint now
                    return
                self._pending.popleft()
            except BaseException as e:
                self._pending.popleft()
                self._fail_request(req, e)
                if not isinstance(e, Exception):
                    # KeyboardInterrupt / SystemExit are not a
                    # per-request failure: the cache may have been
                    # donated into the interrupted call, so poison and
                    # propagate instead of serving on
                    self._poison(e)
                    raise
                # one bad request must not take the pool down: record
                # its error and keep serving (Orca-style isolation) —
                # unless the cache was donated into the failing call, in
                # which case the engine is broken and must poison
                logger.error("serve: admission of rid=%d failed: %r",
                             req.rid, e)
                if self._cache_broken():
                    self._poison(e)
                    raise

    def _cache_broken(self) -> bool:
        """True when a failing call consumed a donated KV cache —
        target or draft: either loss means the engine cannot keep
        serving and must poison instead of isolating the request."""
        def dead(cache):
            k = cache.get("k")
            return not isinstance(k, jnp.ndarray) or \
                getattr(k, "is_deleted", lambda: False)()
        if dead(self.cache):
            return True
        return bool(self.spec_k) and dead(self._draft_cache)

    def _release_pages(self, req: Request) -> None:
        if req.pages:
            for p in req.pages:
                self.pool.deref(p)
        req.pages = None

    def _finish(self, slot: int, reason: str) -> None:
        req = self.scheduler.release(slot, reason)
        if self.paged:
            # eviction = page frees + a zeroed table row (scratch): the
            # freed pages are immediately admissible capacity — except
            # a KV-migration source (detach_kv), whose pages stay held
            # for export_pages; release_detached frees them after the
            # transfer
            self._table[slot, :] = 0
            if not req.detach_kv:
                self._release_pages(req)
        if self.lora and req.adapter_id:
            # unpin the tenant's adapter (refcount 0 keeps it RESIDENT
            # and evictable — the next request is a free hit) and point
            # the dead slot at the reserved zero adapter
            self.adapters.release(req.adapter_id)
            self._adapter_table[slot] = 0
            req.adapter_slot = 0
        # record + trace close BEFORE done.set(): a waiter released by
        # result() must find the completed artifacts already written
        self._write_request_record(req)
        self._end_request_trace(req, reason=reason)
        req.done.set()
        if self.telemetry is not None:
            self._requests_total.inc()

    # -- chunked prefill --------------------------------------------------
    def _prefill_chunk_tick(self) -> int:
        """One chunk of the OLDEST mid-prefill slot (Sarathi-Serve's
        co-scheduling policy, FIFO over prefilling slots): the same
        delta-aware compiled prefill program with ``prefix_len``
        advanced to the chunk boundary — same prefill_len bucket,
        traced prefix/delta lengths and page row, so N chunks cost
        zero recompiles.  Intermediate chunk logits are discarded; the
        FINAL chunk's next-token is the request's first token (TTFT
        stamps here).  Returns tokens produced (0 until the final
        chunk)."""
        req = None
        for r in self.scheduler.active.values():
            if r.prefilling:
                req = r
                break
        if req is None:
            return 0
        slot = req.slot
        delta = req.prompt[req.shared_len:]
        pos = req.chunk_pos
        chunk = delta[pos:pos + self.prefill_chunk_len]
        final = pos + len(chunk) >= len(delta)
        tokens = np.zeros((1, self.prefill_len), np.int32)
        tokens[0, :len(chunk)] = chunk
        with self._span("serve/prefill_chunk", rid=req.rid, pos=pos,
                        chunk=len(chunk)):
            tr = self._tracer
            if final and tr is not None and req.ctx is not None:
                tr.flow_start("serve/request", req.ctx, cat="serve",
                              rid=req.rid)
            with self._pallas_scope():
                self.cache, first = self._prefill_fn(
                    self.params, self.cache, tokens,
                    np.int32(len(chunk)),
                    np.int32(req.shared_len + pos),
                    self._table[slot], np.int32(slot),
                    *((self._lora_pools, np.int32(req.adapter_slot))
                      if self.lora else ()),
                    *self._maybe_key())
            first = int(np.asarray(jax.block_until_ready(first)))
        req.chunk_pos = pos + len(chunk)
        req.kv_len = req.shared_len + req.chunk_pos
        if not final:
            return 0
        now = time.perf_counter()
        req.prefilling = False
        req.prefill_s = now - req.admit_t
        req.kv_len = len(req.prompt)
        if self.prefix is not None:
            # the pages are fully written now — register them for
            # future sharers (deferred from admission), under the same
            # tenant namespace the admission matched with
            self.prefix.insert(
                req.prompt, req.pages,
                f"adapter:{req.adapter_id}" if req.adapter_id else "")
        if self.spec_k:
            self._draft_prefill(req, slot=slot)
        req.tokens.append(first)
        req.token_times.append(now - req.submit_t)
        req.last_token = first
        req.last_t = now
        self._count_token(now - req.submit_t)
        if self.telemetry is not None:
            self._ttft_hist.observe(now - req.submit_t)
        reason = self.scheduler.finish_reason(req, first,
                                              self.max_seq_len)
        if reason is not None:
            self._finish(slot, reason)
        return 1

    # -- the decode tick --------------------------------------------------
    def _decode_tick(self) -> int:
        # mid-prefill slots ride masked: they have no last token to
        # feed and their KV is a partial prefix (chunked prefill)
        active_map = {s: r for s, r in self.scheduler.active.items()
                      if not r.prefilling}
        if self.paged:
            # page-boundary appends allocate BEFORE the tick; a dry
            # pool (even after prefix-cache eviction) finishes the
            # request with the pool-exhaustion-aware kv_capacity reason
            # instead of letting the program write into the void
            for slot, req in list(active_map.items()):
                idx = req.kv_len // self.page_len
                if idx >= len(req.pages):
                    pg = self._alloc_pages(1)
                    if pg is None:
                        self._finish(slot, "kv_capacity")
                        del active_map[slot]
                        continue
                    req.pages.append(pg[0])
                    self._table[slot, idx] = pg[0]
        if not active_map:
            return 0
        tokens = np.zeros((self.slots,), np.int32)
        active = np.zeros((self.slots,), bool)
        for slot, req in active_map.items():
            tokens[slot] = req.last_token
            active[slot] = True
        with self._span("serve/decode_step", active=len(active_map)):
            tr = self._tracer
            if tr is not None:
                # per-tick decode attribution: each active request's
                # flow steps through this tick's span (host appends
                # only — the in-span sync below is the existing pull)
                for req in active_map.values():
                    if req.ctx is not None:
                        tr.flow_step("serve/request", req.ctx,
                                     cat="serve", rid=req.rid,
                                     tick=self._ticks)
            with self._pallas_scope():
                if self.paged:
                    self.cache, next_tok = self._decode_fn(
                        self.params, self.cache, tokens, active,
                        self._table,
                        *((self._lora_pools, self._adapter_table)
                          if self.lora else ()),
                        *self._maybe_key())
                else:
                    self.cache, next_tok = self._decode_fn(
                        self.params, self.cache, tokens, active,
                        *self._maybe_key())
            # the per-token latency point: the pull IS the device sync,
            # inside the span (transfer-real, JL006-clean)
            next_host = np.asarray(jax.block_until_ready(next_tok))
        now = time.perf_counter()
        produced = 0
        for slot, req in active_map.items():
            tok = int(next_host[slot])
            req.kv_len += 1
            req.tokens.append(tok)
            req.token_times.append(now - req.last_t)
            self._count_token(now - req.last_t)
            self._tpot_lat.append(now - req.last_t)
            req.last_t = now
            req.last_token = tok
            produced += 1
            reason = self.scheduler.finish_reason(req, tok,
                                                  self.max_seq_len)
            if reason is not None:
                self._finish(slot, reason)
        return produced

    def _spec_tick(self) -> int:
        """One SPECULATIVE serving tick (serving.speculate_k > 0): the
        draft proposes k tokens per active slot (k+1 chained draft
        passes in one compiled program), the target scores all k+1
        positions per slot in ONE widened verify pass, and each
        request advances by its accepted prefix plus the bonus token —
        1 to k+1 tokens for one target pass.  Accepted-length variance
        across slots is absorbed by the same masked machinery as
        admission/eviction; rejection rollback masks lengths back
        (unpaged) or frees the speculated pages (paged)."""
        W = self.spec_k + 1
        active_map = {s: r for s, r in self.scheduler.active.items()
                      if not r.prefilling}
        if self.paged:
            # allocate the whole speculative block's pages up front: a
            # pool too dry to hold W more rows (even after prefix-leaf
            # eviction) finishes the request with the same pool-aware
            # kv_capacity reason as the one-token appends
            for slot, req in list(active_map.items()):
                need = -(-min(req.kv_len + W, self.max_seq_len)
                         // self.page_len)
                extra = need - len(req.pages)
                if extra > 0:
                    pg = self._alloc_pages(extra)
                    if pg is None:
                        self._finish(slot, "kv_capacity")
                        del active_map[slot]
                        continue
                    for p in pg:
                        self._table[slot, len(req.pages)] = p
                        req.pages.append(p)
        if not active_map:
            return 0
        tokens = np.zeros((self.slots,), np.int32)
        active = np.zeros((self.slots,), bool)
        for slot, req in active_map.items():
            tokens[slot] = req.last_token
            active[slot] = True
        with self._span("serve/draft_propose", active=len(active_map),
                        k=self.spec_k):
            with self._pallas_scope():
                out = self._propose_fn(self.draft_params,
                                       self._draft_cache, tokens,
                                       active, *self._maybe_key())
            if self.temperature > 0:
                self._draft_cache, proposals, qprobs = out
                extra = (qprobs,) + self._maybe_key()
            else:
                self._draft_cache, proposals = out
                extra = ()
            # drain the draft INSIDE its span so the window times real
            # draft compute (the verify pull below syncs the rest)
            jax.block_until_ready(proposals)
        with self._span("serve/verify_step", active=len(active_map),
                        k=self.spec_k):
            tr = self._tracer
            if tr is not None:
                for req in active_map.values():
                    if req.ctx is not None:
                        tr.flow_step("serve/request", req.ctx,
                                     cat="serve", rid=req.rid,
                                     tick=self._ticks)
            with self._pallas_scope():
                if self.paged:
                    self.cache, out_tok, accepted = self._verify_fn(
                        self.params, self.cache, tokens, proposals,
                        active, self._table,
                        *((self._lora_pools, self._adapter_table)
                          if self.lora else ()),
                        *extra)
                else:
                    self.cache, out_tok, accepted = self._verify_fn(
                        self.params, self.cache, tokens, proposals,
                        active, *extra)
            # the per-block latency point: the pull IS the device
            # sync, inside the span (transfer-real, JL006-clean)
            out_host = np.asarray(jax.block_until_ready(out_tok))
            acc_host = np.asarray(accepted)
        now = time.perf_counter()
        produced = 0
        for slot, req in active_map.items():
            m = int(acc_host[slot])
            emit = [int(t) for t in out_host[slot, :m + 1]]
            finished = False
            first_of_block = True
            used = 0
            for tok in emit:
                # the block lands at one wall moment: the first token
                # carries the pass latency, the rest arrive "free" —
                # the burst semantics the latency histograms should see
                req.kv_len += 1
                req.tokens.append(tok)
                lat = (now - req.last_t) if first_of_block else 0.0
                first_of_block = False
                req.token_times.append(lat)
                self._count_token(lat)
                self._tpot_lat.append(lat)
                produced += 1
                used += 1
                reason = self.scheduler.finish_reason(
                    req, tok, self.max_seq_len)
                if reason is not None:
                    # EOS (or budget/capacity) INSIDE the accepted
                    # block: the tail of the block is discarded, the
                    # slot frees this tick — _finish releases every
                    # page incl. the speculative pre-allocation
                    self._finish(slot, reason)
                    finished = True
                    break
            # accounting counts tokens the pass actually DELIVERED
            # (used - 1 accepted drafts + the first/bonus token), not
            # what verify hypothetically accepted: an EOS/budget/
            # capacity truncation inside the block must not let the
            # mean-accepted-length scalars drift from
            # serve_tokens_total (they share the 1/MAL denominator)
            req.spec_accepted.append(used - 1)
            self._spec_passes += 1
            self._spec_proposed_n += self.spec_k
            self._spec_accepted_n += used - 1
            if self.telemetry is not None:
                self._spec_proposed.inc(self.spec_k)
                self._spec_accepted_ctr.inc(used - 1)
                self._spec_len_hist.observe(used)
            if finished:
                continue
            req.last_t = now
            req.last_token = emit[-1]
            if self.paged:
                # rollback: keep the pages covering the verified rows,
                # free the ones only rejected speculation touched
                keep = -(-req.kv_len // self.page_len)
                while len(req.pages) > keep:
                    pg = req.pages.pop()
                    self._table[slot, len(req.pages)] = 0
                    self.pool.deref(pg)
        # draft rollback: one replicated lengths row masks every live
        # slot's draft KV back to its verified length (rejected draft
        # rows become dead tail the kernels never attend)
        dlen = np.zeros((self.slots,), np.int32)
        for slot, req in self.scheduler.active.items():
            dlen[slot] = req.kv_len
        self._draft_cache = dict(self._draft_cache)
        self._draft_cache["lengths"] = jax.device_put(
            jnp.asarray(dlen), self._draft_shardings["lengths"])
        return produced

    def step(self) -> int:
        """One serving tick: admit into free slots, then one masked
        decode — or, speculating, one draft-propose + widened-verify
        block — over the whole pool.  Returns tokens produced."""
        if self._closed:
            raise RuntimeError("ServeEngine is closed")
        if self.kv_tier is not None:
            # park BEFORE admission so pages freed by parking are
            # immediately allocatable this very tick
            self.kv_tier.park_tick(self._ticks)
        self._admit()
        try:
            n = 0
            if self.prefill_chunk_len and any(
                    r.prefilling
                    for r in self.scheduler.active.values()):
                # chunked-prefill co-scheduling: ONE chunk rides this
                # tick next to the decode pass, and the stage point
                # charges one injected delay unit per CHUNK
                # (docs/stages.md) — the bounded-stall guarantee the
                # disagg bench proves
                n += self.stage.call("prefill_chunk",
                                     self._prefill_chunk_tick)
            n += self.stage.call(
                "step",
                self._spec_tick if self.spec_k else self._decode_tick)
        except BaseException as e:
            self._poison(e)
            raise
        if self.telemetry is not None:
            self._active_gauge.set(len(self.scheduler.active))
            if self.paged:
                self._free_pages_gauge.set(self.pool.free_count)
        self._ticks += 1
        if self._ticks % self._flush_every == 0:
            self._flush()
        return n

    def run_until_idle(self, max_ticks: int = 100_000) -> int:
        """Serve until the queue and every slot are empty.  Returns
        total tokens produced."""
        total = 0
        for _ in range(max_ticks):
            if not self.scheduler.active and not self._pending \
                    and self.queue.qsize() == 0:
                return total
            total += self.step()
        raise RuntimeError(
            f"serve loop still busy after max_ticks={max_ticks} "
            f"({len(self.scheduler.active)} active, "
            f"{len(self._pending)} pending, "
            f"{self.queue.qsize()} queued)")

    # -- KV-page migration (disaggregated fleet) --------------------------
    def _page_leaves(self) -> List[str]:
        """Pool-shaped cache leaves in the fixed wire order (mirrors
        _copy_fn: scales ride along on the quantized pool)."""
        return [k for k in ("k", "v", "k_scale", "v_scale")
                if k in self.cache]

    def page_leaf_nbytes(self) -> List[int]:
        """Per-leaf byte lengths inside ONE exported page payload —
        the binary frame header's validation contract (both ends of a
        migration run the same config, so these must agree)."""
        return [int(self.cache[k].nbytes) // int(self.cache[k].shape[1])
                for k in self._page_leaves()]

    def export_pages(self, req: Request) -> List[bytes]:
        """A finished ``detach_kv`` request's KV pages as raw bytes,
        one payload per page: the page's leaf slices concatenated in
        ``_page_leaves`` order.  Whole pages ship (the bounded page
        copy — a partial tail's dead rows are masked by lengths on the
        importing side); the page index is traced, so N exports ride
        one compiled program.  Call :meth:`release_detached` after the
        payloads hit the wire."""
        if not self.paged or not req.pages:
            raise RuntimeError(
                "export_pages needs a paged engine and a finished "
                "detach_kv request still holding its pages")
        out = []
        for pid in req.pages:
            with self._span("serve/page_out", rid=req.rid, page=pid):
                with self._pallas_scope():
                    slices = self._page_out_fn(self.cache,
                                               np.int32(pid))
                slices = jax.block_until_ready(slices)
            out.append(b"".join(np.asarray(s).tobytes()
                                for s in slices))
        return out

    def release_detached(self, req: Request) -> None:
        """Drop the pages a ``detach_kv`` finish kept alive — the
        export's payloads are on the wire, the pages are admissible
        capacity again."""
        self._release_pages(req)

    def _export_page_bytes(self, pid: int) -> bytes:
        """ONE pool page as raw host bytes — the KV tier's spill unit
        (``export_pages``'s packing for a single page; the tier CRC-
        stamps the result before the page's pool ref is released)."""
        with self._span("serve/kv_spill", page=pid):
            with self._pallas_scope():
                slices = self._page_out_fn(self.cache, np.int32(pid))
            slices = jax.block_until_ready(slices)
        return b"".join(np.asarray(s).tobytes() for s in slices)

    def _import_page_bytes(self, pid: int, payload: bytes) -> None:
        """Import one parked page payload into pool page ``pid`` — the
        KV tier's fetch unit (``adopt_request``'s unpacking for a
        single page).  A size mismatch is a corrupt record, typed so
        the tier's recompute fallback catches it."""
        from .kv_tier import KVTierCorruptError
        leaves, off = [], 0
        for ref in [self.cache[k] for k in self._page_leaves()]:
            nb = int(ref.nbytes) // int(ref.shape[1])
            shape = ref.shape[:1] + (1,) + ref.shape[2:]
            leaves.append(np.frombuffer(
                payload, dtype=np.dtype(ref.dtype),
                count=nb // ref.dtype.itemsize,
                offset=off).reshape(shape))
            off += nb
        if off != len(payload):
            raise KVTierCorruptError(
                f"parked page payload is {len(payload)} bytes; this "
                f"pool's page is {off}")
        with self._span("serve/kv_fetch", page=pid):
            with self._pallas_scope():
                self.cache = self._page_in_fn(self.cache,
                                              np.int32(pid), *leaves)

    def adopt_request(self, prompt, first_token: int,
                      max_new_tokens: int,
                      eos_id: Optional[int],
                      page_payloads: List[bytes],
                      adapter_id: int = 0) -> Optional[Request]:
        """Adopt a migrated request mid-decode (docs/serving.md
        "disaggregated fleet"): import its exported KV pages into
        freshly allocated local pages (page ids are replica-local —
        the table is rebuilt), restore the slot's cache length, and
        resume decoding from ``first_token`` on the next tick.
        Identical params + imported KV ⇒ the continued stream is
        bitwise the single-replica stream (the parity bar).  Returns
        None when no slot or pages are free yet — the caller parks and
        retries, the same backpressure contract as admission."""
        if not self.paged:
            raise RuntimeError("KV adoption requires the paged layout")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        need = -(-len(prompt) // self.page_len)
        if need != len(page_payloads):
            raise ValueError(
                f"migrated request ships {len(page_payloads)} pages "
                f"but a {len(prompt)}-token prompt needs {need}")
        if not self.scheduler.has_free():
            return None
        pages = self._alloc_pages(need)
        if pages is None:
            return None
        adapter_id = int(adapter_id)
        if adapter_id > 0 and not self.lora:
            raise ValueError(
                f"migrated request carries adapter_id={adapter_id} but "
                "multi-tenant LoRA is off on this replica")
        aslot = 0
        if self.lora and adapter_id:
            # same ordering as admission: adapter pin AFTER page alloc,
            # pool-dry parks (deterministic synthesis means this
            # replica derives the identical weights locally — no
            # adapter bytes ride the migration payload)
            try:
                got = self.adapters.acquire(adapter_id)
            except BaseException:
                for p in pages:
                    self.pool.deref(p)
                raise
            if got is None:
                for p in pages:
                    self.pool.deref(p)
                return None
            aslot = got
        self._rid += 1
        now = time.perf_counter()
        req = Request(rid=self._rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      eos_id=(self.eos_id_default if eos_id is None
                              else int(eos_id)),
                      submit_t=now)
        req.admit_t = now
        req.adapter_id = adapter_id
        try:
            leaf_refs = [self.cache[k] for k in self._page_leaves()]
            for pid, payload in zip(pages, page_payloads):
                leaves, off = [], 0
                for ref in leaf_refs:
                    nb = int(ref.nbytes) // int(ref.shape[1])
                    shape = ref.shape[:1] + (1,) + ref.shape[2:]
                    leaves.append(np.frombuffer(
                        payload, dtype=np.dtype(ref.dtype),
                        count=nb // ref.dtype.itemsize,
                        offset=off).reshape(shape))
                    off += nb
                if off != len(payload):
                    raise ValueError(
                        f"migrated page payload is {len(payload)} "
                        f"bytes; this pool's page is {off} (config "
                        "mismatch between migration endpoints)")
                with self._span("serve/page_in", rid=req.rid,
                                page=pid):
                    with self._pallas_scope():
                        self.cache = self._page_in_fn(
                            self.cache, np.int32(pid), *leaves)
        except BaseException:
            for p in pages:
                self.pool.deref(p)
            if aslot:
                self.adapters.release(adapter_id)
            raise
        slot = self.scheduler.admit(req, now=now)
        req.pages = list(pages)
        req.shared_len = 0
        req.computed_len = len(prompt)
        req.kv_len = len(prompt)
        self._table[slot, :] = 0
        self._table[slot, :len(pages)] = pages
        if self.lora:
            req.adapter_slot = aslot
            self._adapter_table[slot] = aslot
        with self._pallas_scope():
            self.cache = self._set_len_fn(self.cache, np.int32(slot),
                                          np.int32(len(prompt)))
        if self.spec_k:
            # the draft has no imported pages — mirror the prompt into
            # its slot cache the ordinary way (draft prefill is cheap)
            self._draft_prefill(req, slot=slot)
        # the first token was generated (and latency-counted) on the
        # prefill replica; record it here without double-counting
        req.tokens.append(int(first_token))
        req.token_times.append(0.0)
        req.last_token = int(first_token)
        req.last_t = now
        reason = self.scheduler.finish_reason(req, int(first_token),
                                              self.max_seq_len)
        if reason is not None:
            self._finish(slot, reason)
        return req

    # -- failure + shutdown ----------------------------------------------
    def _fail_request(self, req: Request, err: BaseException) -> None:
        """The one per-request failure path: record + trace close
        BEFORE done.set() (a released waiter must find the artifacts
        written), and keep the failed counter consistent with the
        record-derived summarize count."""
        req.error = err
        self._write_request_record(req)
        self._end_request_trace(req, error=err)
        req.done.set()
        if self.telemetry is not None:
            self._requests_failed.inc()

    def _poison(self, err: BaseException) -> None:
        """A failed decode tick is fatal for every in-flight request:
        donation means the cache is gone.  Typed propagation — requests
        and submitters see the ORIGINAL exception.  Every in-flight
        request's trace ends with an error span (no leaked flows), and
        the flight recorder dumps the pool's last moments."""
        self.queue.poison(err)
        self.stage.record_event("poison", error=repr(err))
        for slot in list(self.scheduler.active):
            req = self.scheduler.release(slot, "error")
            if self.paged:
                self._table[slot, :] = 0
                self._release_pages(req)
            if self.lora and req.adapter_id:
                self.adapters.release(req.adapter_id)
                self._adapter_table[slot] = 0
            self._fail_request(req, err)
        # backpressure-parked requests are in flight too — fail them
        # with the same original exception, never strand their waiters
        while self._pending:
            self._fail_request(self._pending.popleft(), err)
        self.dump_flight_record(reason="serve poison", error=err)

    def _close_queue(self):
        err = RuntimeError("ServeEngine closed")
        # mark closed and capture the backlog under ONE lock hold: a
        # submit() racing close() either sees put() return False
        # (raises to its caller) or its item lands in `items` here and
        # fails typed — never silently cleared with a hung waiter
        with self.queue.cond:
            self.queue.closed = True
            items = list(self.queue.items)
            self.queue.items.clear()
            self.queue.cond.notify_all()
        items = list(self._pending) + items
        self._pending.clear()
        for req in items:
            self._fail_request(req, err)
        if self.prefix is not None:
            self.prefix.clear()

    def _drain_kv_spill(self):
        """Write every host-resident parked page to the disk tier
        (when one exists) — the spill plane's drain barrier, so parked
        sessions survive the process."""
        if self.kv_tier is not None:
            self.kv_tier.drain()

    def _close_kv_spill(self):
        if self.kv_tier is not None:
            self.kv_tier.close_spill()

    def _close_kv_fetch(self):
        if self.kv_tier is not None:
            self.kv_tier.close()

    def _close_telemetry(self):
        if self.telemetry is not None:
            self._flush()
            self.telemetry.close()

    def close(self):
        """Idempotent: drain order is queue -> kv spill -> kv fetch ->
        telemetry (docs/serving.md); queued never-admitted requests
        fail with a typed error instead of hanging their waiters."""
        if self._closed:
            return
        self._closed = True
        errors = self._graph.close_all()
        if errors:
            raise errors[0][1]
