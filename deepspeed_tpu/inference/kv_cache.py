"""Slot-based KV cache: the device state of the serving engine.

One fixed-shape pytree holds every request's keys/values:

    k, v     [L, S, H, T, Dh]   layer-major, slot-batched
    lengths  [S] int32          per-slot LIVE length (0 = free slot)

The shapes never change for the life of the engine — admission writes a
prefilled request's K/V rows into its slot, decode appends one row per
tick, eviction just zeroes the slot's ``lengths`` entry on the next
admission (the stale rows are masked by length and never attended; the
decode kernel hard-zeroes length-0 slots).  That static-shape contract
is what lets ONE compiled decode program serve arbitrary request mixes
(docs/serving.md).

Sharding rides the existing mesh plumbing (parallel/mesh.py): heads on
the ``model`` axis (the same Megatron split the qkv weights declare, so
each TP shard caches exactly the heads it computes), slots on the
``data`` axis (replica-parallel serving — the EP/DP batch dimension).
``lengths`` is replicated: every shard runs the same masking.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    layers: int
    slots: int
    heads: int
    max_len: int
    head_dim: int
    dtype: Any = jnp.float32

    @property
    def bytes(self) -> int:
        per = jnp.dtype(self.dtype).itemsize
        return (2 * self.layers * self.slots * self.heads * self.max_len
                * self.head_dim * per)


def init_cache(spec: KVCacheSpec) -> Dict[str, jnp.ndarray]:
    """Fresh all-free cache pytree (host zeros; shard with
    :func:`shard_cache` before handing it to compiled programs)."""
    shape = (spec.layers, spec.slots, spec.heads, spec.max_len,
             spec.head_dim)
    return {
        "k": jnp.zeros(shape, spec.dtype),
        "v": jnp.zeros(shape, spec.dtype),
        "lengths": jnp.zeros((spec.slots,), jnp.int32),
    }


def cache_partition_specs() -> Dict[str, P]:
    """PartitionSpecs for the cache pytree: slots on ``data``, heads on
    ``model`` (matching the models' Megatron qkv column split)."""
    kv = P(None, DATA_AXIS, MODEL_AXIS, None, None)
    return {"k": kv, "v": kv, "lengths": P()}


def cache_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {name: NamedSharding(mesh, spec)
            for name, spec in cache_partition_specs().items()}


def validate_cache_mesh(mesh: Mesh, spec: KVCacheSpec) -> None:
    """The slot/head counts must divide their mesh axes — fail at build
    time with the real story, not as a GSPMD sharding error mid-serve."""
    dp = mesh.shape.get(DATA_AXIS, 1)
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if spec.slots % dp != 0:
        raise ValueError(
            f"serving.slots={spec.slots} must be divisible by the mesh's "
            f"data axis ({dp}): slots are the replica-sharded batch "
            "dimension of the decode program")
    if spec.heads % tp != 0:
        raise ValueError(
            f"model heads={spec.heads} must be divisible by the mesh's "
            f"model axis ({tp}) to TP-shard the KV cache")
    for axis in ("pipe", "seq"):
        if mesh.shape.get(axis, 1) != 1:
            raise ValueError(
                f"the serving engine does not shard over the {axis!r} "
                f"axis (mesh has {axis}={mesh.shape[axis]}); serve on a "
                "(data, model) mesh")


def shard_cache(cache: Dict[str, jnp.ndarray],
                mesh: Mesh) -> Dict[str, jnp.ndarray]:
    sh = cache_shardings(mesh)
    return {name: jax.device_put(leaf, sh[name])
            for name, leaf in cache.items()}
