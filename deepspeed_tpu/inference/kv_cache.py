"""Slot-based and page-pooled KV caches: the device state of serving.

Two layouts share this file (docs/serving.md):

**Slot cache** (the pre-page reference arm) — one fixed stride per
slot:

    k, v     [L, S, H, T, Dh]   layer-major, slot-batched
    lengths  [S] int32          per-slot LIVE length (0 = free slot)

**Paged cache** (``serving.page_len > 0`` — PagedAttention, PAPERS.md)
— a flat pool of fixed-size pages plus host-owned page tables:

    k, v     [L, P, H, page_len, Dh]   layer-major, page-pooled
    lengths  [S] int32                 per-slot LIVE length

A slot's KV rows live wherever its int32 page table (a TRACED operand
of the decode program, never part of any compiled shape) points; page 0
is the reserved scratch page masked writes of inactive slots land on,
so scatter conflicts can only happen between no-op writes.  Short
requests hold ceil(len/page_len) pages instead of a full ``max_seq_len``
stride — the pool, not the slot count, caps concurrency.

The shapes never change for the life of the engine — admission writes a
prefilled request's K/V rows in place, decode appends one row per tick,
eviction is host bookkeeping (page frees / masked stale rows).  That
static-shape contract is what lets ONE compiled decode program serve
arbitrary request mixes.

Sharding rides the existing mesh plumbing (parallel/mesh.py): heads on
the ``model`` axis (the same Megatron split the qkv weights declare, so
each TP shard caches exactly the heads it computes), slots — or the
page pool — on the ``data`` axis (replica-parallel serving — the EP/DP
batch dimension).  ``lengths`` is replicated: every shard runs the same
masking.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    layers: int
    slots: int
    heads: int
    max_len: int
    head_dim: int
    dtype: Any = jnp.float32

    @property
    def bytes(self) -> int:
        per = jnp.dtype(self.dtype).itemsize
        return (2 * self.layers * self.slots * self.heads * self.max_len
                * self.head_dim * per)


def init_cache(spec: KVCacheSpec) -> Dict[str, jnp.ndarray]:
    """Fresh all-free cache pytree (host zeros; shard with
    :func:`shard_cache` before handing it to compiled programs)."""
    shape = (spec.layers, spec.slots, spec.heads, spec.max_len,
             spec.head_dim)
    return {
        "k": jnp.zeros(shape, spec.dtype),
        "v": jnp.zeros(shape, spec.dtype),
        "lengths": jnp.zeros((spec.slots,), jnp.int32),
    }


def cache_partition_specs() -> Dict[str, P]:
    """PartitionSpecs for the cache pytree: slots on ``data``, heads on
    ``model`` (matching the models' Megatron qkv column split)."""
    kv = P(None, DATA_AXIS, MODEL_AXIS, None, None)
    return {"k": kv, "v": kv, "lengths": P()}


def cache_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {name: NamedSharding(mesh, spec)
            for name, spec in cache_partition_specs().items()}


def _validate_tp_and_axes(mesh: Mesh, heads: int, what: str) -> None:
    """The checks both cache layouts share: TP-divisible heads and a
    strictly (data, model) mesh — fail at build time with the real
    story, not as a GSPMD sharding error mid-serve."""
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if heads % tp != 0:
        raise ValueError(
            f"model heads={heads} must be divisible by the mesh's "
            f"model axis ({tp}) to TP-shard the {what}")
    for axis in ("pipe", "seq"):
        if mesh.shape.get(axis, 1) != 1:
            raise ValueError(
                f"the serving engine does not shard over the {axis!r} "
                f"axis (mesh has {axis}={mesh.shape[axis]}); serve on a "
                "(data, model) mesh")


def validate_cache_mesh(mesh: Mesh, spec: KVCacheSpec) -> None:
    dp = mesh.shape.get(DATA_AXIS, 1)
    if spec.slots % dp != 0:
        raise ValueError(
            f"serving.slots={spec.slots} must be divisible by the mesh's "
            f"data axis ({dp}): slots are the replica-sharded batch "
            "dimension of the decode program")
    _validate_tp_and_axes(mesh, spec.heads, "KV cache")


# ---------------------------------------------------------------------------
# paged layout (serving.page_len > 0)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PagedKVCacheSpec:
    """The flat page pool: ``pages`` fixed-size pages of ``page_len``
    tokens each (page 0 reserved as the scratch page), referenced by
    per-slot page tables the host owns.

    ``quant`` (serving.quantization.kv='int8', docs/serving.md): the
    pool stores int8 rows (``dtype`` must be int8) plus a fp32 scale
    sidecar ``[L, pages, H, page_len]`` — one scale per stored token
    row per head, quantized at write time (inference/quantize.py).
    ``bytes``/``page_bytes`` include the sidecar: they are the ONE
    source of KV-byte truth the bench budgets and the
    ``serve_kv_bytes`` gauge read."""
    layers: int
    slots: int
    heads: int
    pages: int
    page_len: int
    head_dim: int
    #: table width: pages a slot can reference (ceil(max_len/page_len))
    max_pages: int
    dtype: Any = jnp.float32
    #: int8 rows + per-(page, head, row) fp32 scale sidecar
    quant: bool = False

    @property
    def bytes(self) -> int:
        per = jnp.dtype(self.dtype).itemsize
        n = (2 * self.layers * self.pages * self.heads * self.page_len
             * self.head_dim * per)
        if self.quant:
            n += (2 * self.layers * self.pages * self.heads
                  * self.page_len * 4)
        return n

    @property
    def page_bytes(self) -> int:
        """HBM of ONE page across layers and both of k/v (incl. the
        quant scale sidecar rows) — the allocation quantum the bench's
        fixed-byte budget divides by."""
        per = jnp.dtype(self.dtype).itemsize
        n = 2 * self.layers * self.heads * self.page_len \
            * self.head_dim * per
        if self.quant:
            n += 2 * self.layers * self.heads * self.page_len * 4
        return n


def init_paged_cache(spec: PagedKVCacheSpec) -> Dict[str, jnp.ndarray]:
    """Fresh all-free paged pool (host zeros; shard with
    :func:`shard_cache` before handing it to compiled programs).
    Quantized pools get all-zero scale sidecars: dequant of a never-
    written row is 0 * scale = exact zero, the same dead-data story as
    the fp pool."""
    shape = (spec.layers, spec.pages, spec.heads, spec.page_len,
             spec.head_dim)
    cache = {
        "k": jnp.zeros(shape, spec.dtype),
        "v": jnp.zeros(shape, spec.dtype),
        "lengths": jnp.zeros((spec.slots,), jnp.int32),
    }
    if spec.quant:
        sshape = (spec.layers, spec.pages, spec.heads, spec.page_len)
        cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
        cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return cache


def paged_partition_specs(quant: bool = False) -> Dict[str, P]:
    """Pool pages on ``data``, heads on ``model`` — the page pool is
    the DP-sharded storage dimension the way slots were.  The quant
    scale sidecars shard exactly like their pools (minus the row dim's
    trailing head_dim)."""
    kv = P(None, DATA_AXIS, MODEL_AXIS, None, None)
    specs = {"k": kv, "v": kv, "lengths": P()}
    if quant:
        sc = P(None, DATA_AXIS, MODEL_AXIS, None)
        specs["k_scale"] = sc
        specs["v_scale"] = sc
    return specs


def paged_cache_shardings(mesh: Mesh,
                          quant: bool = False) -> Dict[str, NamedSharding]:
    return {name: NamedSharding(mesh, spec)
            for name, spec in paged_partition_specs(quant).items()}


def validate_paged_cache_mesh(mesh: Mesh,
                              spec: PagedKVCacheSpec) -> None:
    dp = mesh.shape.get(DATA_AXIS, 1)
    if spec.pages % dp != 0:
        raise ValueError(
            f"serving.pages={spec.pages} must be divisible by the "
            f"mesh's data axis ({dp}): the page pool is the DP-sharded "
            "storage dimension of the decode program")
    _validate_tp_and_axes(mesh, spec.heads, "KV page pool")


def shard_cache(cache: Dict[str, jnp.ndarray], mesh: Mesh,
                shardings: Optional[Dict[str, NamedSharding]] = None,
                ) -> Dict[str, jnp.ndarray]:
    """Place a cache pytree (either layout) onto the mesh with ONE
    batched list-form ``jax.device_put`` for all leaves — the PR 3/4
    ``_assemble``/``_shard_batch`` idiom: one dispatch instead of one
    per leaf (the spy test in tests/test_paged_kv.py pins the count)."""
    if shardings is None:
        shardings = cache_shardings(mesh)
    names = sorted(cache)
    placed = jax.device_put([cache[n] for n in names],
                            [shardings[n] for n in names])
    return dict(zip(names, placed))
