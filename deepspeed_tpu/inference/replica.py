"""One serving-fleet replica: an ordinary :class:`ServeEngine` behind
a wire socket (``python -m deepspeed_tpu.inference.replica`` — spawned
by ``inference/fleet.py``, docs/serving.md "serving fleet").

The replica is deliberately boring: it builds a model from the config's
``fleet_model`` block (deterministic — every replica of a fleet holds
IDENTICAL params because they share the init seed, which is what makes
failover re-dispatch emit the same greedy stream), connects OUT to the
router's listening socket, says hello, and then pumps three things in
one single-threaded loop:

  frames in    ``submit`` → ``ServeEngine.submit`` (a per-request
               failure — bad prompt, closed queue — answers with an
               ``error`` frame and the pool keeps serving: the Orca
               isolation the engine already provides);
               ``shutdown`` → drain in-flight requests, then exit 0.
  engine tick  ``ServeEngine.step()`` whenever there is work — the
               SAME stage-runtime serving loop as a bare engine, so
               poison/drain/degradation, ``DS_STAGE_FAULT`` /
               ``DS_STAGE_DELAY_S`` chaos and the flight recorder all
               apply unchanged.  An engine POISON (a failed tick kills
               every in-flight request — the cache was donated) exits
               the process with rc 13 WITHOUT error frames: the
               router's failover path re-dispatches the queued-but-
               unstarted requests and typed-fails the mid-stream ones,
               and the original exception is in this replica's flight
               record (``<fleet_dir>/replica_<id>/flightrec_*.json`` —
               the corpse the recorder captured).
  frames out   ``admit`` the moment the engine assigns a slot (the
               router stamps queue wait — the SLO signal), ``token``
               for newly generated ids, ``done``/``error`` on finish.

Disaggregated roles (docs/serving.md "disaggregated fleet"): the
router spawns each replica with ``--role`` (prefill / decode / mixed).
A ``submit`` frame carrying ``migrate: true`` runs the prefill leg
only — one token with ``detach_kv``, then the finished request's KV
pages stream back as ``migrate_out`` + binary page frames and the
local pages free.  A ``migrate_in`` frame (+ its page frames) adopts
a migrated request mid-decode; adoption backpressures host-side like
any admission.  The role itself steers nothing here — the ROUTER
decides who prefills and who decodes; the replica just executes both
halves of the handoff.

Liveness + load: every loop writes a heartbeat into the shared fleet
dir (``telemetry/heartbeat.py``) carrying the serving gauges the
router's join-shortest-queue balancer and per-role autoscaler read —
``role``, ``serve_active_slots``, request-queue depth,
``serve_free_pages`` (paged), ``serve_tpot_p99_s`` (the decode-SLO
gauge), the speculation accept ratio.  Telemetry (when enabled) lands
in ``<fleet_dir>/replica_<id>/`` so ``python -m deepspeed_tpu.
telemetry diagnose <fleet_dir>`` can correlate the whole fleet
post-mortem.
"""
from __future__ import annotations

import argparse
import json
import os
import select
import socket
import sys
import time
from collections import deque
from typing import Dict

#: exit code of an engine poison — the router reads any nonzero exit
#: as replica death; 13 just makes the corpse recognizable in logs
POISON_EXIT_CODE = 13

#: minimum wall seconds between heartbeat writes (a 1ms decode tick
#: must not turn the beat file into an fsync storm)
BEAT_INTERVAL_S = 0.1


class _Tracked:
    """Router-rid → engine-request bridge: how many tokens were already
    streamed, and whether admission was reported.  ``migrate`` marks a
    prefill-leg request whose finish exports KV pages instead of a
    ``done`` frame; adopted requests start with the first token already
    streamed by their prefill replica (``sent=1``, admission already
    stamped)."""

    def __init__(self, req, migrate: bool = False, sent: int = 0,
                 admit_sent: bool = False):
        self.req = req
        self.sent = sent
        self.admit_sent = admit_sent
        self.migrate = migrate


def build_engine(cfg: dict, fleet_dir: str, replica_id: int):
    """Model + ServeEngine from the fleet config: the ``fleet_model``
    block names a GPT-2 geometry and an init seed shared by every
    replica (identical params ⇒ identical greedy streams ⇒ failover
    and single-replica parity are exact)."""
    from ..models.gpt2 import GPT2Config, GPT2Model
    from .engine import ServeEngine
    mspec = cfg.get("fleet_model")
    if not isinstance(mspec, dict):
        raise SystemExit(
            "replica: config needs a 'fleet_model' block "
            "({vocab_size, n_positions, d_model, n_layer, n_head, "
            "attn_impl, seed}) — the deterministic model every replica "
            "of the fleet builds")
    gcfg = GPT2Config(
        vocab_size=int(mspec.get("vocab_size", 256)),
        n_positions=int(mspec.get("n_positions", 64)),
        d_model=int(mspec.get("d_model", 64)),
        n_layer=int(mspec.get("n_layer", 2)),
        n_head=int(mspec.get("n_head", 4)),
        remat=None,
        attn_impl=mspec.get("attn_impl", "dense"))
    model = GPT2Model(gcfg)
    engine_cfg = dict(cfg)
    tel = dict(cfg.get("telemetry") or {})
    if tel.get("enabled"):
        # each replica's telemetry (events, traces, the poison flight
        # record) lands in its own subdir of the fleet directory
        tel["output_path"] = os.path.join(fleet_dir,
                                          f"replica_{replica_id}")
        engine_cfg["telemetry"] = tel
    return ServeEngine(model, engine_cfg,
                       seed=int(mspec.get("seed", 0)))


def _beat_extra(eng, replica_id: int, backlog_n: int = 0,
                role: str = "mixed") -> dict:
    extra = {
        "replica": replica_id,
        #: the per-role autoscaler's grouping dimension (and the
        #: heartbeat_age_s{role=} label in the router's metrics)
        "role": role,
        "serve_active_slots": len(eng.scheduler.active),
        # the JSQ load gauge counts EVERY queued request this replica
        # holds: engine channel + parked admissions + the socket-side
        # overflow backlog
        "serve_queue_depth": (eng.queue.qsize() + len(eng._pending)
                              + backlog_n),
    }
    if eng.paged:
        extra["serve_free_pages"] = eng.pool.free_count
    if eng.lora:
        # the router's tenant-affinity signal: adapters this replica
        # already holds in HBM slots (a dispatch here skips the
        # cold-adapter host->HBM fetch)
        extra["adapters_hot"] = eng.hot_adapters()
    if eng.spec_k:
        extra["spec_accept_ratio"] = eng._spec_ratio()
    tpot = eng.tpot_p99()
    if tpot is not None:
        # the decode-phase SLO gauge the per-role autoscaler defends
        # (docs/serving.md "disaggregated fleet")
        extra["serve_tpot_p99_s"] = round(tpot, 6)
    return extra


def serve(router_addr, replica_id: int, fleet_dir: str,
          cfg: dict, role: str = "mixed") -> int:
    from ..telemetry.heartbeat import HeartbeatWriter
    from .wire import (BinaryFrame, FrameReader, drain_socket,
                       send_binary_frame, send_frame)

    eng = build_engine(cfg, fleet_dir, replica_id)
    hb = HeartbeatWriter(fleet_dir, process_index=replica_id)
    sock = socket.create_connection(router_addr, timeout=30.0)
    sock.settimeout(10.0)
    reader = FrameReader()
    # warm the compiled programs BEFORE saying hello: the router's
    # spawn_timeout_s is sized for jax import + FIRST COMPILE, but
    # after hello only heartbeat_timeout_s guards liveness — and the
    # replica can't beat while blocked inside a first-tick compile, so
    # a real model compiling longer than the beat timeout would be
    # killed as "hung" (and every replacement after it, straight into
    # the give-up budget).  eos_id=-1 never matches a token, so the
    # warm request is guaranteed to reach a decode tick (spec mode:
    # a draft-propose + verify pass) and compile every serving program.
    warm = eng.submit([0], max_new_tokens=2, eos_id=-1)
    eng.run_until_idle()
    assert warm.error is None, f"warmup failed: {warm.error!r}"
    send_frame(sock, {"kind": "hello", "replica": replica_id,
                      "pid": os.getpid(), "role": role})
    hb.beat(0, extra=_beat_extra(eng, replica_id, role=role))
    last_beat = time.monotonic()

    live: Dict[int, _Tracked] = {}
    #: migrate_in transfers still collecting their binary page frames:
    #: rid -> (header, payload list)
    inbound: Dict[int, tuple] = {}
    #: complete transfers waiting for a free slot/pages — adoption
    #: backpressure parks here, FIFO like the engine's _pending
    adoptions: deque = deque()
    #: submit frames not yet handed to the engine: the engine's
    #: request Channel is a BLOCKING bounded queue, and a single-
    #: threaded replica that blocks in submit() can never step the
    #: engine to free the space it is waiting for — so overflow parks
    #: here (host-side, cheap) and drains as the engine makes room
    backlog: deque = deque()
    qcap = eng.queue.capacity or (1 << 30)
    shutting_down = False

    def flush_outputs() -> None:
        for rid in list(live):
            tr = live[rid]
            req = tr.req
            if not tr.admit_sent and req.admit_t:
                tr.admit_sent = True
                send_frame(sock, {"kind": "admit", "rid": rid})
            n = len(req.tokens)
            if n > tr.sent:
                send_frame(sock, {"kind": "token", "rid": rid,
                                  "toks": req.tokens[tr.sent:n]})
                tr.sent = n
            if req.done.is_set():
                if req.error is not None:
                    send_frame(sock, {"kind": "error", "rid": rid,
                                      "error": repr(req.error)})
                elif tr.migrate:
                    # prefill leg complete: export the detached KV
                    # pages as one bounded binary frame per page, then
                    # free them — custody passes to the router the
                    # moment migrate_out and every page frame are on
                    # the wire (a death mid-export leaves the router
                    # holding a partial blob it discards)
                    payloads = eng.export_pages(req)
                    leaves = eng.page_leaf_nbytes()
                    send_frame(sock, {
                        "kind": "migrate_out", "rid": rid,
                        "first_token": req.tokens[0],
                        "kv_len": len(req.prompt),
                        "pages": len(payloads),
                        "page_bytes": sum(len(p) for p in payloads)})
                    for seq, payload in enumerate(payloads):
                        send_binary_frame(
                            sock, {"kind": "page", "rid": rid,
                                   "seq": seq, "leaves": leaves},
                            payload)
                    eng.release_detached(req)
                else:
                    send_frame(sock, {
                        "kind": "done", "rid": rid,
                        "reason": req.finish_reason,
                        "tokens_total": len(req.tokens)})
                del live[rid]

    def try_adopt() -> None:
        """Admit parked migrate_in transfers while capacity allows —
        the engine returns None under slot/page pressure and the head
        transfer stays parked (admission order preserved)."""
        while adoptions:
            hdr, payloads = adoptions[0]
            rid = hdr["rid"]
            try:
                req = eng.adopt_request(
                    hdr["prompt"], hdr["first_token"],
                    hdr.get("max_new_tokens", 16), hdr.get("eos_id"),
                    payloads, adapter_id=hdr.get("adapter_id", 0))
            except Exception as e:
                adoptions.popleft()
                send_frame(sock, {"kind": "error", "rid": rid,
                                  "error": repr(e)})
                continue
            if req is None:
                return
            adoptions.popleft()
            # the prefill replica already streamed the first token and
            # the router stamped admission at the ORIGINAL prefill
            live[rid] = _Tracked(req, sent=1, admit_sent=True)

    try:
        while True:
            frames, closed = drain_socket(sock, reader)
            if closed:
                # the router is gone: nothing to stream to — exit
                # cleanly, a new router incarnation respawns us
                break
            for frame in frames:
                kind = frame.get("kind")
                if kind == "submit" and not shutting_down:
                    backlog.append(frame)
                elif kind == "migrate_in" and not shutting_down:
                    # header first; its binary page frames follow on
                    # the same socket (ordered — TCP)
                    inbound[frame["rid"]] = (frame, [])
                elif kind == "page":
                    entry = inbound.get(frame.get("rid"))
                    if entry is not None and isinstance(frame,
                                                        BinaryFrame):
                        entry[1].append(frame.payload)
                        if len(entry[1]) >= entry[0].get("pages", 0):
                            del inbound[frame.get("rid")]
                            adoptions.append(entry)
                elif kind == "shutdown":
                    shutting_down = True
            # hand backlog to the engine only while its bounded queue
            # has room — submit() must NEVER block this loop (the loop
            # is the only thing that steps the engine to make room)
            while backlog and eng.queue.qsize() < qcap:
                frame = backlog.popleft()
                rid = frame["rid"]
                migrate = bool(frame.get("migrate"))
                try:
                    # a migrating submit is the PREFILL LEG only: one
                    # token (TTFT), pages detached for export — the
                    # router gave the decode budget to whoever adopts
                    req = eng.submit(
                        frame["prompt"],
                        max_new_tokens=(1 if migrate else
                                        frame.get("max_new_tokens",
                                                  16)),
                        eos_id=frame.get("eos_id"),
                        detach_kv=migrate,
                        adapter_id=frame.get("adapter_id", 0))
                except Exception as e:
                    # per-request isolation: a bad prompt answers
                    # typed, the pool keeps serving
                    send_frame(sock, {"kind": "error", "rid": rid,
                                      "error": repr(e)})
                    continue
                live[rid] = _Tracked(req, migrate=migrate)
            try_adopt()
            busy = (eng.scheduler.active or eng._pending
                    or eng.queue.qsize() or backlog or adoptions)
            if busy:
                try:
                    eng.step()
                except BaseException:
                    # POISON: the engine already failed every in-flight
                    # request and dumped its flight record (the corpse);
                    # exit nonzero and let the router's failover path
                    # sort started from unstarted
                    return POISON_EXIT_CODE
            flush_outputs()
            if shutting_down and not live and not busy and not inbound:
                break
            now = time.monotonic()
            if now - last_beat >= BEAT_INTERVAL_S:
                last_beat = now
                hb.beat(eng._ticks,
                        extra=_beat_extra(eng, replica_id,
                                          len(backlog), role=role))
            if not busy:
                try:
                    select.select([sock], [], [], 0.02)
                except (OSError, ValueError):
                    break
    except (BrokenPipeError, ConnectionResetError, socket.timeout):
        # router vanished mid-send — same clean exit as EOF above
        return 0
    finally:
        try:
            eng.close()
        except Exception:
            pass
        try:
            sock.close()
        except OSError:
            pass
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.inference.replica",
        description="one serving-fleet replica (spawned by "
                    "inference/fleet.py)")
    parser.add_argument("--router", required=True,
                        help="host:port of the fleet router's "
                             "listening socket")
    parser.add_argument("--replica-id", type=int, required=True)
    parser.add_argument("--fleet-dir", required=True,
                        help="shared fleet directory (heartbeats + "
                             "per-replica telemetry)")
    parser.add_argument("--config", required=True,
                        help="ds_config.json with serving/telemetry/"
                             "fleet_model blocks")
    parser.add_argument("--role", default="mixed",
                        choices=("prefill", "decode", "mixed"),
                        help="phase specialization (disaggregated "
                             "fleet; the router decides who prefills "
                             "and who decodes)")
    args = parser.parse_args(argv)
    host, _, port = args.router.rpartition(":")
    with open(args.config) as f:
        cfg = json.load(f)
    return serve((host, int(port)), args.replica_id, args.fleet_dir,
                 cfg, role=args.role)


if __name__ == "__main__":
    sys.exit(main())
