"""Speculative decoding: token selection + draft-verify acceptance.

The device-side math of the draft-verify loop (docs/serving.md,
"speculative decoding"; Leviathan et al. 2023, Chen et al. 2023,
PAPERS.md).  A small DRAFT model proposes ``k`` tokens per serving
tick; the target model scores all ``k+1`` positions (the slot's
pending token + the proposals) in ONE widened ``verify_step`` program,
and this module decides — inside that same compiled program — how many
proposals survive and which tokens the tick actually emits.

Two acceptance arms, dispatched STATICALLY on the engine's
``serving.temperature`` (a python float — the arm never changes for
the life of a compiled program, so the zero-recompile contract of
docs/serving.md is untouched):

* ``temperature == 0`` — greedy: proposal ``i`` survives iff it equals
  the target's argmax at the previous position; the emitted tokens are
  exactly the target argmaxes over the accepted prefix plus one BONUS
  token (the target's own continuation after the last accepted
  proposal).  The emitted stream is therefore the non-speculative
  greedy stream, token for token — the parity bar of
  tests/test_spec_decode.py.
* ``temperature > 0`` — the rejection-sampling rule of Chen et al.
  2023: accept proposal ``x`` with probability ``min(1, p(x)/q(x))``
  (``p`` target, ``q`` draft), resample the first rejection from the
  residual ``max(p - q, 0)`` (renormalized), and sample the bonus from
  ``p`` when everything was accepted.  The emitted tokens are then
  EXACTLY distributed as ordinary ancestral sampling from the target —
  the distribution-recovery guarantee the unit tests check empirically.

Everything here is shape-static (``k`` is baked into the program) and
pure jnp — callable from inside the engine's jitted verify program and
directly from tests.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def select_next_token(logits: jnp.ndarray, temperature: float = 0.0,
                      rng=None) -> jnp.ndarray:
    """The one next-token rule every serving program shares (the four
    prefill/decode emission sites of inference/engine.py land here).

    ``temperature`` is a STATIC python float: 0 is greedy — bitwise the
    ``jnp.argmax`` the pre-speculation engine inlined (pinned by
    tests/test_spec_decode.py) — and > 0 samples
    ``softmax(logits / temperature)`` via ``jax.random.categorical``
    (which needs ``rng``).  Works on any ``[..., vocab]`` logits."""
    if temperature and temperature > 0.0:
        if rng is None:
            raise ValueError(
                "select_next_token with temperature > 0 needs an rng key")
        return jax.random.categorical(
            rng, logits.astype(jnp.float32) / temperature,
            axis=-1).astype(jnp.int32)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def greedy_accept(target_logits: jnp.ndarray,
                  draft_tokens: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy draft-verify acceptance.

    target_logits [S, W, V] — the verify program's logits; row ``i``
    scores the token AFTER the tick's ``i``-th input token (the pending
    token, then the ``k = W-1`` proposals).  draft_tokens [S, k].

    Returns ``(out_tokens [S, W] int32, accepted [S] int32)``:
    ``accepted[s] = m`` is the length of the longest proposal prefix
    matching the target argmaxes, and ``out_tokens[s, :m+1]`` are the
    tokens the tick emits — the accepted proposals ARE the argmaxes of
    rows ``0..m-1``, and row ``m`` is the bonus token, so the emitted
    block is uniformly ``argmax(target_logits)[:m+1]``.  Entries past
    ``m`` are the target's hypothetical continuation and must be
    ignored by the caller."""
    g = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # [S, W]
    k = draft_tokens.shape[1]
    ok = draft_tokens.astype(jnp.int32) == g[:, :k]           # [S, k]
    keep = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    return g, jnp.sum(keep, axis=1).astype(jnp.int32)


def rejection_sample_accept(target_logits: jnp.ndarray,
                            draft_tokens: jnp.ndarray,
                            draft_probs: jnp.ndarray,
                            temperature: float,
                            rng) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Speculative SAMPLING acceptance (Chen et al. 2023, PAPERS.md).

    target_logits [S, W, V]; draft_tokens [S, k]; draft_probs [S, k, V]
    — the full proposal distributions ``q_i`` the draft sampled from
    (the residual needs all of ``q``, not just ``q(x)``).

    Per position ``i``: accept ``x = draft_tokens[:, i]`` with
    probability ``min(1, p_i(x) / q_i(x))`` (realized as
    ``u * q_i(x) <= p_i(x)``, division-free); the first rejection
    resamples from ``normalize(max(p_i - q_i, 0))`` (falling back to
    ``p_i`` when the residual is identically zero, i.e. p == q); full
    acceptance samples the bonus from ``p_k``.  Output tokens are then
    exactly target-distributed — the Leviathan/Chen guarantee.

    Returns ``(out_tokens [S, W] int32, accepted [S] int32)`` with the
    same contract as :func:`greedy_accept`: the tick emits
    ``out_tokens[s, :accepted[s] + 1]``."""
    S, W, V = target_logits.shape
    k = W - 1
    t = float(temperature)
    p = jax.nn.softmax(target_logits.astype(jnp.float32) / t, axis=-1)
    q = draft_probs.astype(jnp.float32)                       # [S, k, V]
    d = draft_tokens.astype(jnp.int32)                        # [S, k]
    s_idx = jnp.arange(S)[:, None]
    i_idx = jnp.arange(k)[None, :]
    p_d = p[:, :k][s_idx, i_idx, d]                           # p_i(d_i)
    q_d = q[s_idx, i_idx, d]
    k_u, k_r = jax.random.split(rng)
    u = jax.random.uniform(k_u, (S, k), jnp.float32)
    ok = u * q_d <= p_d                                       # [S, k]
    keep = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    accepted = jnp.sum(keep, axis=1).astype(jnp.int32)        # [S]
    # the replacement token for every possible stop position at once:
    # positions 0..k-1 resample the residual, position k (full
    # acceptance) samples the bonus from p_k — one categorical per row
    resid = jnp.maximum(p[:, :k] - q, 0.0)
    rsum = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(rsum > 0.0, resid / jnp.where(rsum > 0.0, rsum, 1.0),
                      p[:, :k])
    repl_dist = jnp.concatenate([resid, p[:, k:]], axis=1)    # [S, W, V]
    # log of exact zeros -> -inf is the correct "never pick this" mask
    repl = jax.random.categorical(
        k_r, jnp.log(repl_dist), axis=-1).astype(jnp.int32)   # [S, W]
    out = jnp.concatenate([d, repl[:, k:k + 1]], axis=1)      # [S, W]
    out = out.at[jnp.arange(S), accepted].set(
        repl[jnp.arange(S), accepted])
    return out, accepted


def speculative_accept(target_logits: jnp.ndarray,
                       draft_tokens: jnp.ndarray,
                       draft_probs: Optional[jnp.ndarray],
                       temperature: float,
                       rng=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Static dispatch between the two acceptance arms — greedy at
    ``temperature == 0`` (``draft_probs``/``rng`` unused), rejection
    sampling otherwise.  ``temperature`` is a python float, so the
    branch is resolved at trace time: one arm per compiled program."""
    if temperature and temperature > 0.0:
        if draft_probs is None or rng is None:
            raise ValueError(
                "speculative_accept with temperature > 0 needs the "
                "draft's proposal distributions and an rng key")
        return rejection_sample_accept(target_logits, draft_tokens,
                                       draft_probs, temperature, rng)
    return greedy_accept(target_logits, draft_tokens)
