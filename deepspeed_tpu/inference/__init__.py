"""deepspeed_tpu.inference — KV-cached decode engine with static-shape
continuous batching (docs/serving.md).

The serving half of the framework: the reference v0.3.2 ships no
inference engine; this package opens the "heavy traffic" workload over
the existing models — slot-based KV cache (kv_cache.py), Orca-style
iteration-level scheduling in the static-shape idiom (scheduler.py),
and the ServeEngine (engine.py) whose ONE compiled decode program
serves arbitrary request mixes with zero recompiles.
"""
from .engine import ServeEngine  # noqa: F401
from .fleet import (FleetGiveUpError, FleetRequest,  # noqa: F401
                    FleetRouter, ReplicaFailure)
from .kv_cache import (KVCacheSpec, PagedKVCacheSpec,  # noqa: F401
                       cache_partition_specs, cache_shardings,
                       init_cache, init_paged_cache,
                       paged_cache_shardings, paged_partition_specs,
                       shard_cache)
from .quantize import (dequantize_rows, param_nbytes,  # noqa: F401
                       quantize_channels, quantize_gpt2_params,
                       quantize_rows, quantized_partition_specs)
from .scheduler import (PagePool, PrefixCache, Request,  # noqa: F401
                        SlotScheduler)
from .speculative import (greedy_accept,  # noqa: F401
                          rejection_sample_accept, select_next_token,
                          speculative_accept)
