"""ds_config.json → typed config.

Behavioral contract mirrors the reference parser
(reference: deepspeed/runtime/config.py:485-694): same key surface, the
batch-size triangle solver ``train_batch = micro_batch × grad_acc ×
world_size`` (config.py:586-636 there), duplicate-JSON-key rejection
(config_utils.py there), and the same sanity checks — re-expressed as
plain dataclass-style objects with no torch anywhere.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

from . import constants as C
from ..utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


def _dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate top-level keys instead of silently last-wins."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counts = {}
        for k, _ in ordered_pairs:
            counts[k] = counts.get(k, 0) + 1
        dupes = [k for k, n in counts.items() if n > 1]
        raise DeepSpeedConfigError(f"Duplicate keys in DeepSpeed config: {dupes}")
    return d


def get_scalar_param(d: Dict, key: str, default):
    return d.get(key, default) if d is not None else default


class DeepSpeedZeroConfig:
    """ZeRO block. Accepts both the dict form and the deprecated bool form
    (reference: deepspeed/runtime/zero/config.py:34-47)."""

    def __init__(self, param_dict: Dict[str, Any]):
        zero = param_dict.get(C.ZERO_OPTIMIZATION, None)
        if zero is None:
            zero = {}
        elif isinstance(zero, bool):  # deprecated style: "zero_optimization": true
            logger.warning(
                "zero_optimization boolean form is deprecated; use {'stage': n}"
            )
            zero = {C.ZERO_STAGE: 1 if zero else 0}
        if not isinstance(zero, dict):
            raise DeepSpeedConfigError(
                f"{C.ZERO_OPTIMIZATION} must be a dict or bool, got {type(zero)}"
            )
        self.stage = get_scalar_param(zero, C.ZERO_STAGE, C.ZERO_STAGE_DEFAULT)
        self.allgather_partitions = get_scalar_param(
            zero, C.ZERO_ALLGATHER_PARTITIONS, C.ZERO_ALLGATHER_PARTITIONS_DEFAULT)
        self.reduce_scatter = get_scalar_param(
            zero, C.ZERO_REDUCE_SCATTER, C.ZERO_REDUCE_SCATTER_DEFAULT)
        self.overlap_comm = get_scalar_param(
            zero, C.ZERO_OVERLAP_COMM, C.ZERO_OVERLAP_COMM_DEFAULT)
        self.contiguous_gradients = get_scalar_param(
            zero, C.ZERO_CONTIGUOUS_GRADIENTS, C.ZERO_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = get_scalar_param(
            zero, C.ZERO_REDUCE_BUCKET_SIZE, C.ZERO_REDUCE_BUCKET_SIZE_DEFAULT)
        self.allgather_bucket_size = get_scalar_param(
            zero, C.ZERO_ALLGATHER_BUCKET_SIZE, C.ZERO_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.max_elements_per_comm = get_scalar_param(
            zero, C.ZERO_MAX_ELEMENTS_PER_COMM, C.ZERO_MAX_ELEMENTS_PER_COMM_DEFAULT)
        self.cpu_offload = get_scalar_param(
            zero, C.ZERO_CPU_OFFLOAD, C.ZERO_CPU_OFFLOAD_DEFAULT)
        self.offload_impl = get_scalar_param(
            zero, C.ZERO_OFFLOAD_IMPL, C.ZERO_OFFLOAD_IMPL_DEFAULT)
        self.offload_grad_chunks = get_scalar_param(
            zero, C.ZERO_OFFLOAD_GRAD_CHUNKS,
            C.ZERO_OFFLOAD_GRAD_CHUNKS_DEFAULT)
        self.delayed_param_update = get_scalar_param(
            zero, C.ZERO_DELAYED_PARAM_UPDATE,
            C.ZERO_DELAYED_PARAM_UPDATE_DEFAULT)
        self.param_streaming = get_scalar_param(
            zero, C.ZERO_PARAM_STREAMING, C.ZERO_PARAM_STREAMING_DEFAULT)
        self.offload_split_update = get_scalar_param(
            zero, C.ZERO_OFFLOAD_SPLIT_UPDATE,
            C.ZERO_OFFLOAD_SPLIT_UPDATE_DEFAULT)
        self.offload_pipeline = get_scalar_param(
            zero, C.ZERO_OFFLOAD_PIPELINE,
            C.ZERO_OFFLOAD_PIPELINE_DEFAULT)
        # default-True knob: only an EXPLICIT offload_pipeline entry is
        # validated against cpu_offload (the default must not make every
        # non-offload config invalid); explicit false is always allowed
        self.offload_pipeline_explicit = C.ZERO_OFFLOAD_PIPELINE in zero
        if (not isinstance(self.offload_grad_chunks, int)
                or self.offload_grad_chunks < 1):
            raise DeepSpeedConfigError(
                f"{C.ZERO_OFFLOAD_GRAD_CHUNKS} must be an int >= 1, "
                f"got {self.offload_grad_chunks!r}")
        self.elastic_checkpoint = get_scalar_param(
            zero, C.ZERO_ELASTIC_CHECKPOINT, C.ZERO_ELASTIC_CHECKPOINT_DEFAULT)
        self.pg_correctness_test = get_scalar_param(
            zero, C.ZERO_PG_CORRECTNESS_TEST,
            C.ZERO_PG_CORRECTNESS_TEST_DEFAULT)
        if self.offload_impl not in ("auto", "xla", "host"):
            raise DeepSpeedConfigError(
                f"{C.ZERO_OFFLOAD_IMPL} must be 'auto', 'xla', or 'host', "
                f"got {self.offload_impl!r}")

        if not isinstance(self.stage, int) or not (
                C.ZERO_OPTIMIZATION_DISABLED <= self.stage <= C.MAX_STAGE_ZERO_OPTIMIZATION):
            raise DeepSpeedConfigError(
                f"ZeRO stage must be an int in [0, {C.MAX_STAGE_ZERO_OPTIMIZATION}], "
                f"got {self.stage!r}")

    def repr_dict(self):
        return {
            C.ZERO_STAGE: self.stage,
            C.ZERO_ALLGATHER_PARTITIONS: self.allgather_partitions,
            C.ZERO_REDUCE_SCATTER: self.reduce_scatter,
            C.ZERO_OVERLAP_COMM: self.overlap_comm,
            C.ZERO_CONTIGUOUS_GRADIENTS: self.contiguous_gradients,
            C.ZERO_REDUCE_BUCKET_SIZE: self.reduce_bucket_size,
            C.ZERO_ALLGATHER_BUCKET_SIZE: self.allgather_bucket_size,
            C.ZERO_CPU_OFFLOAD: self.cpu_offload,
            C.ZERO_ELASTIC_CHECKPOINT: self.elastic_checkpoint,
        }


class DeepSpeedActivationCheckpointingConfig:
    """Activation-checkpointing block → remat policy knobs
    (reference: deepspeed/runtime/activation_checkpointing/config.py)."""

    def __init__(self, param_dict: Dict[str, Any]):
        act = param_dict.get(C.ACTIVATION_CHECKPOINTING) or {}
        self.partition_activations = get_scalar_param(
            act, C.ACT_CKPT_PARTITION_ACTIVATIONS,
            C.ACT_CKPT_PARTITION_ACTIVATIONS_DEFAULT)
        self.contiguous_memory_optimization = get_scalar_param(
            act, C.ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION,
            C.ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)
        self.cpu_checkpointing = get_scalar_param(
            act, C.ACT_CKPT_CPU_CHECKPOINTING, C.ACT_CKPT_CPU_CHECKPOINTING_DEFAULT)
        self.number_checkpoints = get_scalar_param(
            act, C.ACT_CKPT_NUMBER_CHECKPOINTS, C.ACT_CKPT_NUMBER_CHECKPOINTS_DEFAULT)
        self.synchronize_checkpoint_boundary = get_scalar_param(
            act, C.ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY,
            C.ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)
        self.profile = get_scalar_param(
            act, C.ACT_CKPT_PROFILE, C.ACT_CKPT_PROFILE_DEFAULT)


class DeepSpeedFP16Config:
    def __init__(self, param_dict: Dict[str, Any]):
        fp16 = param_dict.get(C.FP16) or {}
        self.enabled = get_scalar_param(fp16, C.FP16_ENABLED, C.FP16_ENABLED_DEFAULT)
        self.loss_scale = get_scalar_param(
            fp16, C.FP16_LOSS_SCALE, C.FP16_LOSS_SCALE_DEFAULT)
        self.initial_scale_power = get_scalar_param(
            fp16, C.FP16_INITIAL_SCALE_POWER, C.FP16_INITIAL_SCALE_POWER_DEFAULT)
        self.loss_scale_window = get_scalar_param(
            fp16, C.FP16_LOSS_SCALE_WINDOW, C.FP16_LOSS_SCALE_WINDOW_DEFAULT)
        self.hysteresis = get_scalar_param(
            fp16, C.FP16_HYSTERESIS, C.FP16_HYSTERESIS_DEFAULT)
        self.min_loss_scale = get_scalar_param(
            fp16, C.FP16_MIN_LOSS_SCALE, C.FP16_MIN_LOSS_SCALE_DEFAULT)

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0

    @property
    def initial_dynamic_scale(self) -> float:
        return 2 ** self.initial_scale_power


class DeepSpeedBF16Config:
    """TPU-native precision block (extension; bf16 needs no loss scale)."""

    def __init__(self, param_dict: Dict[str, Any]):
        bf16 = param_dict.get(C.BF16) or {}
        self.enabled = get_scalar_param(bf16, C.BF16_ENABLED, C.BF16_ENABLED_DEFAULT)


class DeepSpeedSparseAttentionConfig:
    def __init__(self, param_dict: Dict[str, Any]):
        sa = param_dict.get(C.SPARSE_ATTENTION)
        self.enabled = sa is not None
        self.params: Optional[Dict[str, Any]] = dict(sa) if sa else None
        if sa is not None:
            mode = sa.get(C.SPARSE_MODE, C.SPARSE_MODE_DEFAULT)
            valid = {C.SPARSE_DENSE_MODE, C.SPARSE_FIXED_MODE, C.SPARSE_VARIABLE_MODE,
                     C.SPARSE_BIGBIRD_MODE, C.SPARSE_BSLONGFORMER_MODE}
            if mode not in valid:
                raise DeepSpeedConfigError(f"Invalid sparse attention mode {mode!r}")
            self.mode = mode
            # Per-mode layout knobs — routed with their schema defaults so
            # downstream kernels never re-spell fallback values.
            self.block = get_scalar_param(
                sa, C.SPARSE_BLOCK, C.SPARSE_BLOCK_DEFAULT)
            self.different_layout_per_head = get_scalar_param(
                sa, C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
                C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT)
            self.num_local_blocks = get_scalar_param(
                sa, C.SPARSE_NUM_LOCAL_BLOCKS, C.SPARSE_NUM_LOCAL_BLOCKS_DEFAULT)
            self.num_global_blocks = get_scalar_param(
                sa, C.SPARSE_NUM_GLOBAL_BLOCKS, C.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT)
            self.attention = get_scalar_param(
                sa, C.SPARSE_ATTENTION_TYPE, C.SPARSE_ATTENTION_TYPE_DEFAULT)
            self.horizontal_global_attention = get_scalar_param(
                sa, C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT)
            self.num_different_global_patterns = get_scalar_param(
                sa, C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
                C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT)
            self.num_random_blocks = get_scalar_param(
                sa, C.SPARSE_NUM_RANDOM_BLOCKS, C.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT)
            self.local_window_blocks = get_scalar_param(
                sa, C.SPARSE_LOCAL_WINDOW_BLOCKS,
                C.SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT)
            self.global_block_indices = get_scalar_param(
                sa, C.SPARSE_GLOBAL_BLOCK_INDICES,
                C.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT)
            self.global_block_end_indices = get_scalar_param(
                sa, C.SPARSE_GLOBAL_BLOCK_END_INDICES,
                C.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT)
            self.num_sliding_window_blocks = get_scalar_param(
                sa, C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT)
        else:
            self.mode = None


class DeepSpeedPLDConfig:
    def __init__(self, param_dict: Dict[str, Any]):
        pld = param_dict.get(C.PROGRESSIVE_LAYER_DROP) or {}
        self.enabled = get_scalar_param(pld, C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT)
        self.theta = get_scalar_param(pld, C.PLD_THETA, C.PLD_THETA_DEFAULT)
        self.gamma = get_scalar_param(pld, C.PLD_GAMMA, C.PLD_GAMMA_DEFAULT)


class DeepSpeedProfilerConfig:
    """xplane trace window: capture steps ``[start_step,
    start_step + num_steps)`` to ``output_path`` via jax.profiler."""

    def __init__(self, param_dict: Dict[str, Any]):
        prof = param_dict.get(C.PROFILER) or {}
        self.enabled = get_scalar_param(
            prof, C.PROFILER_ENABLED, C.PROFILER_ENABLED_DEFAULT)
        self.start_step = get_scalar_param(
            prof, C.PROFILER_START_STEP, C.PROFILER_START_STEP_DEFAULT)
        self.num_steps = get_scalar_param(
            prof, C.PROFILER_NUM_STEPS, C.PROFILER_NUM_STEPS_DEFAULT)
        self.output_path = get_scalar_param(
            prof, C.PROFILER_OUTPUT_PATH, C.PROFILER_OUTPUT_PATH_DEFAULT)
        if self.enabled and (self.start_step < 0 or self.num_steps < 1):
            raise DeepSpeedConfigError(
                f"profiler window invalid: start_step={self.start_step} "
                f"num_steps={self.num_steps}")


class DeepSpeedTensorboardConfig:
    def __init__(self, param_dict: Dict[str, Any]):
        tb = param_dict.get(C.TENSORBOARD) or {}
        self.enabled = get_scalar_param(
            tb, C.TENSORBOARD_ENABLED, C.TENSORBOARD_ENABLED_DEFAULT)
        self.output_path = get_scalar_param(
            tb, C.TENSORBOARD_OUTPUT_PATH, C.TENSORBOARD_OUTPUT_PATH_DEFAULT)
        self.job_name = get_scalar_param(
            tb, C.TENSORBOARD_JOB_NAME, C.TENSORBOARD_JOB_NAME_DEFAULT)


class DeepSpeedTelemetryConfig:
    """Unified telemetry block: metrics registry + span tracing +
    compile/memory instrumentation (docs/observability.md)."""

    def __init__(self, param_dict: Dict[str, Any]):
        tel = param_dict.get(C.TELEMETRY) or {}
        self.enabled = get_scalar_param(
            tel, C.TELEMETRY_ENABLED, C.TELEMETRY_ENABLED_DEFAULT)
        self.output_path = get_scalar_param(
            tel, C.TELEMETRY_OUTPUT_PATH, C.TELEMETRY_OUTPUT_PATH_DEFAULT)
        self.trace = get_scalar_param(
            tel, C.TELEMETRY_TRACE, C.TELEMETRY_TRACE_DEFAULT)
        self.compile_events = get_scalar_param(
            tel, C.TELEMETRY_COMPILE_EVENTS,
            C.TELEMETRY_COMPILE_EVENTS_DEFAULT)
        self.memory = get_scalar_param(
            tel, C.TELEMETRY_MEMORY, C.TELEMETRY_MEMORY_DEFAULT)
        self.recompile_storm_threshold = get_scalar_param(
            tel, C.TELEMETRY_STORM_THRESHOLD,
            C.TELEMETRY_STORM_THRESHOLD_DEFAULT)
        if (not isinstance(self.recompile_storm_threshold, int)
                or isinstance(self.recompile_storm_threshold, bool)
                or self.recompile_storm_threshold < 1):
            raise DeepSpeedConfigError(
                f"{C.TELEMETRY_STORM_THRESHOLD} must be an int >= 1, "
                f"got {self.recompile_storm_threshold!r}")
        self.heartbeat = get_scalar_param(
            tel, C.TELEMETRY_HEARTBEAT, C.TELEMETRY_HEARTBEAT_DEFAULT)
        self.heartbeat_dir = get_scalar_param(
            tel, C.TELEMETRY_HEARTBEAT_DIR,
            C.TELEMETRY_HEARTBEAT_DIR_DEFAULT)
        self.straggler_ratio = get_scalar_param(
            tel, C.TELEMETRY_STRAGGLER_RATIO,
            C.TELEMETRY_STRAGGLER_RATIO_DEFAULT)
        self.anomaly_ratio = get_scalar_param(
            tel, C.TELEMETRY_ANOMALY_RATIO,
            C.TELEMETRY_ANOMALY_RATIO_DEFAULT)
        if (not isinstance(self.anomaly_ratio, (int, float))
                or isinstance(self.anomaly_ratio, bool)
                or not (self.anomaly_ratio == 0
                        or self.anomaly_ratio > 1.0)):
            raise DeepSpeedConfigError(
                f"telemetry.{C.TELEMETRY_ANOMALY_RATIO} must be 0 "
                f"(disabled) or a number > 1.0 (it multiplies the "
                f"trailing median step time), got {self.anomaly_ratio!r}")
        if not isinstance(self.heartbeat, bool):
            # the async_save lesson: a JSON string like "false" is truthy
            raise DeepSpeedConfigError(
                f"telemetry.{C.TELEMETRY_HEARTBEAT} must be a bool, "
                f"got {self.heartbeat!r}")
        if not isinstance(self.heartbeat_dir, str):
            raise DeepSpeedConfigError(
                f"telemetry.{C.TELEMETRY_HEARTBEAT_DIR} must be a string "
                f"path, got {self.heartbeat_dir!r}")
        if (not isinstance(self.straggler_ratio, (int, float))
                or isinstance(self.straggler_ratio, bool)
                or not self.straggler_ratio > 1.0):
            raise DeepSpeedConfigError(
                f"telemetry.{C.TELEMETRY_STRAGGLER_RATIO} must be a "
                f"number > 1.0 (it multiplies the fleet median), got "
                f"{self.straggler_ratio!r}")


class DeepSpeedDataPrefetchConfig:
    """Asynchronous input pipeline block (docs/observability.md): a
    daemon worker prefetches + device-places batches through a bounded
    queue so the step loop never pays collate/H2D inline.  Default ON;
    ``DS_PREFETCH=0`` is the no-config escape hatch (resolved by the
    engine, not here — config objects stay env-independent)."""

    def __init__(self, param_dict: Dict[str, Any]):
        pf = param_dict.get(C.DATA_PREFETCH) or {}
        self.enabled = get_scalar_param(
            pf, C.DATA_PREFETCH_ENABLED, C.DATA_PREFETCH_ENABLED_DEFAULT)
        self.depth = get_scalar_param(
            pf, C.DATA_PREFETCH_DEPTH, C.DATA_PREFETCH_DEPTH_DEFAULT)
        if (not isinstance(self.depth, int)
                or isinstance(self.depth, bool) or self.depth < 1):
            raise DeepSpeedConfigError(
                f"{C.DATA_PREFETCH_DEPTH} must be an int >= 1, "
                f"got {self.depth!r}")


class DeepSpeedCheckpointConfig:
    """Fault-tolerant checkpointing block (docs/checkpointing.md): async
    background saves, ``keep_last_n`` retention, the corrupt-latest
    ``load_fallback`` chain, transient-I/O retry, and the opt-in SIGTERM
    preemption save.  All knobs validate eagerly — a typo'd retention
    policy must fail at config parse, not at the 40-hour mark when the
    first GC runs."""

    def __init__(self, param_dict: Dict[str, Any]):
        ck = param_dict.get(C.CHECKPOINT) or {}
        self.async_save = get_scalar_param(
            ck, C.CKPT_ASYNC_SAVE, C.CKPT_ASYNC_SAVE_DEFAULT)
        self.keep_last_n = get_scalar_param(
            ck, C.CKPT_KEEP_LAST_N, C.CKPT_KEEP_LAST_N_DEFAULT)
        self.load_fallback = get_scalar_param(
            ck, C.CKPT_LOAD_FALLBACK, C.CKPT_LOAD_FALLBACK_DEFAULT)
        self.io_retry_attempts = get_scalar_param(
            ck, C.CKPT_IO_RETRY_ATTEMPTS, C.CKPT_IO_RETRY_ATTEMPTS_DEFAULT)
        self.io_retry_base_s = get_scalar_param(
            ck, C.CKPT_IO_RETRY_BASE_S, C.CKPT_IO_RETRY_BASE_S_DEFAULT)
        self.sigterm_save = get_scalar_param(
            ck, C.CKPT_SIGTERM_SAVE, C.CKPT_SIGTERM_SAVE_DEFAULT)
        self.save_dir = get_scalar_param(
            ck, C.CKPT_SAVE_DIR, C.CKPT_SAVE_DIR_DEFAULT)
        for name, v in ((C.CKPT_KEEP_LAST_N, self.keep_last_n),
                        (C.CKPT_LOAD_FALLBACK, self.load_fallback)):
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise DeepSpeedConfigError(
                    f"checkpoint.{name} must be an int >= 0, got {v!r}")
        if (not isinstance(self.io_retry_attempts, int)
                or isinstance(self.io_retry_attempts, bool)
                or self.io_retry_attempts < 1):
            raise DeepSpeedConfigError(
                f"checkpoint.{C.CKPT_IO_RETRY_ATTEMPTS} must be an int "
                f">= 1 (1 = no retry), got {self.io_retry_attempts!r}")
        if (not isinstance(self.io_retry_base_s, (int, float))
                or isinstance(self.io_retry_base_s, bool)
                or self.io_retry_base_s < 0):
            raise DeepSpeedConfigError(
                f"checkpoint.{C.CKPT_IO_RETRY_BASE_S} must be a number "
                f">= 0, got {self.io_retry_base_s!r}")
        if not isinstance(self.save_dir, str):
            raise DeepSpeedConfigError(
                f"checkpoint.{C.CKPT_SAVE_DIR} must be a string path, "
                f"got {self.save_dir!r}")
        for name, v in ((C.CKPT_ASYNC_SAVE, self.async_save),
                        (C.CKPT_SIGTERM_SAVE, self.sigterm_save)):
            # a JSON string like "false" is truthy — silently flipping
            # every save async (or installing the SIGTERM hook) is the
            # opposite of what was configured
            if not isinstance(v, bool):
                raise DeepSpeedConfigError(
                    f"checkpoint.{name} must be a bool, got {v!r}")


class DeepSpeedStagesConfig:
    """Shared async-stage runtime block (docs/stages.md): the
    per-stage consecutive-failure budget before graceful degradation.
    Validates eagerly — a typo'd budget must fail at config parse, not
    at the first transient fault mid-run."""

    def __init__(self, param_dict: Dict[str, Any]):
        sg = param_dict.get(C.STAGES) or {}
        self.max_stage_failures = get_scalar_param(
            sg, C.STAGES_MAX_FAILURES, C.STAGES_MAX_FAILURES_DEFAULT)
        if (not isinstance(self.max_stage_failures, int)
                or isinstance(self.max_stage_failures, bool)
                or self.max_stage_failures < 1):
            raise DeepSpeedConfigError(
                f"stages.{C.STAGES_MAX_FAILURES} must be an int >= 1 "
                f"(consecutive transient failures before a stage "
                f"degrades), got {self.max_stage_failures!r}")


class DeepSpeedOffloadConfig:
    """Offload-tier block (runtime/disk_offload.py, docs/stages.md):
    selects which tier holds the fp32 master + Adam moments under the
    host offload impl — host RAM ("host", the default) or per-leaf
    CRC'd files on disk ("disk", the ZeRO-Infinity bottom tier).
    Validates eagerly: a typo'd tier or a missing disk_dir must fail at
    config parse, not as a mid-run surprise after the first step."""

    def __init__(self, param_dict: Dict[str, Any]):
        off = param_dict.get(C.OFFLOAD) or {}
        if not isinstance(off, dict):
            raise DeepSpeedConfigError(
                f"{C.OFFLOAD} must be a dict, got {type(off)}")
        self.tier = get_scalar_param(
            off, C.OFFLOAD_TIER, C.OFFLOAD_TIER_DEFAULT)
        self.disk_dir = get_scalar_param(
            off, C.OFFLOAD_DISK_DIR, C.OFFLOAD_DISK_DIR_DEFAULT)
        self.io_depth = get_scalar_param(
            off, C.OFFLOAD_IO_DEPTH, C.OFFLOAD_IO_DEPTH_DEFAULT)
        self.fsync = get_scalar_param(
            off, C.OFFLOAD_FSYNC, C.OFFLOAD_FSYNC_DEFAULT)
        if self.tier not in ("host", "disk"):
            raise DeepSpeedConfigError(
                f"{C.OFFLOAD}.{C.OFFLOAD_TIER} must be 'host' or 'disk', "
                f"got {self.tier!r}")
        if (not isinstance(self.io_depth, int)
                or isinstance(self.io_depth, bool) or self.io_depth < 1):
            raise DeepSpeedConfigError(
                f"{C.OFFLOAD}.{C.OFFLOAD_IO_DEPTH} must be an int >= 1 "
                f"(bounded disk read-ahead/write-back depth), got "
                f"{self.io_depth!r}")
        if not isinstance(self.fsync, bool):
            raise DeepSpeedConfigError(
                f"{C.OFFLOAD}.{C.OFFLOAD_FSYNC} must be a bool, got "
                f"{self.fsync!r}")
        if self.tier == "disk":
            if not isinstance(self.disk_dir, str) or not self.disk_dir:
                raise DeepSpeedConfigError(
                    f"{C.OFFLOAD}.{C.OFFLOAD_TIER}='disk' requires "
                    f"{C.OFFLOAD}.{C.OFFLOAD_DISK_DIR} (the directory "
                    "holding the per-leaf master/moment state files)")
        elif self.disk_dir is not None and not isinstance(
                self.disk_dir, str):
            raise DeepSpeedConfigError(
                f"{C.OFFLOAD}.{C.OFFLOAD_DISK_DIR} must be a string path, "
                f"got {self.disk_dir!r}")


class DeepSpeedServingConfig:
    """Serving block (docs/serving.md): the static slot pool the
    KV-cached decode engine compiles ONE program against.  Everything
    validates eagerly — a typo'd slot count must fail at config parse,
    not as a silent recompile storm under production traffic."""

    def __init__(self, param_dict: Dict[str, Any]):
        sv = param_dict.get(C.SERVING) or {}
        self.slots = get_scalar_param(
            sv, C.SERVING_SLOTS, C.SERVING_SLOTS_DEFAULT)
        self.max_seq_len = get_scalar_param(
            sv, C.SERVING_MAX_SEQ_LEN, C.SERVING_MAX_SEQ_LEN_DEFAULT)
        self.prefill_len = get_scalar_param(
            sv, C.SERVING_PREFILL_LEN, C.SERVING_PREFILL_LEN_DEFAULT)
        self.decode_impl = get_scalar_param(
            sv, C.SERVING_DECODE_IMPL, C.SERVING_DECODE_IMPL_DEFAULT)
        self.queue_capacity = get_scalar_param(
            sv, C.SERVING_QUEUE_CAPACITY, C.SERVING_QUEUE_CAPACITY_DEFAULT)
        self.flush_interval_ticks = get_scalar_param(
            sv, C.SERVING_FLUSH_INTERVAL, C.SERVING_FLUSH_INTERVAL_DEFAULT)
        self.eos_id = get_scalar_param(
            sv, C.SERVING_EOS_ID, C.SERVING_EOS_ID_DEFAULT)
        self.page_len = get_scalar_param(
            sv, C.SERVING_PAGE_LEN, C.SERVING_PAGE_LEN_DEFAULT)
        self.pages = get_scalar_param(
            sv, C.SERVING_PAGES, C.SERVING_PAGES_DEFAULT)
        self.prefix_cache = get_scalar_param(
            sv, C.SERVING_PREFIX_CACHE, C.SERVING_PREFIX_CACHE_DEFAULT)
        self.speculate_k = get_scalar_param(
            sv, C.SERVING_SPECULATE_K, C.SERVING_SPECULATE_K_DEFAULT)
        self.temperature = get_scalar_param(
            sv, C.SERVING_TEMPERATURE, C.SERVING_TEMPERATURE_DEFAULT)
        self.prefill_chunk_len = get_scalar_param(
            sv, C.SERVING_PREFILL_CHUNK_LEN,
            C.SERVING_PREFILL_CHUNK_LEN_DEFAULT)
        self.draft = self._validate_draft(sv.get(C.SERVING_DRAFT))
        self.quantization = self._validate_quantization(
            sv.get(C.SERVING_QUANTIZATION), self.page_len)
        self.lora = self._validate_lora(
            sv.get(C.SERVING_LORA), self.page_len)
        self.kv_tier = self._validate_kv_tier(
            sv.get(C.SERVING_KV_TIER), self.page_len)
        for name, v, lo in ((C.SERVING_SLOTS, self.slots, 1),
                            (C.SERVING_MAX_SEQ_LEN, self.max_seq_len, 0),
                            (C.SERVING_PREFILL_LEN, self.prefill_len, 0),
                            (C.SERVING_PAGE_LEN, self.page_len, 0),
                            (C.SERVING_PAGES, self.pages, 0),
                            (C.SERVING_PREFILL_CHUNK_LEN,
                             self.prefill_chunk_len, 0),
                            (C.SERVING_QUEUE_CAPACITY,
                             self.queue_capacity, 1),
                            (C.SERVING_FLUSH_INTERVAL,
                             self.flush_interval_ticks, 1)):
            if not isinstance(v, int) or isinstance(v, bool) or v < lo:
                raise DeepSpeedConfigError(
                    f"serving.{name} must be an int >= {lo}, got {v!r}")
        if (self.max_seq_len and self.prefill_len
                and self.prefill_len > self.max_seq_len):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_PREFILL_LEN}={self.prefill_len} "
                f"exceeds serving.{C.SERVING_MAX_SEQ_LEN}="
                f"{self.max_seq_len}: a prompt bucket longer than the KV "
                "capacity can never be admitted")
        if self.decode_impl not in ("auto", "pallas", "dense"):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_DECODE_IMPL} must be 'auto', "
                f"'pallas', or 'dense', got {self.decode_impl!r}")
        if not isinstance(self.eos_id, int) or isinstance(self.eos_id, bool):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_EOS_ID} must be an int token id "
                f"(-1 = none), got {self.eos_id!r}")
        # JSON "true"/"false" strings are truthy — a string here would
        # silently flip the prefix plane, the PR 5 async_save bug class
        if not isinstance(self.prefix_cache, bool):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_PREFIX_CACHE} must be a bool, got "
                f"{self.prefix_cache!r}")
        if self.pages and not self.page_len:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_PAGES}={self.pages} needs "
                f"serving.{C.SERVING_PAGE_LEN} > 0 (pages size a paged "
                "pool; page_len=0 is the pre-page slot cache)")
        if self.page_len and self.pages and self.pages < 2:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_PAGES}={self.pages} is too small: "
                "page 0 is the reserved scratch page, so a usable pool "
                "needs at least 2 pages (0 = auto-size)")
        if self.prefill_chunk_len and not self.page_len:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_PREFILL_CHUNK_LEN}="
                f"{self.prefill_chunk_len} needs serving."
                f"{C.SERVING_PAGE_LEN} > 0: chunked prefill rides the "
                "delta-aware paged prefill program (the slot layout "
                "prefills whole prompts)")
        if not isinstance(self.speculate_k, int) \
                or isinstance(self.speculate_k, bool) \
                or self.speculate_k < 0:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_SPECULATE_K} must be an int >= 0 "
                f"(0 = speculation off), got {self.speculate_k!r}")
        if isinstance(self.temperature, bool) \
                or not isinstance(self.temperature, (int, float)) \
                or self.temperature < 0:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_TEMPERATURE} must be a number >= 0 "
                f"(0 = greedy), got {self.temperature!r}")
        self.temperature = float(self.temperature)

    @staticmethod
    def _validate_draft(draft) -> Dict[str, Any]:
        """Eager validation of the ``serving.draft`` block: a typo'd
        draft dimension must fail at config parse, not as a shape error
        inside the first verify pass.  Returns the block with defaults
        filled (vocab_size/n_positions are the ENGINE's to force from
        the target model — they are rejected here)."""
        if draft is None:
            draft = {}
        if not isinstance(draft, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_DRAFT} must be a dict of draft-"
                f"model dimensions, got {draft!r}")
        allowed = {C.SERVING_DRAFT_D_MODEL, C.SERVING_DRAFT_N_LAYER,
                   C.SERVING_DRAFT_N_HEAD, C.SERVING_DRAFT_ATTN_IMPL}
        unknown = set(draft) - allowed
        if unknown:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_DRAFT} has unknown key(s) "
                f"{sorted(unknown)}; allowed: {sorted(allowed)} "
                "(vocab_size/n_positions always follow the target "
                "model)")
        out = {
            C.SERVING_DRAFT_D_MODEL: get_scalar_param(
                draft, C.SERVING_DRAFT_D_MODEL,
                C.SERVING_DRAFT_D_MODEL_DEFAULT),
            C.SERVING_DRAFT_N_LAYER: get_scalar_param(
                draft, C.SERVING_DRAFT_N_LAYER,
                C.SERVING_DRAFT_N_LAYER_DEFAULT),
            C.SERVING_DRAFT_N_HEAD: get_scalar_param(
                draft, C.SERVING_DRAFT_N_HEAD,
                C.SERVING_DRAFT_N_HEAD_DEFAULT),
            C.SERVING_DRAFT_ATTN_IMPL: get_scalar_param(
                draft, C.SERVING_DRAFT_ATTN_IMPL,
                C.SERVING_DRAFT_ATTN_IMPL_DEFAULT),
        }
        for key in (C.SERVING_DRAFT_D_MODEL, C.SERVING_DRAFT_N_LAYER,
                    C.SERVING_DRAFT_N_HEAD):
            v = out[key]
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise DeepSpeedConfigError(
                    f"serving.{C.SERVING_DRAFT}.{key} must be an int "
                    f">= 1, got {v!r}")
        if out[C.SERVING_DRAFT_D_MODEL] % out[C.SERVING_DRAFT_N_HEAD]:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_DRAFT}: d_model="
                f"{out[C.SERVING_DRAFT_D_MODEL]} must be divisible by "
                f"n_head={out[C.SERVING_DRAFT_N_HEAD]}")
        if out[C.SERVING_DRAFT_ATTN_IMPL] not in ("", "flash", "dense"):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_DRAFT}.{C.SERVING_DRAFT_ATTN_IMPL} "
                "must be '' (follow the target), 'flash', or 'dense', "
                f"got {out[C.SERVING_DRAFT_ATTN_IMPL]!r}")
        return out

    @staticmethod
    def _validate_quantization(quant, page_len: int) -> Dict[str, str]:
        """Eager validation of ``serving.quantization`` (docs/serving.md
        "quantized serving"): a typo'd arm must fail at config parse,
        not as a silent fp fallback under production traffic.  Returns
        the block with defaults filled ('fp16' = the master dtype as
        loaded — no cast, bitwise-unchanged programs)."""
        if quant is None:
            quant = {}
        if not isinstance(quant, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_QUANTIZATION} must be a dict "
                f"(weights/kv arms), got {quant!r}")
        allowed = {C.SERVING_QUANT_WEIGHTS, C.SERVING_QUANT_KV}
        unknown = set(quant) - allowed
        if unknown:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_QUANTIZATION} has unknown key(s) "
                f"{sorted(unknown)}; allowed: {sorted(allowed)}")
        out = {
            C.SERVING_QUANT_WEIGHTS: get_scalar_param(
                quant, C.SERVING_QUANT_WEIGHTS,
                C.SERVING_QUANT_WEIGHTS_DEFAULT),
            C.SERVING_QUANT_KV: get_scalar_param(
                quant, C.SERVING_QUANT_KV, C.SERVING_QUANT_KV_DEFAULT),
        }
        for key in allowed:
            if out[key] not in ("fp16", "int8"):
                raise DeepSpeedConfigError(
                    f"serving.{C.SERVING_QUANTIZATION}.{key} must be "
                    f"'fp16' (the master dtype — no quantization) or "
                    f"'int8', got {out[key]!r}")
        if out[C.SERVING_QUANT_KV] == "int8" and not page_len:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_QUANT_KV}='int8' requires serving."
                f"{C.SERVING_PAGE_LEN} > 0: quantized KV is a property "
                "of the paged pool (the slot layout keeps the master "
                "dtype)")
        if out[C.SERVING_QUANT_KV] == "int8" and page_len > 128:
            # the fused-dequant kernels ride one scale lane per stored
            # row (ops/pallas/decode_attention.py _scale_tile) — catch
            # the limit here, not as a trace error on the first decode
            # tick under live traffic
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_QUANT_KV}='int8' supports "
                f"serving.{C.SERVING_PAGE_LEN} <= 128 (one scale lane "
                f"per page row in the fused-dequant kernels), got "
                f"{page_len}")
        return out

    @staticmethod
    def _validate_lora(lora, page_len: int) -> Dict[str, Any]:
        """Eager validation of ``serving.lora`` (docs/serving.md
        "multi-tenant serving"): a typo'd rank or target must fail at
        config parse, not as a shape error inside the first decode tick
        under live multi-tenant traffic.  Returns the block with
        defaults filled (rank=0 = lora OFF — no pool, no extra
        operands, bitwise-unchanged programs)."""
        if lora is None:
            lora = {}
        if not isinstance(lora, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_LORA} must be a dict "
                f"(rank/alpha/max_adapters/hbm_adapter_slots/targets), "
                f"got {lora!r}")
        allowed = {C.SERVING_LORA_RANK, C.SERVING_LORA_ALPHA,
                   C.SERVING_LORA_MAX_ADAPTERS, C.SERVING_LORA_HBM_SLOTS,
                   C.SERVING_LORA_TARGETS}
        unknown = set(lora) - allowed
        if unknown:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_LORA} has unknown key(s) "
                f"{sorted(unknown)}; allowed: {sorted(allowed)}")
        out = {
            C.SERVING_LORA_RANK: get_scalar_param(
                lora, C.SERVING_LORA_RANK, C.SERVING_LORA_RANK_DEFAULT),
            C.SERVING_LORA_ALPHA: get_scalar_param(
                lora, C.SERVING_LORA_ALPHA,
                C.SERVING_LORA_ALPHA_DEFAULT),
            C.SERVING_LORA_MAX_ADAPTERS: get_scalar_param(
                lora, C.SERVING_LORA_MAX_ADAPTERS,
                C.SERVING_LORA_MAX_ADAPTERS_DEFAULT),
            C.SERVING_LORA_HBM_SLOTS: get_scalar_param(
                lora, C.SERVING_LORA_HBM_SLOTS,
                C.SERVING_LORA_HBM_SLOTS_DEFAULT),
            C.SERVING_LORA_TARGETS: tuple(lora.get(
                C.SERVING_LORA_TARGETS, C.SERVING_LORA_TARGETS_DEFAULT)),
        }
        rank = out[C.SERVING_LORA_RANK]
        if not isinstance(rank, int) or isinstance(rank, bool) \
                or rank < 0:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_LORA}.{C.SERVING_LORA_RANK} must "
                f"be an int >= 0 (0 = lora off), got {rank!r}")
        alpha = out[C.SERVING_LORA_ALPHA]
        if isinstance(alpha, bool) \
                or not isinstance(alpha, (int, float)) or alpha <= 0:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_LORA}.{C.SERVING_LORA_ALPHA} must "
                f"be a number > 0 (the alpha/rank delta scale), got "
                f"{alpha!r}")
        out[C.SERVING_LORA_ALPHA] = float(alpha)
        for key in (C.SERVING_LORA_MAX_ADAPTERS,
                    C.SERVING_LORA_HBM_SLOTS):
            v = out[key]
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise DeepSpeedConfigError(
                    f"serving.{C.SERVING_LORA}.{key} must be an int "
                    f">= 1, got {v!r}")
        target_names = ("qkv_w", "out_w", "fc_w", "proj_w")
        targets = out[C.SERVING_LORA_TARGETS]
        if not targets or any(t not in target_names for t in targets) \
                or len(set(targets)) != len(targets):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_LORA}.{C.SERVING_LORA_TARGETS} "
                f"must be a non-empty list of distinct block-param "
                f"names from {list(target_names)}, got "
                f"{list(targets)!r}")
        if rank and not page_len:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_LORA}.{C.SERVING_LORA_RANK}="
                f"{rank} requires serving.{C.SERVING_PAGE_LEN} > 0: "
                "the adapter pool rides the paged serving plane (its "
                "residency slots are managed exactly like KV pages)")
        return out

    @staticmethod
    def _validate_kv_tier(kv, page_len: int) -> Dict[str, Any]:
        """Eager validation of ``serving.kv_tier`` (docs/serving.md
        "KV tiering"): a typo'd budget or park threshold must fail at
        config parse, not as a silently-never-parking tier under live
        traffic.  Returns the block with defaults filled
        (idle_park_ticks=0 = tiering OFF — no tier object, no extra
        host copies, engine behavior bitwise unchanged)."""
        if kv is None:
            kv = {}
        if not isinstance(kv, dict):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_KV_TIER} must be a dict "
                f"(idle_park_ticks/host_budget_pages/disk_dir/fsync), "
                f"got {kv!r}")
        allowed = {C.SERVING_KV_TIER_IDLE_PARK_TICKS,
                   C.SERVING_KV_TIER_HOST_BUDGET_PAGES,
                   C.SERVING_KV_TIER_DISK_DIR,
                   C.SERVING_KV_TIER_FSYNC}
        unknown = set(kv) - allowed
        if unknown:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_KV_TIER} has unknown key(s) "
                f"{sorted(unknown)}; allowed: {sorted(allowed)}")
        out = {
            C.SERVING_KV_TIER_IDLE_PARK_TICKS: get_scalar_param(
                kv, C.SERVING_KV_TIER_IDLE_PARK_TICKS,
                C.SERVING_KV_TIER_IDLE_PARK_TICKS_DEFAULT),
            C.SERVING_KV_TIER_HOST_BUDGET_PAGES: get_scalar_param(
                kv, C.SERVING_KV_TIER_HOST_BUDGET_PAGES,
                C.SERVING_KV_TIER_HOST_BUDGET_PAGES_DEFAULT),
            C.SERVING_KV_TIER_DISK_DIR: get_scalar_param(
                kv, C.SERVING_KV_TIER_DISK_DIR,
                C.SERVING_KV_TIER_DISK_DIR_DEFAULT),
            C.SERVING_KV_TIER_FSYNC: get_scalar_param(
                kv, C.SERVING_KV_TIER_FSYNC,
                C.SERVING_KV_TIER_FSYNC_DEFAULT),
        }
        for key in (C.SERVING_KV_TIER_IDLE_PARK_TICKS,
                    C.SERVING_KV_TIER_HOST_BUDGET_PAGES):
            v = out[key]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise DeepSpeedConfigError(
                    f"serving.{C.SERVING_KV_TIER}.{key} must be an "
                    f"int >= 0, got {v!r}")
        if not isinstance(out[C.SERVING_KV_TIER_DISK_DIR], str):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_KV_TIER}."
                f"{C.SERVING_KV_TIER_DISK_DIR} must be a string "
                f"directory path ('' = no disk tier), got "
                f"{out[C.SERVING_KV_TIER_DISK_DIR]!r}")
        if not isinstance(out[C.SERVING_KV_TIER_FSYNC], bool):
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_KV_TIER}.{C.SERVING_KV_TIER_FSYNC} "
                f"must be a bool, got {out[C.SERVING_KV_TIER_FSYNC]!r}")
        if out[C.SERVING_KV_TIER_IDLE_PARK_TICKS] and not page_len:
            raise DeepSpeedConfigError(
                f"serving.{C.SERVING_KV_TIER}."
                f"{C.SERVING_KV_TIER_IDLE_PARK_TICKS}="
                f"{out[C.SERVING_KV_TIER_IDLE_PARK_TICKS]} requires "
                f"serving.{C.SERVING_PAGE_LEN} > 0: the KV tier parks "
                "prefix-cache pages, which exist only on the paged "
                "serving plane")
        return out


class DeepSpeedFleetConfig:
    """Serving-fleet block (docs/serving.md "serving fleet"): the
    router/autoscaler knobs over N ServeEngine replicas.  Validates
    eagerly — a typo'd SLO or an inverted min/max clamp must fail at
    config parse, not as a silent never-scaling fleet under live
    traffic."""

    def __init__(self, param_dict: Dict[str, Any]):
        fl = param_dict.get(C.FLEET) or {}
        self.replicas = get_scalar_param(
            fl, C.FLEET_REPLICAS, C.FLEET_REPLICAS_DEFAULT)
        self.min_replicas = get_scalar_param(
            fl, C.FLEET_MIN_REPLICAS, C.FLEET_MIN_REPLICAS_DEFAULT)
        self.max_replicas = get_scalar_param(
            fl, C.FLEET_MAX_REPLICAS, C.FLEET_MAX_REPLICAS_DEFAULT)
        self.slo_p99_s = get_scalar_param(
            fl, C.FLEET_SLO_P99_S, C.FLEET_SLO_P99_S_DEFAULT)
        self.scale_up_window_s = get_scalar_param(
            fl, C.FLEET_SCALE_UP_WINDOW_S,
            C.FLEET_SCALE_UP_WINDOW_S_DEFAULT)
        self.scale_down_window_s = get_scalar_param(
            fl, C.FLEET_SCALE_DOWN_WINDOW_S,
            C.FLEET_SCALE_DOWN_WINDOW_S_DEFAULT)
        self.heartbeat_timeout_s = get_scalar_param(
            fl, C.FLEET_HEARTBEAT_TIMEOUT_S,
            C.FLEET_HEARTBEAT_TIMEOUT_S_DEFAULT)
        self.max_restarts = get_scalar_param(
            fl, C.FLEET_MAX_RESTARTS, C.FLEET_MAX_RESTARTS_DEFAULT)
        self.backoff_base_s = get_scalar_param(
            fl, C.FLEET_BACKOFF_BASE_S, C.FLEET_BACKOFF_BASE_S_DEFAULT)
        self.backoff_max_s = get_scalar_param(
            fl, C.FLEET_BACKOFF_MAX_S, C.FLEET_BACKOFF_MAX_S_DEFAULT)
        self.spawn_timeout_s = get_scalar_param(
            fl, C.FLEET_SPAWN_TIMEOUT_S, C.FLEET_SPAWN_TIMEOUT_S_DEFAULT)
        self.term_grace_s = get_scalar_param(
            fl, C.FLEET_TERM_GRACE_S, C.FLEET_TERM_GRACE_S_DEFAULT)
        self.slo_ttft_s = get_scalar_param(
            fl, C.FLEET_SLO_TTFT_S, C.FLEET_SLO_TTFT_S_DEFAULT)
        self.slo_tpot_s = get_scalar_param(
            fl, C.FLEET_SLO_TPOT_S, C.FLEET_SLO_TPOT_S_DEFAULT)
        self.roles = self._validate_roles(
            fl.get(C.FLEET_ROLES, C.FLEET_ROLES_DEFAULT))
        if self.roles is not None:
            # roles size the fleet; an explicit replicas count that
            # disagrees is a config contradiction, not a tiebreak
            if C.FLEET_REPLICAS in fl \
                    and fl[C.FLEET_REPLICAS] != sum(self.roles.values()):
                raise DeepSpeedConfigError(
                    f"fleet.{C.FLEET_REPLICAS}={fl[C.FLEET_REPLICAS]} "
                    f"contradicts fleet.{C.FLEET_ROLES}={self.roles} "
                    f"(role counts sum to {sum(self.roles.values())}); "
                    "drop one of them")
            self.replicas = sum(self.roles.values())
        for name, v in ((C.FLEET_REPLICAS, self.replicas),
                        (C.FLEET_MIN_REPLICAS, self.min_replicas),
                        (C.FLEET_MAX_REPLICAS, self.max_replicas)):
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise DeepSpeedConfigError(
                    f"fleet.{name} must be an int >= 1, got {v!r}")
        if not (self.min_replicas <= self.replicas <= self.max_replicas):
            raise DeepSpeedConfigError(
                f"fleet replica clamps must nest: min_replicas="
                f"{self.min_replicas} <= replicas={self.replicas} <= "
                f"max_replicas={self.max_replicas}")
        for name, v, lo in (
                (C.FLEET_SLO_P99_S, self.slo_p99_s, 0.0),
                (C.FLEET_SCALE_UP_WINDOW_S, self.scale_up_window_s, 0.0),
                (C.FLEET_SCALE_DOWN_WINDOW_S,
                 self.scale_down_window_s, 0.0),
                (C.FLEET_BACKOFF_BASE_S, self.backoff_base_s, 0.0),
                (C.FLEET_BACKOFF_MAX_S, self.backoff_max_s, 0.0),
                (C.FLEET_SPAWN_TIMEOUT_S, self.spawn_timeout_s, 0.0),
                (C.FLEET_TERM_GRACE_S, self.term_grace_s, 0.0)):
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or v <= lo:
                raise DeepSpeedConfigError(
                    f"fleet.{name} must be a number > {lo}, got {v!r}")
        if isinstance(self.heartbeat_timeout_s, bool) or \
                not isinstance(self.heartbeat_timeout_s, (int, float)) \
                or self.heartbeat_timeout_s < 0:
            raise DeepSpeedConfigError(
                f"fleet.{C.FLEET_HEARTBEAT_TIMEOUT_S} must be a number "
                f">= 0 (0 = off), got {self.heartbeat_timeout_s!r}")
        if not isinstance(self.max_restarts, int) \
                or isinstance(self.max_restarts, bool) \
                or self.max_restarts < 0:
            raise DeepSpeedConfigError(
                f"fleet.{C.FLEET_MAX_RESTARTS} must be an int >= 0 "
                f"(consecutive no-progress replica failures before the "
                f"typed give-up), got {self.max_restarts!r}")
        for name, v in ((C.FLEET_SLO_TTFT_S, self.slo_ttft_s),
                        (C.FLEET_SLO_TPOT_S, self.slo_tpot_s)):
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or v < 0:
                raise DeepSpeedConfigError(
                    f"fleet.{name} must be a number >= 0 (0 = fall "
                    f"back to the queue-wait SLO), got {v!r}")

    @staticmethod
    def _validate_roles(roles):
        """Eager validation of ``fleet.roles`` (docs/serving.md
        "disaggregated fleet"): role name -> initial replica count.
        None = the homogeneous fleet (every replica "mixed").  A typo'd
        role must fail at config parse, not as a router that never
        finds a decode replica to migrate to."""
        if roles is None:
            return None
        if not isinstance(roles, dict) or not roles:
            raise DeepSpeedConfigError(
                f"fleet.{C.FLEET_ROLES} must be a non-empty dict of "
                f"role -> replica count (or omitted for a homogeneous "
                f"fleet), got {roles!r}")
        allowed = {"prefill", "decode", "mixed"}
        unknown = set(roles) - allowed
        if unknown:
            raise DeepSpeedConfigError(
                f"fleet.{C.FLEET_ROLES} has unknown role(s) "
                f"{sorted(unknown)}; allowed: {sorted(allowed)}")
        for role, count in roles.items():
            if not isinstance(count, int) or isinstance(count, bool) \
                    or count < 1:
                raise DeepSpeedConfigError(
                    f"fleet.{C.FLEET_ROLES}.{role} must be an int >= 1 "
                    f"replica, got {count!r}")
        # a prefill-only replica can never decode, so its migrations
        # need somewhere to land (mixed replicas can adopt too)
        if "prefill" in roles and not ({"decode", "mixed"} & set(roles)):
            raise DeepSpeedConfigError(
                f"fleet.{C.FLEET_ROLES}={dict(roles)} has prefill "
                "replicas but nowhere to migrate finished prefills: "
                "add a 'decode' (or 'mixed') role")
        return dict(roles)


class DeepSpeedPipelineConfig:
    def __init__(self, param_dict: Dict[str, Any]):
        pipe = param_dict.get(C.PIPELINE) or {}
        self.stages = get_scalar_param(
            pipe, C.PIPELINE_STAGES, C.PIPELINE_STAGES_DEFAULT)
        self.partition = get_scalar_param(
            pipe, C.PIPELINE_PARTITION, C.PIPELINE_PARTITION_DEFAULT)
        self.seed_layers = get_scalar_param(
            pipe, C.PIPELINE_SEED_LAYERS, C.PIPELINE_SEED_LAYERS_DEFAULT)
        self.activation_checkpoint_interval = get_scalar_param(
            pipe, C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL,
            C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT)
        self.schedule = get_scalar_param(
            pipe, C.PIPELINE_SCHEDULE, C.PIPELINE_SCHEDULE_DEFAULT)


class DeepSpeedConfigWriter:
    """Build/modify ds_config json files from templates
    (reference: runtime/config.py:468-482 — used by launch scripts to
    tweak parameters from the command line)."""

    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self.data = data if data is not None else {}

    def add_config(self, key: str, value: Any) -> None:
        self.data[key] = value

    def load_config(self, filename: str) -> None:
        with open(filename) as f:
            self.data = json.load(
                f, object_pairs_hook=_dict_raise_error_on_duplicate_keys)

    def write_config(self, filename: str) -> None:
        with open(filename, "w") as outfile:
            json.dump(self.data, outfile)


class DeepSpeedConfig:
    """Parse a ds_config path or dict; solve + validate the batch triangle.

    ``world_size`` is the number of data-parallel replicas (mesh ``data``-axis
    size on TPU — the analogue of the reference's DP world size).
    """

    def __init__(self, config: Any, world_size: int = 1):
        if isinstance(config, (str,)):
            with open(config, "r") as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=_dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = config
        else:
            raise DeepSpeedConfigError(
                f"Expected a config path or dict, got {type(config)}")

        self.world_size = world_size
        pd = self._param_dict

        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE, C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = pd.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = pd.get(
            C.GRADIENT_ACCUMULATION_STEPS, C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = pd.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.wall_clock_breakdown = pd.get(
            C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)

        self.gradient_clipping = pd.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = pd.get(
            C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = pd.get(
            C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = pd.get(
            C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.allreduce_always_fp32 = pd.get(
            C.ALLREDUCE_ALWAYS_FP32, C.ALLREDUCE_ALWAYS_FP32_DEFAULT)
        self.disable_allgather = pd.get(
            C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)

        opt = pd.get(C.OPTIMIZER)
        self.optimizer_name = opt.get(C.TYPE) if opt else None
        if self.optimizer_name is not None:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = (opt.get(C.OPTIMIZER_PARAMS) if opt else None) or {}
        self.optimizer_legacy_fusion = (
            opt.get(C.LEGACY_FUSION, C.LEGACY_FUSION_DEFAULT) if opt else False)

        sched = pd.get(C.SCHEDULER)
        self.scheduler_name = sched.get(C.TYPE) if sched else None
        self.scheduler_params = (sched.get(C.SCHEDULER_PARAMS) if sched else None) or {}

        self.fp16 = DeepSpeedFP16Config(pd)
        self.bf16 = DeepSpeedBF16Config(pd)
        # Apex AMP block (reference constants.py:162-172): no apex on TPU —
        # enabled => native bf16 mixed precision, the closest equivalent
        amp = pd.get(C.AMP) or {}
        self.amp_enabled = bool(amp.get(C.AMP_ENABLED, C.AMP_ENABLED_DEFAULT))
        self.amp_params = {k: v for k, v in amp.items()
                           if k != C.AMP_ENABLED}
        if self.amp_enabled:
            if self.fp16.enabled:
                raise DeepSpeedConfigError(
                    "amp and fp16 are mutually exclusive (reference "
                    "config sanity: engine chooses ONE precision scheme)")
            if not self.bf16.enabled:
                logger.warning(
                    "amp has no apex on TPU; mapping to native bf16 "
                    "mixed precision (amp_params recorded, not applied)")
                self.bf16.enabled = True
        self.zero_allow_untested_optimizer = pd.get(
            C.ZERO_ALLOW_UNTESTED_OPTIMIZER,
            C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)
        self.zero_config = DeepSpeedZeroConfig(pd)
        self.activation_checkpointing_config = (
            DeepSpeedActivationCheckpointingConfig(pd))
        self.sparse_attention_config = DeepSpeedSparseAttentionConfig(pd)
        self.pld_config = DeepSpeedPLDConfig(pd)
        self.tensorboard_config = DeepSpeedTensorboardConfig(pd)
        self.profiler_config = DeepSpeedProfilerConfig(pd)
        self.telemetry_config = DeepSpeedTelemetryConfig(pd)
        self.data_prefetch_config = DeepSpeedDataPrefetchConfig(pd)
        self.checkpoint_config = DeepSpeedCheckpointConfig(pd)
        self.stages_config = DeepSpeedStagesConfig(pd)
        self.offload_config = DeepSpeedOffloadConfig(pd)
        self.serving_config = DeepSpeedServingConfig(pd)
        self.fleet_config = DeepSpeedFleetConfig(pd)
        self.pipeline_config = DeepSpeedPipelineConfig(pd)

        self._solve_batch_triangle()
        self._do_sanity_check()

    # ---- compat properties matching reference attribute names ----
    @property
    def fp16_enabled(self):
        return self.fp16.enabled

    @property
    def bf16_enabled(self):
        return self.bf16.enabled

    @property
    def loss_scale(self):
        return self.fp16.loss_scale

    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    def _solve_batch_triangle(self):
        """Solve train_batch = micro_batch * grad_acc * world_size given any
        subset (reference: runtime/config.py:586-636)."""
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        accum = self.gradient_accumulation_steps
        ws = self.world_size

        if train is not None and micro is not None and accum is not None:
            pass  # fully specified; checked below
        elif train is not None and micro is not None:
            accum = train // (micro * ws)
        elif train is not None and accum is not None:
            micro = train // (ws * accum)
        elif micro is not None and accum is not None:
            train = micro * accum * ws
        elif train is not None:
            accum = 1
            micro = train // ws
        elif micro is not None:
            train = micro * ws
            accum = 1
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size or "
                "train_micro_batch_size_per_gpu must be set")

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = accum

        if train != micro * accum * ws:
            raise DeepSpeedConfigError(
                f"Batch triangle check failed: train_batch_size={train} != "
                f"micro_batch={micro} * grad_acc={accum} * world_size={ws}")
        for name, v in [("train_batch_size", train),
                        ("train_micro_batch_size_per_gpu", micro),
                        ("gradient_accumulation_steps", accum)]:
            if not isinstance(v, int) or v <= 0:
                raise DeepSpeedConfigError(f"{name} must be a positive int, got {v!r}")

    def _do_sanity_check(self):
        if self.zero_enabled and not (self.fp16_enabled or self.bf16_enabled):
            # The reference requires fp16 for ZeRO (config.py:664 there); on
            # TPU we additionally accept bf16 (the native dtype).
            raise DeepSpeedConfigError(
                "ZeRO optimization requires fp16 or bf16 to be enabled")
        if self.zero_config.cpu_offload and self.zero_config.stage < 2:
            raise DeepSpeedConfigError(
                "cpu_offload requires ZeRO stage >= 2")
        if self.zero_config.offload_grad_chunks > 1:
            if not self.zero_config.cpu_offload:
                raise DeepSpeedConfigError(
                    "offload_grad_chunks > 1 requires cpu_offload")
            if self.zero_config.offload_impl == "host":
                raise DeepSpeedConfigError(
                    "offload_grad_chunks > 1 is an xla-tier capacity mode "
                    "(offload_impl 'xla' or 'auto')")
        if self.zero_config.offload_split_update:
            if not self.zero_config.cpu_offload:
                raise DeepSpeedConfigError(
                    "offload_split_update requires cpu_offload")
            if self.zero_config.offload_impl == "host":
                raise DeepSpeedConfigError(
                    "offload_split_update is an xla-tier mode "
                    "(offload_impl 'xla' or 'auto')")
        if self.zero_config.delayed_param_update:
            if not self.zero_config.cpu_offload:
                raise DeepSpeedConfigError(
                    "delayed_param_update requires cpu_offload")
        if (self.zero_config.offload_pipeline_explicit
                and self.zero_config.offload_pipeline
                and not self.zero_config.cpu_offload):
            raise DeepSpeedConfigError(
                "offload_pipeline requires cpu_offload (it streams the "
                "host-tier optimizer update)")
        if self.zero_config.param_streaming:
            if not self.zero_config.cpu_offload:
                raise DeepSpeedConfigError(
                    "param_streaming requires cpu_offload")
            if self.zero_config.offload_impl == "host":
                raise DeepSpeedConfigError(
                    "param_streaming is an xla-tier capacity mode "
                    "(offload_impl 'xla' or 'auto')")
        if self.offload_config.tier == "disk":
            if not self.zero_config.cpu_offload:
                raise DeepSpeedConfigError(
                    "offload.tier='disk' requires "
                    "zero_optimization.cpu_offload (the disk tier sits "
                    "below the host offload plane)")
            if self.zero_config.offload_impl == "xla":
                raise DeepSpeedConfigError(
                    "offload.tier='disk' is a host-impl structure "
                    "(per-leaf C++ Adam over disk-resident state); "
                    "offload_impl must be 'host' or 'auto'")
        if self.optimizer_name is not None and self.optimizer_name in (
                C.ONEBIT_ADAM_OPTIMIZER,) and not (self.fp16_enabled or self.bf16_enabled):
            raise DeepSpeedConfigError("onebitadam requires fp16 or bf16")

    def print_config(self):
        logger.info("DeepSpeedConfig:")
        for k in ("train_batch_size", "train_micro_batch_size_per_gpu",
                  "gradient_accumulation_steps", "world_size", "optimizer_name",
                  "scheduler_name", "gradient_clipping"):
            logger.info("  %s: %s", k, getattr(self, k))
        logger.info("  zero: %s", self.zero_config.repr_dict())
