"""Canonical ds_config.json key names and defaults.

Key-for-key compatible with the reference config surface
(reference: deepspeed/runtime/constants.py, deepspeed/runtime/zero/constants.py)
so existing ``ds_config.json`` files parse unchanged.  Defaults differ only
where TPU hardware makes the reference default meaningless (noted inline).
"""

#############################################
# Batch-size triangle
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler blocks
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_PARAMS = "params"

ADAM_OPTIMIZER = "adam"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
DEEPSPEED_OPTIMIZERS = [ADAM_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER]

#############################################
# Precision (fp16 block kept for config parity; bf16 is the TPU default)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

# TPU-native extension: bf16 needs no loss scaling.
BF16 = "bf16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False

#############################################
# Gradient handling
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

# Apex AMP block (reference constants.py:162-172).  Apex has no TPU
# analogue; the block is accepted for ds_config compatibility and, when
# enabled, maps to native bf16 mixed precision (the closest equivalent).
AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

# reference constants.py:73 — client optimizers outside the ZeRO whitelist.
# Under GSPMD any optax transformation's state shards generically, so the
# key is accepted and recorded (nothing to gate).
ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

ALLREDUCE_ALWAYS_FP32 = "fp32_allreduce"
ALLREDUCE_ALWAYS_FP32_DEFAULT = False

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
# Unlike the reference (capped at stage 2: zero/constants.py:33), the TPU
# build supports parameter sharding (stage 3) natively via GSPMD.
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_STAGE = "stage"
ZERO_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_ALLGATHER_PARTITIONS_DEFAULT = True
ZERO_REDUCE_SCATTER = "reduce_scatter"
ZERO_REDUCE_SCATTER_DEFAULT = True
ZERO_OVERLAP_COMM = "overlap_comm"
ZERO_OVERLAP_COMM_DEFAULT = False
ZERO_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_CONTIGUOUS_GRADIENTS_DEFAULT = False
ZERO_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_REDUCE_BUCKET_SIZE_DEFAULT = 500_000_000
ZERO_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_ALLGATHER_BUCKET_SIZE_DEFAULT = 500_000_000
ZERO_CPU_OFFLOAD = "cpu_offload"
ZERO_CPU_OFFLOAD_DEFAULT = False
# TPU extension: which offload tier implements cpu_offload.
#   'xla'  — optimizer state in pinned_host memory; cast + Adam run as XLA
#            host computations inside the one compiled step (server-side
#            PCIe streaming, XLA-scheduled overlap).
#   'host' — single-controller numpy tier + native C++ CPU Adam.
#   'auto' — 'xla' on TPU meshes, 'host' elsewhere.
ZERO_OFFLOAD_IMPL = "offload_impl"
ZERO_OFFLOAD_IMPL_DEFAULT = "auto"
# TPU extension (capacity mode): compute parameter gradients in K
# balanced groups, one compiled program per group, so device-resident
# gradient bytes are bounded by the largest group instead of the full
# model (the program boundary guarantees the liveness bound).  Each
# group re-runs the forward — a deliberate K× compute trade for
# capacity, the in-XLA analogue of the reference streaming grads into
# pinned host buffers during backward (stage2.py:743-816).  1 = off.
ZERO_OFFLOAD_GRAD_CHUNKS = "offload_grad_chunks"
ZERO_OFFLOAD_GRAD_CHUNKS_DEFAULT = 1
# TPU extension (host tier): delayed parameter update — the host Adam
# for step t runs concurrently with the device forward/backward of step
# t+1, which therefore uses one-step-stale parameters (the ZeRO-Offload
# paper's DPU; the reference repo gained it after v0.3.2).  Off by
# default: staleness changes numerics slightly, so it is opt-in like
# the paper describes (enable after convergence stabilizes).
ZERO_DELAYED_PARAM_UPDATE = "delayed_param_update"
ZERO_DELAYED_PARAM_UPDATE_DEFAULT = False
# TPU extension (capacity mode, xla tier): ZeRO-Infinity-style parameter
# streaming (reference: deepspeed/runtime/zero/partition_parameters.py +
# the ZeRO-Infinity paper's NVMe/CPU param offload).  Compute copies of
# the leaves the model marks via ``TrainModule.streaming_param_spec``
# (its stacked-over-layers scan leaves) STAY in host memory; the model
# fetches one layer's slice per scan tick, so device-resident parameter
# bytes ~ one layer instead of 2 bytes/param for the whole model — the
# floor that bounds offload_grad_chunks capacity.  Composes with dp=1
# (any ZeRO stage >= 2) or ZeRO-3 (host leaves stay data-sharded; no
# host-side collectives are ever needed).
ZERO_PARAM_STREAMING = "param_streaming"
ZERO_PARAM_STREAMING_DEFAULT = False
# TPU extension (xla tier): run the optimizer update as ONE COMPILED
# PROGRAM PER MASTER PIECE instead of one fused update program.  XLA
# cannot extend buffer liveness across program boundaries, so device-
# resident optimizer-state bytes are bounded by the largest piece even
# where the compiler materializes host-placed buffers in HBM (observed
# on the AOT compile path: the fused 1.5B update program allocated the
# whole fp32 state as HBM temps).  Costs one dispatch per piece per
# step; numerics identical.  Composes with delayed_param_update: the
# deferred per-piece programs run without donation (ping-pong, the same
# transient 2x host state the fused DPU pays) so the next step's grad
# program can keep reading the old pieces.
ZERO_OFFLOAD_SPLIT_UPDATE = "offload_split_update"
ZERO_OFFLOAD_SPLIT_UPDATE_DEFAULT = False
# TPU extension (host tier): streaming offload update pipeline — the
# engine uploads each leaf's updated low-precision copy H2D the moment
# the C++ Adam writes its block, so while Adam updates leaf i, leaf
# i+1's gradient D2H is in flight AND leaf i-1's upload is already
# streaming (the full three-stage overlap of the ZeRO-Offload design;
# the serial path only overlapped the D2H half).  Numerics identical to
# the serial path.  Default ON; set false (or DS_OFFLOAD_PIPELINE=0,
# the no-config escape hatch) to restore the serial post-step upload.
ZERO_OFFLOAD_PIPELINE = "offload_pipeline"
ZERO_OFFLOAD_PIPELINE_DEFAULT = True
ZERO_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_ELASTIC_CHECKPOINT_DEFAULT = True
ZERO_MAX_ELEMENTS_PER_COMM = "max_elements_per_comm"
ZERO_MAX_ELEMENTS_PER_COMM_DEFAULT = 500_000_000
# Partitioning-correctness debug toggle (the reference's module-level
# ``pg_correctness_test`` in stage2.py:23-25 — here a config key): the
# engine diffs plan-sharded gradients against an unconstrained replicated
# reduction on the first step and raises on mismatch.
ZERO_PG_CORRECTNESS_TEST = "pg_correctness_test"
ZERO_PG_CORRECTNESS_TEST_DEFAULT = False

#############################################
# Activation checkpointing (rematerialization on TPU)
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CKPT_PARTITION_ACTIVATIONS_DEFAULT = False
ACT_CKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CKPT_NUMBER_CHECKPOINTS_DEFAULT = None
ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT = False
ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT = False
ACT_CKPT_PROFILE = "profile"
ACT_CKPT_PROFILE_DEFAULT = False
ACT_CKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
ACT_CKPT_CPU_CHECKPOINTING_DEFAULT = False

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = "fixed"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Pipeline block (TPU extension mirrors reference engine kwargs)
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = 1
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "best"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0
# compiled schedule: "1f1b" (hand-scheduled backward, min(S,M) activation
# ring — the reference TrainSchedule's memory bound), "1f1b_uniform"
# (F+B units masked every tick: schedule-invariant collectives — the
# variant that carries sequence parallelism; min(2S-1,M) ring; selected
# automatically for "1f1b" when the mesh has seq > 1), or "gpipe" (AD
# over the fill/drain scan; O(M) boundary liveness, kept as the fallback)
PIPELINE_SCHEDULE = "schedule"
PIPELINE_SCHEDULE_DEFAULT = "1f1b"

#############################################
# Logging / observability
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

# XLA/xplane trace capture (TPU-native upgrade of the reference's
# cuda-synchronized named timers, utils/timer.py there; SURVEY §5.1 notes
# the reference ships no external tracer — on TPU the jax.profiler xplane
# trace is the native equivalent, viewable in tensorboard-profile/xprof).
PROFILER = "profiler"
PROFILER_ENABLED = "enabled"
PROFILER_ENABLED_DEFAULT = False
PROFILER_START_STEP = "start_step"
PROFILER_START_STEP_DEFAULT = 2       # skip compile on step 0/1
PROFILER_NUM_STEPS = "num_steps"
PROFILER_NUM_STEPS_DEFAULT = 3
PROFILER_OUTPUT_PATH = "output_path"
PROFILER_OUTPUT_PATH_DEFAULT = "/tmp/deepspeed_tpu_profile"

TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

# Unified telemetry block (TPU extension; docs/observability.md): one
# structured observability layer — metrics registry with JSONL /
# Prometheus / SummaryWriter exporters, Chrome-trace span tracing that
# rides the engine's EXISTING sync points (zero added per-step device
# syncs, unlike wall_clock_breakdown), jax.monitoring compile tracking
# (recompiles_total{program=...} — jaxlint JL005's runtime complement),
# and device-memory gauges from the structured memory_status.
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"
TELEMETRY_ENABLED_DEFAULT = False
# "" resolves to <cwd>/telemetry; files: events.jsonl, trace.json,
# metrics.prom
TELEMETRY_OUTPUT_PATH = "output_path"
TELEMETRY_OUTPUT_PATH_DEFAULT = ""
TELEMETRY_TRACE = "trace"
TELEMETRY_TRACE_DEFAULT = True
TELEMETRY_COMPILE_EVENTS = "compile_events"
TELEMETRY_COMPILE_EVENTS_DEFAULT = True
TELEMETRY_MEMORY = "memory"
TELEMETRY_MEMORY_DEFAULT = True
# retraces of one program within a single sample window that trigger the
# recompile-storm warning
TELEMETRY_STORM_THRESHOLD = "recompile_storm_threshold"
TELEMETRY_STORM_THRESHOLD_DEFAULT = 3
# Elastic-training liveness (docs/elastic.md): every process writes a
# per-host heartbeat file each step (atomic JSON) into a shared dir —
# the supervisor's liveness signal and the straggler monitor's input.
# Enabled implicitly when the supervisor exports DS_HEARTBEAT_DIR;
# `heartbeat: true` enables it without a supervisor (files land under
# heartbeat_dir, default <telemetry output>/heartbeats).
TELEMETRY_HEARTBEAT = "heartbeat"
TELEMETRY_HEARTBEAT_DEFAULT = False
TELEMETRY_HEARTBEAT_DIR = "heartbeat_dir"
TELEMETRY_HEARTBEAT_DIR_DEFAULT = ""
# a host whose per-step time exceeds this multiple of the fleet median
# is flagged (straggler_detected_total + summarize row); must be > 1
TELEMETRY_STRAGGLER_RATIO = "straggler_ratio"
TELEMETRY_STRAGGLER_RATIO_DEFAULT = 2.0
# One-shot anomaly trigger (docs/observability.md): when a synced
# interval's per-step time exceeds anomaly_ratio x the trailing median
# of recent intervals — or the straggler monitor flags THIS host — the
# engine fires ONE bounded jax.profiler capture (stopped at the next
# sync) plus a flight-record dump, so the slow episode is captured
# while it is still happening.  Opt-in: 0.0 (default) disables; when
# set it must be > 1.0 (it multiplies the trailing median).
TELEMETRY_ANOMALY_RATIO = "anomaly_ratio"
TELEMETRY_ANOMALY_RATIO_DEFAULT = 0.0

# Asynchronous input pipeline (TPU extension; docs/observability.md):
# a single daemon worker prefetches batches through a bounded queue and
# runs collate + batch sharding (H2D placement) OFF the step loop's
# thread, so train_batch receives already-device-resident pytrees — the
# input-feeding half of the ZeRO-Offload overlap story.  Default ON;
# set enabled:false (or DS_PREFETCH=0, the no-config escape hatch) to
# restore the inline collate+placement.  ``depth`` is the queue bound
# (2 = double buffering: one batch consumed, one staged ahead).
DATA_PREFETCH = "data_prefetch"
DATA_PREFETCH_ENABLED = "enabled"
DATA_PREFETCH_ENABLED_DEFAULT = True
DATA_PREFETCH_DEPTH = "depth"
DATA_PREFETCH_DEPTH_DEFAULT = 2

#############################################
# Fault-tolerant checkpointing (TPU extension; docs/checkpointing.md)
#############################################
# One block for the save/load resilience plane: async background writes,
# integrity verification (per-leaf CRC32 + manifest digests), the
# corrupt-latest fallback chain, retention GC, transient-I/O retry, and
# the SIGTERM preemption hook.  The reference writes synchronously and
# trusts the filesystem (reference engine.py:1211-1290).
CHECKPOINT = "checkpoint"
# true = every save_checkpoint call defaults to the async path (snapshot
# to host, daemon writer serializes off the hot path); per-call
# async_write= overrides.  Single-controller only (multi-host saves need
# the cross-process barriers and stay synchronous).
CKPT_ASYNC_SAVE = "async_save"
CKPT_ASYNC_SAVE_DEFAULT = False
# retention: keep the newest N tags, GC older ones (and orphaned *.tmp
# dirs) strictly AFTER a new save verifies.  0 = unlimited (never
# delete) — the reference behavior, and the safe default.
CKPT_KEEP_LAST_N = "keep_last_n"
CKPT_KEEP_LAST_N_DEFAULT = 0
# corrupt-latest fallback: how many OLDER on-disk tags
# load_checkpoint(tag=None) tries (deep CRC verify) after the tag
# `latest` names fails verification or is gone.  0 disables walking back.
CKPT_LOAD_FALLBACK = "load_fallback"
CKPT_LOAD_FALLBACK_DEFAULT = 2
# transient-I/O retry: TOTAL attempts per read/write (1 = no retry) and
# the exponential-backoff base (full jitter; capped at 2s per wait)
CKPT_IO_RETRY_ATTEMPTS = "io_retry_attempts"
CKPT_IO_RETRY_ATTEMPTS_DEFAULT = 3
CKPT_IO_RETRY_BASE_S = "io_retry_base_s"
CKPT_IO_RETRY_BASE_S_DEFAULT = 0.05
# opt-in preemption hook: on SIGTERM, one final SYNCHRONOUS save + clean
# engine.close() so a preempted pod resumes at the last step instead of
# the last checkpoint-interval boundary.  Single-controller only.
CKPT_SIGTERM_SAVE = "sigterm_save"
CKPT_SIGTERM_SAVE_DEFAULT = False
# where the SIGTERM save lands when no save_checkpoint has run yet this
# process ("" = use the directory of the most recent save)
CKPT_SAVE_DIR = "save_dir"
CKPT_SAVE_DIR_DEFAULT = ""

#############################################
# Shared async-stage runtime (TPU extension; docs/stages.md)
#############################################
# One fault plane for every async subsystem (input prefetch, streamed
# offload uploads, the async checkpoint writer): shared worker/queue/
# poison/drain primitives in runtime/stages.py, a single documented
# drain order, and graceful degradation — a stage that keeps failing
# with a TRANSIENT error falls back to its inline/serial equivalent
# (prefetch -> inline iteration, streamed offload -> serial update,
# async save -> sync save) with one loud warning and a
# ``stage_degraded_total`` counter instead of killing the run.
STAGES = "stages"
# consecutive transient failures a stage absorbs (retrying) before it
# degrades.  1 = degrade on the first failure; the budget resets on
# every success.
STAGES_MAX_FAILURES = "max_stage_failures"
STAGES_MAX_FAILURES_DEFAULT = 3

#############################################
# Offload tier selection (ZeRO-Infinity disk tier; docs/stages.md)
#############################################
# Which tier holds the fp32 master params + Adam moments under
# cpu_offload with the host impl: "host" keeps them in host RAM (the
# PR 3 host tier), "disk" streams them through per-leaf CRC'd files in
# ``disk_dir`` (runtime/disk_offload.py) — host RAM then holds only a
# bounded window of leaves, so trainable size is capped by disk, not
# RAM.
OFFLOAD = "offload"
OFFLOAD_TIER = "tier"
OFFLOAD_TIER_DEFAULT = "host"
# directory for the disk tier's per-leaf state files (REQUIRED when
# tier == "disk"; created if missing).
OFFLOAD_DISK_DIR = "disk_dir"
OFFLOAD_DISK_DIR_DEFAULT = None
# bounded read-ahead/write-back depth of the disk pipeline: at most
# io_depth leaf states are prefetched from disk (and at most io_depth
# queued for write-back) while the C++ Adam runs — THE knob bounding
# resident host bytes to ~(2*io_depth + 1) leaf states.
OFFLOAD_IO_DEPTH = "io_depth"
OFFLOAD_IO_DEPTH_DEFAULT = 2
# per-file fsync before the atomic rename of each leaf-state write
# (power-loss durability; the DS_CKPT_FSYNC discipline).  The
# DS_DISK_FSYNC env var (default on; tests set 0) can force it off
# without a config edit — see runtime/disk_offload.py.
OFFLOAD_FSYNC = "fsync"
OFFLOAD_FSYNC_DEFAULT = True

#############################################
# Serving / inference engine (TPU extension; docs/serving.md)
#############################################
# The KV-cached decode engine with static-shape continuous batching
# (deepspeed_tpu/inference/).  The reference v0.3.2 ships no inference
# engine at all; this block configures the slot pool that one compiled
# decode program serves for arbitrary request mixes.
SERVING = "serving"
# fixed number of concurrent request slots — THE static batch shape of
# the decode program.  Admission/eviction are masked in-place KV
# updates, never a shape change.
SERVING_SLOTS = "slots"
SERVING_SLOTS_DEFAULT = 8
# per-slot KV capacity (prompt + generated tokens).  0 = the model's
# n_positions.
SERVING_MAX_SEQ_LEN = "max_seq_len"
SERVING_MAX_SEQ_LEN_DEFAULT = 0
# prompts are right-padded to this static bucket so prefill is ONE
# compiled program too.  0 = max_seq_len.
SERVING_PREFILL_LEN = "prefill_len"
SERVING_PREFILL_LEN_DEFAULT = 0
# decode attention kernel arm: 'pallas' (single-query flash kernel,
# interpret mode off-TPU), 'dense' (the jnp reference — the CPU
# fallback), or 'auto' (follow the model's attn_impl).
SERVING_DECODE_IMPL = "decode_impl"
SERVING_DECODE_IMPL_DEFAULT = "auto"
# bound of the request Channel feeding the slot scheduler; submit()
# blocks when full (open-loop backpressure).
SERVING_QUEUE_CAPACITY = "queue_capacity"
SERVING_QUEUE_CAPACITY_DEFAULT = 128
# serving ticks between telemetry materializations (tokens/s +
# per-token latency percentiles land as sync scalars each flush)
SERVING_FLUSH_INTERVAL = "flush_interval_ticks"
SERVING_FLUSH_INTERVAL_DEFAULT = 50
# default end-of-sequence token id finishing a request early; -1 = none
# (per-request eos_id overrides)
SERVING_EOS_ID = "eos_id"
SERVING_EOS_ID_DEFAULT = -1
# paged KV cache (PagedAttention, PAPERS.md): tokens-per-page of the
# flat page pool replacing the fixed max_seq_len stride per slot.
# 0 = paged OFF (the pre-page slot cache — the parity reference arm).
SERVING_PAGE_LEN = "page_len"
SERVING_PAGE_LEN_DEFAULT = 0
# total pages in the pool (page 0 is the reserved scratch page masked
# writes land on).  0 = auto: enough for every slot at max_seq_len
# (capacity-neutral) + the scratch page, rounded up to the mesh's data
# width so the pool DP-shards evenly.
SERVING_PAGES = "pages"
SERVING_PAGES_DEFAULT = 0
# prefix reuse over shared pages (RadixAttention, PAPERS.md): prompt
# prefixes hash to refcounted read-only pages so template-sharing
# requests pay prefill once; divergent appends copy-on-write the last
# partial page.  Only meaningful with page_len > 0.
SERVING_PREFIX_CACHE = "prefix_cache"
SERVING_PREFIX_CACHE_DEFAULT = True
# speculative decoding (Leviathan/Chen 2023, PAPERS.md): draft tokens
# proposed per tick; the target scores all k+1 positions in ONE widened
# verify program and emits the accepted prefix + one bonus token.
# 0 = speculation OFF (the one-token-per-tick parity reference arm).
SERVING_SPECULATE_K = "speculate_k"
SERVING_SPECULATE_K_DEFAULT = 0
# decode sampling temperature for the whole engine (STATIC — it picks
# the compiled emission/acceptance arm).  0.0 = greedy (bitwise the
# pre-sampling argmax); > 0 samples softmax(logits/T), and speculation
# switches to the Chen et al. rejection-sampling acceptance that
# recovers the target distribution exactly.
SERVING_TEMPERATURE = "temperature"
SERVING_TEMPERATURE_DEFAULT = 0.0
# the DRAFT model block (speculate_k > 0): a small GPT-2 config built
# through the ordinary config system.  vocab_size/n_positions are
# FORCED from the target model (the proposal streams must share a
# token space); everything else defaults tiny.  The draft always runs
# its own fixed-stride slot KV cache — at draft scale a full stride is
# a rounding error next to the target's pool, paged or not.
SERVING_DRAFT = "draft"
SERVING_DRAFT_D_MODEL = "d_model"
SERVING_DRAFT_D_MODEL_DEFAULT = 256
SERVING_DRAFT_N_LAYER = "n_layer"
SERVING_DRAFT_N_LAYER_DEFAULT = 2
SERVING_DRAFT_N_HEAD = "n_head"
SERVING_DRAFT_N_HEAD_DEFAULT = 4
# draft attention impl: '' = follow the target model's attn_impl
SERVING_DRAFT_ATTN_IMPL = "attn_impl"
SERVING_DRAFT_ATTN_IMPL_DEFAULT = ""
# quantized serving plane (LLM.int8 weights + KVQuant/KIVI-style KV
# pages, PAPERS.md; docs/serving.md "quantized serving").  Each arm is
# independently togglable; 'fp16' = the master dtype as loaded (fp16 on
# a half-precision deployment, fp32 on the CPU oracle) — NO cast, so
# the default config is bitwise-unchanged vs the pre-quant engine.
SERVING_QUANTIZATION = "quantization"
# 'int8': one-shot post-load symmetric per-output-channel absmax
# quantization of the GPT-2 matmul weights (attn qkv/proj, MLP) with
# dequant fused into the serving matmuls as (int8_w · x) * scale; the
# fp master copy never reaches device memory — params HBM ~ halved.
SERVING_QUANT_WEIGHTS = "weights"
SERVING_QUANT_WEIGHTS_DEFAULT = "fp16"
# 'int8': the paged KV pool stores int8 rows + a per-(page, head, row)
# fp32 scale sidecar, quantized on write inside the compiled programs
# and dequantized fused into the decode kernels — ~2x more pages in
# the same KV bytes, multiplicative with serving.page_len.  Requires
# page_len > 0 (the slot layout keeps the master dtype).
SERVING_QUANT_KV = "kv"
SERVING_QUANT_KV_DEFAULT = "fp16"
# chunked prefill (Sarathi-Serve, PAPERS.md; docs/serving.md
# "disaggregated fleet"): prompts whose delta is longer than this are
# prefilled one fixed-size chunk per engine step, co-scheduled with
# decode ticks, so a long admission never stalls in-flight decodes.
# prefix_len/delta_len are traced, so every chunk reuses the ONE
# compiled prefill program.  0 = chunking OFF (whole-delta prefill at
# admission).  Requires page_len > 0.
SERVING_PREFILL_CHUNK_LEN = "prefill_chunk_len"
SERVING_PREFILL_CHUNK_LEN_DEFAULT = 0
# multi-tenant LoRA serving (S-LoRA / Punica, PAPERS.md;
# docs/serving.md "multi-tenant serving"): per-tenant low-rank
# adapters batched HETEROGENEOUSLY over one base model — each
# decode/prefill/verify pass gathers per-slot adapter weights by a
# traced int32 adapter-table indirection (the page-table idiom applied
# to weights) and fuses y += (x·A)·B · (alpha/rank) next to the base
# matmul, so requests for different tenants ride the SAME compiled
# tick.  Adapters live in a refcounted host/HBM residency pool managed
# exactly like KV pages (inference/adapters.py).
SERVING_LORA = "lora"
# the shared low-rank dimension r of every adapter (STATIC — it is a
# compiled shape).  0 = lora OFF: no pool, no extra operands, programs
# bitwise-unchanged vs the pre-lora engine.
SERVING_LORA_RANK = "rank"
SERVING_LORA_RANK_DEFAULT = 0
# the LoRA scaling numerator: deltas apply as (alpha / rank) · BAx.
# Static — baked into the compiled programs at trace time.
SERVING_LORA_ALPHA = "alpha"
SERVING_LORA_ALPHA_DEFAULT = 16.0
# registry capacity: distinct tenant adapters the HOST tier holds
# (cheap numpy copies — the S-LoRA main-memory tier)
SERVING_LORA_MAX_ADAPTERS = "max_adapters"
SERVING_LORA_MAX_ADAPTERS_DEFAULT = 64
# HBM residency slots: adapters resident on device simultaneously.
# Slot 0 is the reserved all-zero adapter (requests without a tenant
# gather it — a masked no-op, like the KV scratch page), so the device
# pool allocates hbm_adapter_slots + 1 slots.  Cold tenants LRU-evict
# refcount-0 residents; when every slot is referenced, admission PARKS
# (the page-pool backpressure contract).
SERVING_LORA_HBM_SLOTS = "hbm_adapter_slots"
SERVING_LORA_HBM_SLOTS_DEFAULT = 8
# which base matmuls carry adapters, by block-param name: any subset
# of qkv_w / out_w (attention) and fc_w / proj_w (MLP).  The default
# adapts the attention projections — the S-LoRA/Punica headline
# targets; widening to the MLP pair scales cost, not mechanism.
SERVING_LORA_TARGETS = "targets"
SERVING_LORA_TARGETS_DEFAULT = ("qkv_w", "out_w")

#############################################
# KV tiering (TPU extension; docs/serving.md "KV tiering")
#############################################
# Park idle sessions' KV pages off HBM (inference/kv_tier.py): cold
# prefix-cache pages spill HBM -> host RAM -> disk and stream back on
# session resume as a prefix-cache hit.  Rides the paged serving plane
# (serving.page_len > 0 with the prefix cache on).
SERVING_KV_TIER = "kv_tier"
# a prefix-cache leaf idle for this many engine TICKS is parked:
# exported to the host tier, CRC-stamped, then evicted from the page
# pool.  0 = KV tiering off (the default: no tier, no extra host
# copies, engine behavior bitwise unchanged).
SERVING_KV_TIER_IDLE_PARK_TICKS = "idle_park_ticks"
SERVING_KV_TIER_IDLE_PARK_TICKS_DEFAULT = 0
# parked page payloads kept in host RAM; beyond this the OLDEST parked
# pages write back to the disk tier (or, with no disk_dir, are dropped
# — resume recomputes them from the prompt).  0 = write-through: every
# parked page goes straight to disk.
SERVING_KV_TIER_HOST_BUDGET_PAGES = "host_budget_pages"
SERVING_KV_TIER_HOST_BUDGET_PAGES_DEFAULT = 256
# directory of the disk tier's parked-page files (PR 15's magic/JSON-
# header/section-CRC format, tmp+rename).  "" = no disk tier: the host
# budget is the tier's total capacity.
SERVING_KV_TIER_DISK_DIR = "disk_dir"
SERVING_KV_TIER_DISK_DIR_DEFAULT = ""
# fsync parked-page files before rename (crash durability for the disk
# tier; DS_DISK_FSYNC=0 force-disables, same switch as the optimizer
# disk tier)
SERVING_KV_TIER_FSYNC = "fsync"
SERVING_KV_TIER_FSYNC_DEFAULT = True

#############################################
# Serving fleet (TPU extension; docs/serving.md "serving fleet")
#############################################
# Router + replicated ServeEngines + SLO autoscaling
# (deepspeed_tpu/inference/fleet.py): one jax-free front door spawns N
# replica subprocesses, balances admissions join-shortest-queue over
# the replicas' heartbeat gauges, fails queued-but-unstarted requests
# over on replica death, and scales the replica count against a
# queue-wait SLO.
FLEET = "fleet"
# replicas launched at start() — the fleet's initial width
FLEET_REPLICAS = "replicas"
FLEET_REPLICAS_DEFAULT = 1
# autoscale clamps: the router never retires below min_replicas and
# never spawns above max_replicas (a runaway SLO breach must not fork
# the host to death)
FLEET_MIN_REPLICAS = "min_replicas"
FLEET_MIN_REPLICAS_DEFAULT = 1
FLEET_MAX_REPLICAS = "max_replicas"
FLEET_MAX_REPLICAS_DEFAULT = 4
# the SLO target: queue-wait (router submit -> replica admission) p99
# the autoscaler defends
FLEET_SLO_P99_S = "slo_p99_s"
FLEET_SLO_P99_S_DEFAULT = 2.0
# hysteresis windows: a breach (p99 over the SLO, or any request
# waiting longer than it) must persist scale_up_window_s before a spawn;
# slack (p99 under SLO/2 — or no waiters at all — with an empty router
# queue) must persist scale_down_window_s before a retire.  Every scale
# event resets both clocks, so the fleet can never flap inside a window.
FLEET_SCALE_UP_WINDOW_S = "scale_up_window_s"
FLEET_SCALE_UP_WINDOW_S_DEFAULT = 10.0
FLEET_SCALE_DOWN_WINDOW_S = "scale_down_window_s"
FLEET_SCALE_DOWN_WINDOW_S_DEFAULT = 30.0
# a replica whose newest heartbeat is older than this is HUNG (wedged
# device call with the process still alive): killed + failed over like
# a dead one.  0 = heartbeat liveness off (process exits only).
FLEET_HEARTBEAT_TIMEOUT_S = "heartbeat_timeout_s"
FLEET_HEARTBEAT_TIMEOUT_S_DEFAULT = 60.0
# crash-loop give-up budget: consecutive replica failures WITHOUT any
# request completing in between before the router raises the typed
# FleetGiveUpError (progress resets the count — a fleet serving for
# days must not die on its max_restarts'th isolated blip)
FLEET_MAX_RESTARTS = "max_restarts"
FLEET_MAX_RESTARTS_DEFAULT = 3
# exponential backoff between replica respawns (the elastic
# supervisor's discipline, launcher/supervise.py)
FLEET_BACKOFF_BASE_S = "backoff_base_s"
FLEET_BACKOFF_BASE_S_DEFAULT = 1.0
FLEET_BACKOFF_MAX_S = "backoff_max_s"
FLEET_BACKOFF_MAX_S_DEFAULT = 30.0
# a spawned replica must say hello within this budget or the spawn
# counts as failed (jax import + model build + first compile all land
# inside it — size generously for real models)
FLEET_SPAWN_TIMEOUT_S = "spawn_timeout_s"
FLEET_SPAWN_TIMEOUT_S_DEFAULT = 120.0
# SIGTERM -> grace -> SIGKILL teardown window per replica
FLEET_TERM_GRACE_S = "term_grace_s"
FLEET_TERM_GRACE_S_DEFAULT = 5.0
# disaggregated prefill/decode roles (DistServe/Splitwise, PAPERS.md;
# docs/serving.md "disaggregated fleet"): a mapping of role name ->
# initial replica count, keys from {"prefill", "decode", "mixed"}.
# None (the default) = every replica is "mixed" — the homogeneous
# fleet, byte-identical to the pre-role router.  With prefill+decode
# roles set, the router steers admissions to prefill replicas and
# migrates finished prefills' KV pages to decode replicas over binary
# wire frames; fleet.replicas, when given alongside roles, must equal
# the sum of the role counts.
FLEET_ROLES = "roles"
FLEET_ROLES_DEFAULT = None
# per-phase SLOs the role-aware autoscaler defends SEPARATELY:
# slo_ttft_s bounds time-to-first-token (prefill-role capacity; 0 =
# fall back to slo_p99_s) and slo_tpot_s bounds time-per-output-token
# p99 read from the decode replicas' heartbeat gauges (0 = TPOT
# scaling off).  Homogeneous fleets ignore both and keep the
# queue-wait SLO above.
FLEET_SLO_TTFT_S = "slo_ttft_s"
FLEET_SLO_TTFT_S_DEFAULT = 0.0
FLEET_SLO_TPOT_S = "slo_tpot_s"
FLEET_SLO_TPOT_S_DEFAULT = 0.0

PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001
