from .config import (
    DeepSpeedConfig,
    DeepSpeedConfigError,
    DeepSpeedZeroConfig,
    DeepSpeedFP16Config,
    DeepSpeedBF16Config,
    DeepSpeedActivationCheckpointingConfig,
    DeepSpeedSparseAttentionConfig,
    DeepSpeedServingConfig,
    DeepSpeedPipelineConfig,
    DeepSpeedConfigWriter,
)
from . import constants
