from .config import (
    DeepSpeedConfig,
    DeepSpeedConfigError,
    DeepSpeedZeroConfig,
    DeepSpeedFP16Config,
    DeepSpeedBF16Config,
    DeepSpeedActivationCheckpointingConfig,
    DeepSpeedSparseAttentionConfig,
    DeepSpeedPipelineConfig,
    DeepSpeedConfigWriter,
)
from . import constants
