from .config import (
    DeepSpeedConfig,
    DeepSpeedConfigError,
    DeepSpeedZeroConfig,
    DeepSpeedFP16Config,
    DeepSpeedBF16Config,
    DeepSpeedActivationCheckpointingConfig,
    DeepSpeedSparseAttentionConfig,
    DeepSpeedServingConfig,
    DeepSpeedFleetConfig,
    DeepSpeedPipelineConfig,
    DeepSpeedConfigWriter,
)
from . import constants
