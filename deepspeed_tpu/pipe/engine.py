"""Pipeline-parallel engine (1F1B over the ``pipe`` mesh axis).

Implementation lands with the pipeline milestone; this placeholder keeps
``deepspeed_tpu.initialize`` dispatch importable with a clear error instead
of a ModuleNotFoundError.
"""
from __future__ import annotations


class PipelineEngine:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "PipelineEngine is not implemented yet in this build; "
            "use a non-pipeline model or ZeRO data parallelism meanwhile")
