"""Pipeline-parallel engine — the whole schedule in one compiled program.

The reference interprets schedules imperatively: a dispatch table maps
instructions to Python methods that issue NCCL ops and autograd calls
(reference: deepspeed/runtime/pipe/engine.py:1131-1157, p2p pair-group
broadcasts at runtime/pipe/p2p.py:31-55, shape-metadata handshake at
pipe/engine.py:653-764).  On TPU the entire pipelined training step is ONE
jit program (SURVEY.md §7 "hard parts" #3, option (b)):

  - ``shard_map`` over the ``pipe`` mesh axis, manual only on that axis
    (data/model stay under GSPMD, so ZeRO + tensor parallelism compose);
  - a ``lax.scan`` over M + S - 1 ticks; at each tick every stage runs its
    layer range (``lax.switch`` on ``axis_index('pipe')`` — heterogeneous
    stages supported, only stage-BOUNDARY activations must share a shape);
  - activation handoff is one ``ppermute`` per tick (static shapes: the
    reference's meta handshake has no equivalent here);
  - the backward schedule is not written at all: differentiating the scan
    transposes every ppermute and replays ticks in reverse — the fill/drain
    structure the reference hand-codes in TrainSchedule falls out of AD;
  - loss is computed on the last stage under ``lax.cond`` and shared via
    ``psum`` (reference _aggregate_total_loss, pipe/engine.py:373-403).

Gradient accumulation IS pipeline micro-batching here (as in the
reference's train_batch contract, pipe/engine.py:229-303): the engine
consumes ``gradient_accumulation_steps`` micro-batches per step, all live
in the pipeline at once.

Tied layers (e.g. embedding/LM-head): tied params live once in the param
tree; every stage's branch reads them, so AD sums their gradient
contributions across stages — replacing the tied-weight comm groups and
explicit allreduce (reference: runtime/pipe/module.py:405-474).

Parameter placement is STAGE-LOCAL (reference materializes only each
stage's own layers: runtime/pipe/module.py:197-249): homogeneous layers
are stacked into [num_stages, k, ...] leaves sharded over ``pipe``
(see PipelineModule.stack_plan), enter the shard_map with in_spec
``P('pipe')`` — so their gradient transpose is local (no psum over pipe,
no fp32 all-stage replica) and each chip stores ≈ total/num_stages param
bytes.  Only the pipe-replicated remainder (embedding/norm/tied, a small
fraction) crosses the boundary replicated in fp32.  Activation liveness is
bounded by whole-stage rematerialization per tick: the scan stores only
stage-BOUNDARY activations, the remat analogue of the reference's 1F1B
buffer bound min(stages - stage_id + 1, micro_batches)
(reference: runtime/pipe/schedule.py:243-247).

ZeRO composes on top: stages 1/2 shard master/opt-state and grads over
``data`` on the non-pipe dims; stage 3 additionally stores compute params
data-sharded — the boundary constraint is then the per-step param
all-gather.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, PIPE_AXIS, mesh_axis_size
from ..runtime.engine import DeepSpeedEngine
from ..runtime.module import TrainModule
from ..runtime.prefetch import DevicePlacedBatch
from ..utils.logging import log_dist
from .module import PipelineModule


class _ReplicatedParamsView(dict):
    """Params visible to a 3-ary pipeline loss head.  The loss head is
    traced on every stage (lax.cond), so it may only read pipe-replicated
    params; reading a stage-local (stacked) layer fails here with a real
    explanation instead of a bare KeyError from deep inside jit."""

    def __missing__(self, key):
        raise KeyError(
            f"pipeline loss head tried to read param {key!r}, which is "
            "stage-local (stacked over the pipe axis). A 3-ary loss head "
            "runs on every stage and may only read pipe-replicated params: "
            f"tied layers or non-stacked resident layers ({list(self)}). "
            "Make the layer a TiedLayerSpec or compute the loss inside the "
            "last stage's layers instead.")


class _PipelinedTrainModule(TrainModule):
    """Adapts a PipelineModule to the engine's TrainModule protocol; its
    loss_fn runs the full GPipe-style pipelined forward."""

    def __init__(self, pipe_module: PipelineModule, mesh, num_micro: int):
        self.pm = pipe_module
        self.mesh = mesh
        self.num_micro = num_micro
        self.num_stages = pipe_module.num_stages
        if pipe_module.loss_fn is None:
            raise ValueError("PipelineModule needs loss_fn for training")
        # loss_fn arity: (outputs, labels) or (params, outputs, labels) —
        # the 3-ary form lets the loss head read params (e.g. a tied
        # embedding projection, the reference's TiedLayerSpec LM head).
        # Count only required positional params so `def mse(o, l, eps=1e-8)`
        # stays 2-ary.
        import inspect
        try:
            sig = inspect.signature(pipe_module.loss_fn)
            nargs = sum(
                1 for p in sig.parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                and p.default is p.empty)
        except (TypeError, ValueError):
            nargs = 2
        self._loss_takes_params = nargs >= 3

    def init(self, rng):
        return self.pm.init(rng)

    def param_partition_specs(self, params):
        # replicated over pipe; tensor-parallel ('model') placement comes
        # from the layers; ZeRO composes the data axis on top
        return self.pm.param_partition_specs(params)

    # -----------------------------------------------------------------
    def _boundary_struct(self, params, inputs_micro, rng):
        """Shape/dtype of activations at each stage boundary (must agree)."""
        pm = self.pm
        structs = []
        x = inputs_micro
        for s in range(self.num_stages):
            start, stop = pm.stage_layer_range(s)
            try:
                x = jax.eval_shape(
                    lambda p, xx: pm.forward_range(p, xx, rng, start, stop,
                                                   train=True),
                    params, x)
            except Exception as e:
                raise ValueError(
                    f"pipeline stage {s} (layers [{start},{stop})) cannot "
                    f"consume the previous stage's boundary activation — "
                    f"stage boundaries must share one shape: {e}") from e
            structs.append(x)
        # Every stage output must share one shape: boundaries feed the next
        # stage AND all stage bodies are branches of one lax.switch.
        first = structs[0]
        for i, st in enumerate(structs):
            if (st.shape, st.dtype) != (first.shape, first.dtype):
                raise ValueError(
                    "pipeline stage boundaries must share one activation "
                    f"shape; stage {i} output is {st.shape}/{st.dtype} vs "
                    f"{first.shape}/{first.dtype} — adjust the partition")
        return structs[0]

    def _split_micro(self, tree):
        """[B, ...] -> [M, B/M, ...] sharded over data on the sample dim."""
        M, mesh = self.num_micro, self.mesh

        def r(x):
            if x.shape[0] % M != 0:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"micro count {M}")
            x = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(None, DATA_AXIS)))
        return jax.tree.map(r, tree)

    def _prepare(self, params, batch, rng):
        """Shared front half of both schedules: micro split + boundary."""
        if not (isinstance(batch, (tuple, list)) and len(batch) == 2):
            raise ValueError(
                "pipeline batch must be a (inputs, labels) pair")
        inputs, labels = batch
        micros_in = self._split_micro(inputs)
        micros_lb = self._split_micro(labels)
        sample_in = jax.tree.map(lambda x: jax.ShapeDtypeStruct(
            x.shape[1:], x.dtype), micros_in)
        boundary = self._boundary_struct(params, sample_in, rng)
        parts = [self.pm.stage_layer_range(s)
                 for s in range(self.num_stages)]
        return micros_in, micros_lb, boundary, parts

    def _uniform_stack_info(self):
        """Uniform-stage layout, or None.

        Returns ``(stack_name, rows [S,k] int table, prefix, suffix)``
        when every stage runs the same count of stacked rows and the only
        non-stacked layers sit at the very edges (a stage-0 prefix like a
        tied embedding, a last-stage suffix like a final norm).  This is
        the layout that lets the tick body run WITHOUT a per-stage
        lax.switch — required for sequence parallelism × pipeline (the
        ring attention ppermutes over 'seq' must execute uniformly on
        every pipe rank; collectives inside divergent switch branches
        deadlock the collective rendezvous)."""
        pm, S = self.pm, self.num_stages
        plan = pm.stack_plan()
        if S < 2 or len(plan) != 1:
            return None
        (name, stages), = plan.items()
        k = len(stages[0])
        if k == 0 or any(len(r) != k for r in stages):
            return None
        parts = [pm.stage_layer_range(s) for s in range(S)]
        stacked = {i for r in stages for i in r}
        prefix = [i for i in range(*parts[0]) if i not in stacked]
        suffix = [i for i in range(*parts[S - 1]) if i not in stacked]
        if any(i > min(stages[0]) for i in prefix):
            return None
        if any(i < max(stages[S - 1]) for i in suffix):
            return None
        for s in range(1, S - 1):
            if any(i not in stacked for i in range(*parts[s])):
                return None
        import numpy as _np
        return name, _np.asarray(stages, _np.int32), prefix, suffix

    def loss_fn(self, params, batch, rng, train: bool = True):
        pm, S, M = self.pm, self.num_stages, self.num_micro
        mesh = self.mesh
        plan = pm.stack_plan()
        micros_in, micros_lb, boundary, parts = self._prepare(
            params, batch, rng)
        from ..parallel.sequence import SEQ_AXIS
        sp = dict(mesh.shape).get(SEQ_AXIS, 1)
        uni = self._uniform_stack_info() if sp > 1 else None
        if sp > 1 and uni is None:
            raise NotImplementedError(
                "sequence parallelism × pipeline needs a uniformly "
                "stacked PipelineModule (equal stacked rows per stage, "
                "non-stacked layers only as a stage-0 prefix / last-stage "
                "suffix) so the per-tick seq collectives are identical on "
                "every pipe rank; this module's partition is not uniform")

        # ALL params cross the shard_map boundary in fp32 so gradient
        # accumulation across the scan's ticks happens in fp32 (the per-tick
        # bf16 cotangent is cast up by the astype transpose before the scan
        # sums it — with M micro-batches a bf16 sum would lose ~2^-8
        # relative precision and overflow earlier under fp16 loss scaling).
        # Placement differs per top-level key:
        #  - STACKED params enter sharded over ``pipe`` (in_spec P('pipe')):
        #    their transpose is LOCAL (no psum over pipe) and the fp32 copy
        #    is stage-local and transient — each chip holds total/S, not a
        #    full replica.  Dims past the stage dim are constrained
        #    replicated — under ZeRO-3 this boundary constraint IS the
        #    per-step param all-gather over ``data``.
        #  - pipe-REPLICATED params (tied/resident — small) cross fully
        #    replicated: a replicated input's transpose is a psum over
        #    ``pipe`` (a bf16 psum also trips an XLA-CPU AllReducePromotion
        #    crash on the test mesh).  The constraint keeps every collective
        #    at the shard_map boundary — a data-axis all-gather inside the
        #    last-stage-only lax.cond loss head deadlocks the pipe ppermute
        #    rendezvous otherwise.
        param_dtypes = {k: jax.tree.map(lambda l: l.dtype, v)
                        for k, v in params.items()}

        def place(tree):
            out = {}
            for k, v in tree.items():
                spec = P(PIPE_AXIS) if k in plan else P()
                out[k] = jax.tree.map(
                    lambda l, spec=spec: jax.lax.with_sharding_constraint(
                        l.astype(jnp.float32)
                        if jnp.issubdtype(l.dtype, jnp.floating) else l,
                        NamedSharding(mesh, spec)), v)
            return out

        param_in_specs = {
            k: jax.tree.map(lambda _: P(PIPE_AXIS) if k in plan else P(),
                            v)
            for k, v in params.items()}

        def spmd(params_in, micros_in, micros_lb, rng):
            stage = jax.lax.axis_index(PIPE_AXIS)
            local = {}
            for k, v in params_in.items():
                # restore compute dtype; stacked slices arrive as [1, k, ...]
                v = jax.tree.map(lambda l, d: l.astype(d), v,
                                 param_dtypes[k])
                local[k] = (jax.tree.map(lambda a: jnp.squeeze(a, 0), v)
                            if k in plan else v)
            loss_params = _ReplicatedParamsView(pm.replicated_view(local))

            def branch(s):
                start, stop = parts[s]

                def stage_fwd(view, x, mrng):
                    return pm.forward_range(view, x, mrng, start, stop,
                                            train=train)
                if pm.stage_remat:
                    # store only stage-boundary activations per tick; the
                    # stage body recomputes in backward (1F1B's memory
                    # bound, remat-style)
                    stage_fwd = jax.checkpoint(stage_fwd)

                def run(buf, m_idx):
                    mrng = jax.random.fold_in(rng, m_idx)
                    if s == 0:
                        x = jax.tree.map(lambda a: a[m_idx], micros_in)
                    else:
                        x = buf
                    view = pm.stage_view(local, s, local=True)
                    return stage_fwd(view, x, mrng)
                return run

            branches = None if uni is not None else [
                branch(s) for s in range(S)]

            if uni is not None:
                # Uniform-stage body — NO lax.switch over stages, so the
                # nested seq-axis collectives inside the stacked layers
                # (ring attention ppermutes) execute in the same order on
                # every pipe rank.  The per-stage differences that remain
                # are collective-free: the stage-0 prefix (embedding) runs
                # under a cond, the row's global layer index (for the
                # per-layer RNG fold, matching apply_layer's
                # fold_in(rng, i)) is a traced table lookup, and the
                # last-stage suffix runs inside the loss cond below.
                uname, rows_tbl, prefix, suffix = uni
                rows = jnp.asarray(rows_tbl)
                layers = pm.build_layers()

                from ..parallel.sequence import SEQ_AXIS as _SEQ
                from jax.sharding import AxisType as _AT
                _seq_explicit = (
                    dict(zip(mesh.axis_names,
                             getattr(mesh, "axis_types", ()))).get(_SEQ)
                    == _AT.Explicit)

                def tag_seq(v):
                    # Pin the boundary layout (batch over 'data', seq over
                    # 'seq') at every producer: the embed cond's branches
                    # and the scan carry must already agree with the
                    # stacked blocks' layout, otherwise GSPMD inserts a
                    # resharding collective-permute INSIDE a divergent
                    # branch — which only some pipe ranks execute, and the
                    # collective rendezvous hangs.  Under EXPLICIT axes
                    # the same op also reconciles the @seq sharding types
                    # across cond branches.
                    nd = getattr(v, "ndim", 0)
                    if nd < 2:
                        return v
                    spec = P(*([DATA_AXIS, _SEQ] + [None] * (nd - 2)))
                    if _seq_explicit:
                        return jax.sharding.reshard(v, spec)
                    # constraints inside the manual region must be built
                    # on the ABSTRACT mesh (pipe marked Manual), not the
                    # concrete one
                    return jax.lax.with_sharding_constraint(
                        v, NamedSharding(jax.sharding.get_abstract_mesh(),
                                         spec))

                def stacked_rows(local_tree, x, mrng):
                    st = local_tree[uname]
                    for j in range(rows_tbl.shape[1]):
                        lp = jax.tree.map(lambda a, j=j: a[j], st)
                        lrng = jax.random.fold_in(mrng, rows[stage, j])
                        # stage-0's row-j layer instance serves every rank:
                        # rows stack only when layer fingerprints match,
                        # and a stacked layer's apply must not depend on
                        # its construction index
                        x = layers[int(rows_tbl[0][j])].apply(
                            lp, x, lrng, train=train)
                    return x
                if pm.stage_remat:
                    stacked_rows = jax.checkpoint(stacked_rows)

                def run_uniform(buf, m_idx):
                    mrng = jax.random.fold_in(rng, m_idx)
                    # The stage-0 prefix (embedding) runs UNCONDITIONALLY
                    # on every rank, then an elementwise select keeps
                    # stage 0's result.  Hiding it in a lax.cond invites
                    # GSPMD to insert resharding collective-permutes
                    # inside the divergent branch (observed on the wpe
                    # slice and its pad transpose) — executed by only
                    # some pipe ranks, deadlocking the rendezvous.  The
                    # wasted prefix FLOPs on non-0 stages are a tiny
                    # fraction of a stage body.
                    x = jax.tree.map(lambda a: a[m_idx], micros_in)
                    for i in prefix:
                        x = pm.apply_layer(i, local, x, mrng, train=train)
                    x = jnp.where(stage == 0, tag_seq(x), buf)
                    return stacked_rows(local, x, mrng)

            def tick(carry, t):
                buf, loss_sum = carry
                m = t - stage
                m_idx = jnp.clip(m, 0, M - 1)
                active = (m >= 0) & (m < M)
                if uni is not None:
                    y = tag_seq(run_uniform(buf, m_idx))
                else:
                    y = jax.lax.switch(stage, branches, buf, m_idx)
                # Fill/drain ticks run the stage on recycled activations.
                # Zero their outputs: otherwise an inf/NaN produced from
                # garbage input survives into the scan's backward pass
                # (0 * inf = NaN) and poisons the real gradients.  With
                # outputs zeroed, inactive inputs are always zeros (buf0 is
                # zeros and the ring only carries masked values).
                y = jax.tree.map(
                    lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)

                def loss_branch(_):
                    z = y
                    lb = jax.tree.map(lambda a: a[m_idx], micros_lb)
                    if uni is not None:
                        # last-stage suffix (e.g. final norm) — resident
                        # replicated layers, collective-free by contract
                        mrng = jax.random.fold_in(rng, m_idx)
                        for i in uni[3]:
                            z = pm.apply_layer(i, local, z, mrng,
                                               train=train)
                        # labels meet the seq-sharded hidden state
                        lb = jax.tree.map(tag_seq, lb)
                    if self._loss_takes_params:
                        # the loss head is traced on EVERY stage (lax.cond)
                        # — it may only read pipe-replicated params
                        return pm.loss_fn(loss_params, z,
                                          lb).astype(jnp.float32)
                    return pm.loss_fn(z, lb).astype(jnp.float32)

                lm = jax.lax.cond(active & (stage == S - 1), loss_branch,
                                  lambda _: jnp.asarray(0.0, jnp.float32),
                                  None)
                # forward handoff ring: stage s -> s+1 (no wraparound; the
                # last stage's output is consumed by the loss above)
                buf_next = jax.lax.ppermute(
                    y, PIPE_AXIS, perm=[(i, i + 1) for i in range(S - 1)])
                return (buf_next, loss_sum + lm), None

            buf0 = jnp.zeros(boundary.shape, boundary.dtype)
            if uni is not None:
                buf0 = tag_seq(buf0)
            (_, loss_sum), _ = jax.lax.scan(
                tick, (buf0, jnp.asarray(0.0, jnp.float32)),
                jnp.arange(M + S - 1))
            # only the last stage accumulated loss; share it
            return jax.lax.psum(loss_sum, PIPE_AXIS) / M

        sm = jax.shard_map(
            spmd, mesh=mesh,
            in_specs=(param_in_specs, P(), P(), P()),
            out_specs=P(),
            axis_names={PIPE_AXIS},
            check_vma=False)
        return sm(place(params), micros_in, micros_lb, rng)

    # -----------------------------------------------------------------
    # 1F1B: hand-scheduled backward inside the same compiled scan.
    #
    # The GPipe path above differentiates the whole fill/drain scan with
    # AD, which stores one stage-boundary activation per tick — O(M) live
    # boundaries.  Here the backward is part of the schedule itself (the
    # reference's TrainSchedule, runtime/pipe/schedule.py:189-247): each
    # stage alternates Forward and Backward ticks, so a micro-batch's
    # boundary activation is freed after at most 2(S-s) ticks and the
    # activation store is a ring of min(S, M) slots — the compiled
    # analogue of the reference's buffer bound
    # min(stages - stage_id + 1, micro_batches) (schedule.py:243-247).
    #
    # Timetable (T = 2(M+S-1) ticks, the reference TrainSchedule's step
    # count): stage s runs F(m) at tick 2m + s and B(m) at tick
    # 2m + 2S - 1 - s.  F-ticks have parity s, B-ticks parity s+1, so
    # every tick is exactly one of the two; the F handoff (ppermute
    # s->s+1) and the cotangent handoff (ppermute s+1->s) both run every
    # tick, carrying zeros on the off-parity.  The per-stage backward is
    # jax.vjp of the stage body (recomputing its forward — the same
    # whole-stage remat granularity the GPipe path uses), seeded at the
    # last stage by grad(loss * scale / M).
    # -----------------------------------------------------------------
    def value_and_grads(self, params, batch, rng, loss_scale):
        """(scaled mean loss, grads) with 1F1B activation liveness.

        ``params`` arrive in compute dtype; gradients accumulate in fp32
        in the scan carry (the per-tick vjp cotangents are compute-dtype,
        exactly like the AD path's per-tick transposes).  Returned grads
        are d(loss_scale * mean_loss)/dparams, matching what
        ``jax.grad`` of the scaled GPipe loss would produce."""
        pm, S, M = self.pm, self.num_stages, self.num_micro
        mesh = self.mesh
        plan = pm.stack_plan()
        micros_in, micros_lb, boundary, parts = self._prepare(
            params, batch, rng)
        D = min(S, M)                 # ring depth: max in-flight micros
        T = 2 * (M + S - 1)

        param_in_specs = {
            k: jax.tree.map(lambda _: P(PIPE_AXIS) if k in plan else P(),
                            v)
            for k, v in params.items()}

        def place(tree):
            out = {}
            for k, v in tree.items():
                spec = P(PIPE_AXIS) if k in plan else P()
                out[k] = jax.tree.map(
                    lambda l, spec=spec: jax.lax.with_sharding_constraint(
                        l, NamedSharding(mesh, spec)), v)
            return out

        def spmd(params_in, micros_in, micros_lb, rng, scale):
            stage = jax.lax.axis_index(PIPE_AXIS)
            local = {k: (jax.tree.map(lambda a: jnp.squeeze(a, 0), v)
                         if k in plan else v)
                     for k, v in params_in.items()}

            def stage_fwd(s, tree, x, mrng):
                start, stop = parts[s]
                view = pm.stage_view(tree, s, local=True)
                return pm.forward_range(view, x, mrng, start, stop,
                                        train=True)

            # ---- forward tick ----
            def f_branch(carry, t):
                buf_f, buf_ct, ring, gacc, loss_sum = carry
                m = (t - stage) // 2
                m_idx = jnp.clip(m, 0, M - 1)
                active = (m >= 0) & (m < M)

                def fb(s):
                    def run(buf):
                        mrng = jax.random.fold_in(rng, m_idx)
                        x = (jax.tree.map(lambda a: a[m_idx], micros_in)
                             if s == 0 else buf)
                        return stage_fwd(s, local, x, mrng)
                    return run

                y = jax.lax.switch(stage, [fb(s) for s in range(S)], buf_f)
                y = jnp.where(active, y, jnp.zeros_like(y))
                # stash this micro's stage INPUT for the backward tick
                # (stage 0 re-reads micros_in instead; its slot is unused).
                # dynamic_update_slice, NOT .at[].set: a traced-index
                # scatter trips a GSPMD check when partitioning mixed
                # manual(pipe)/auto(model,data) collectives.
                slot = m_idx % D
                cur = jax.lax.dynamic_index_in_dim(ring, slot, 0,
                                                   keepdims=False)
                ring = jax.lax.dynamic_update_slice_in_dim(
                    ring, jnp.where(active, buf_f, cur)[None], slot, 0)
                return y, jnp.zeros(boundary.shape, boundary.dtype), \
                    ring, gacc, loss_sum

            # ---- backward tick ----
            def b_branch(carry, t):
                buf_f, buf_ct, ring, gacc, loss_sum = carry
                m = (t - (2 * S - 1 - stage)) // 2
                m_idx = jnp.clip(m, 0, M - 1)
                active = (m >= 0) & (m < M)

                def bb(s):
                    # stage 0 consumes raw batch inputs (possibly integer
                    # tokens) — never differentiated w.r.t. x; its input
                    # cotangent has no consumer anyway.
                    wrt_x = s > 0

                    def run(ct_in):
                        mrng = jax.random.fold_in(rng, m_idx)
                        x = (jax.tree.map(lambda a: a[m_idx], micros_in)
                             if s == 0 else jax.lax.dynamic_index_in_dim(
                                 ring, m_idx % D, 0, keepdims=False))
                        zero_gx = jnp.zeros(boundary.shape, boundary.dtype)

                        def compute(_):
                            if s == S - 1:
                                def head(tree, xx):
                                    yy = stage_fwd(s, tree, xx, mrng)
                                    lb = jax.tree.map(
                                        lambda a: a[m_idx], micros_lb)
                                    if self._loss_takes_params:
                                        lp = _ReplicatedParamsView(
                                            pm.replicated_view(tree))
                                        lv = pm.loss_fn(lp, yy, lb)
                                    else:
                                        lv = pm.loss_fn(yy, lb)
                                    return (lv.astype(jnp.float32)
                                            * (scale / M))
                                if wrt_x:
                                    lv, (gl, gx) = jax.value_and_grad(
                                        head, argnums=(0, 1))(local, x)
                                else:
                                    lv, gl = jax.value_and_grad(head)(
                                        local, x)
                                    gx = zero_gx
                                return lv, gl, gx.astype(boundary.dtype)
                            if wrt_x:
                                _, vjp = jax.vjp(
                                    lambda tree, xx: stage_fwd(
                                        s, tree, xx, mrng), local, x)
                                gl, gx = vjp(ct_in)
                            else:
                                _, vjp = jax.vjp(
                                    lambda tree: stage_fwd(
                                        s, tree, x, mrng), local)
                                (gl,) = vjp(ct_in)
                                gx = zero_gx
                            return (jnp.asarray(0.0, jnp.float32), gl,
                                    gx.astype(boundary.dtype))

                        def skip(_):
                            return (jnp.asarray(0.0, jnp.float32),
                                    jax.tree.map(jnp.zeros_like, local),
                                    zero_gx)
                        return jax.lax.cond(active, compute, skip, None)
                    return run

                lv, gl, gx = jax.lax.switch(
                    stage, [bb(s) for s in range(S)], buf_ct)
                gacc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), gacc, gl)
                return jnp.zeros(boundary.shape, boundary.dtype), \
                    gx, ring, gacc, loss_sum + lv

            def tick(carry, t):
                is_f = ((t - stage) % 2) == 0
                y_out, ct_out, ring, gacc, loss_sum = jax.lax.cond(
                    is_f, f_branch, b_branch, carry, t)
                buf_f = jax.lax.ppermute(
                    y_out, PIPE_AXIS,
                    perm=[(i, i + 1) for i in range(S - 1)])
                buf_ct = jax.lax.ppermute(
                    ct_out, PIPE_AXIS,
                    perm=[(i + 1, i) for i in range(S - 1)])
                return (buf_f, buf_ct, ring, gacc, loss_sum), None

            buf0 = jnp.zeros(boundary.shape, boundary.dtype)
            ring0 = jnp.zeros((D,) + tuple(boundary.shape), boundary.dtype)
            gacc0 = jax.tree.map(
                lambda l: jnp.zeros(l.shape, jnp.float32), local)
            carry0 = (buf0, jnp.zeros(boundary.shape, boundary.dtype),
                      ring0, gacc0, jnp.asarray(0.0, jnp.float32))
            (_, _, _, gacc, loss_sum), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T))

            loss = jax.lax.psum(loss_sum, PIPE_AXIS)
            grads = {}
            for k, v in gacc.items():
                if k in plan:
                    # stage-local grads: restore the leading pipe dim
                    grads[k] = jax.tree.map(
                        lambda a: jnp.expand_dims(a, 0), v)
                else:
                    # pipe-replicated params: sum stage contributions
                    grads[k] = jax.tree.map(
                        lambda a: jax.lax.psum(a, PIPE_AXIS), v)
            return loss, grads

        sm = jax.shard_map(
            spmd, mesh=mesh,
            in_specs=(param_in_specs, P(), P(), P(), P()),
            # grads mirror the param placement exactly (stacked keys local
            # to their pipe rank, the rest replicated-after-psum)
            out_specs=(P(), param_in_specs),
            axis_names={PIPE_AXIS},
            check_vma=False)
        return sm(place(params), micros_in, micros_lb, rng,
                  jnp.asarray(loss_scale, jnp.float32))

    # -----------------------------------------------------------------
    # Uniform-tick 1F1B: the schedule that carries seq-axis collectives.
    #
    # The cond-based 1F1B above cannot compose with sequence parallelism:
    # its F and B cond branches lower to DISTINCT collective instances,
    # and at any tick different pipe ranks take different branches, so a
    # seq collective's rendezvous never assembles (the empirical deadlock
    # behind the old gpipe fallback).  Here every tick runs BOTH units on
    # every rank, masked by activity:
    #
    #   F(m_f = t - s):        the uniform stage forward (gpipe's body);
    #   B(m_b = t - (2S-1-s)): jax.vjp of the SAME uniform body at the
    #                          ring-stashed boundary input, seeded at the
    #                          last stage by the collective-free loss
    #                          head's gradient.
    #
    # Timetable: F(m) at tick m+s, B(m) at tick m+2S-1-s; T = M+2S-1
    # ticks.  Dependencies hold tick-to-tick: the F handoff (s -> s+1)
    # and the cotangent handoff (s+1 -> s) each cross exactly one tick,
    # and the last stage's B(m) at tick m+S follows its F(m) at m+S-1.
    # The collective footprint per tick is IDENTICAL on every rank —
    # one uniform forward + one uniform vjp — so the seq ppermutes and
    # their transposes rendezvous across the whole mesh.
    #
    # Cost model vs the alternatives: every tick pays fwd + (refwd+bwd)
    # ~ 3 units x (M+2S-1) ticks — the same total as gpipe-with-remat's
    # 3(M+S-1) for M >> S — while activation liveness stays a ring of
    # min(2S-1, M) boundary slots instead of gpipe's O(M) (stage s holds
    # a micro's input for 2(S-s)-1 ticks).  The reference has no
    # analogue: its interpreter dispatches per-rank instruction lists
    # (runtime/pipe/schedule.py:189-247) that SPMD cannot express
    # divergently when collectives ride inside the stage body.
    # -----------------------------------------------------------------
    def value_and_grads_uniform(self, params, batch, rng, loss_scale):
        """(scaled mean loss, grads), uniform-tick 1F1B.  Contract
        matches ``value_and_grads``: grads of d(loss_scale * mean_loss)
        accumulated in fp32; params arrive in compute dtype."""
        pm, S, M = self.pm, self.num_stages, self.num_micro
        mesh = self.mesh
        plan = pm.stack_plan()
        micros_in, micros_lb, boundary, parts = self._prepare(
            params, batch, rng)
        uni = self._uniform_stack_info()
        if uni is None:
            raise NotImplementedError(
                "the uniform-tick 1F1B schedule needs a uniformly stacked "
                "PipelineModule (equal stacked rows per stage, non-stacked "
                "layers only as a stage-0 prefix / last-stage suffix); "
                "this module's partition is not uniform — use gpipe")
        uname, rows_tbl, prefix, suffix = uni
        D = min(2 * S - 1, M)
        T = M + 2 * S - 1

        from jax.sharding import AxisType as _AT
        from ..parallel.sequence import SEQ_AXIS as _SEQ
        _seq_explicit = (
            dict(zip(mesh.axis_names,
                     getattr(mesh, "axis_types", ()))).get(_SEQ)
            == _AT.Explicit)

        param_in_specs = {
            k: jax.tree.map(lambda _: P(PIPE_AXIS) if k in plan else P(),
                            v)
            for k, v in params.items()}

        def place(tree):
            out = {}
            for k, v in tree.items():
                spec = P(PIPE_AXIS) if k in plan else P()
                out[k] = jax.tree.map(
                    lambda l, spec=spec: jax.lax.with_sharding_constraint(
                        l, NamedSharding(mesh, spec)), v)
            return out

        def spmd(params_in, micros_in, micros_lb, rng, scale):
            stage = jax.lax.axis_index(PIPE_AXIS)
            local = {k: (jax.tree.map(lambda a: jnp.squeeze(a, 0), v)
                         if k in plan else v)
                     for k, v in params_in.items()}
            rows = jnp.asarray(rows_tbl)
            layers = pm.build_layers()

            def tag_seq(v):
                # see loss_fn's tag_seq: pin the boundary layout at every
                # producer so no resharding lands inside a divergent cond
                nd = getattr(v, "ndim", 0)
                if nd < 2:
                    return v
                spec = P(*([DATA_AXIS, _SEQ] + [None] * (nd - 2)))
                if _seq_explicit:
                    return jax.sharding.reshard(v, spec)
                return jax.lax.with_sharding_constraint(
                    v, NamedSharding(jax.sharding.get_abstract_mesh(),
                                     spec))

            def stacked_rows(local_tree, x, mrng):
                st = local_tree[uname]
                for j in range(rows_tbl.shape[1]):
                    lp = jax.tree.map(lambda a, j=j: a[j], st)
                    lrng = jax.random.fold_in(mrng, rows[stage, j])
                    x = layers[int(rows_tbl[0][j])].apply(
                        lp, x, lrng, train=True)
                return x
            if pm.stage_remat:
                stacked_rows = jax.checkpoint(stacked_rows)

            def stage_fn(local_tree, buf, m_idx):
                """The uniform stage body (prefix + select + stacked
                rows) — the SAME function the F unit runs forward and
                the B unit vjps, so their collective footprints match."""
                mrng = jax.random.fold_in(rng, m_idx)
                x = jax.tree.map(lambda a: a[m_idx], micros_in)
                for i in prefix:
                    x = pm.apply_layer(i, local_tree, x, mrng, train=True)
                x = jnp.where(stage == 0, tag_seq(x), buf)
                return tag_seq(stacked_rows(local_tree, x, mrng))

            def head_fn(local_tree, y, m_idx):
                """Last-stage suffix + loss — collective-free by the
                uniform contract, so it may live inside a cond."""
                mrng = jax.random.fold_in(rng, m_idx)
                z = y
                for i in suffix:
                    z = pm.apply_layer(i, local_tree, z, mrng, train=True)
                lb = jax.tree.map(lambda a: tag_seq(a[m_idx]), micros_lb)
                if self._loss_takes_params:
                    lp = _ReplicatedParamsView(
                        pm.replicated_view(local_tree))
                    lv = pm.loss_fn(lp, z, lb)
                else:
                    lv = pm.loss_fn(z, lb)
                return lv.astype(jnp.float32) * (scale / M)

            def tick(carry, t):
                buf_f, buf_ct, ring, gacc, loss_sum = carry
                # B's stash read comes FIRST: when D divides 2S-1-2s the
                # F unit's write this tick lands on the very slot B(m_b)
                # needs (stashed at tick m_b+s) — read the old value
                # before overwriting
                m_b = t - (2 * S - 1 - stage)
                mb_idx = jnp.clip(m_b, 0, M - 1)
                act_b = (m_b >= 0) & (m_b < M)
                x_b = jax.lax.dynamic_index_in_dim(ring, mb_idx % D, 0,
                                                   keepdims=False)
                # ---- F unit (uniform forward) ----
                m_f = t - stage
                mf_idx = jnp.clip(m_f, 0, M - 1)
                act_f = (m_f >= 0) & (m_f < M)
                y = stage_fn(local, buf_f, mf_idx)
                y = jnp.where(act_f, y, jnp.zeros_like(y))
                slot = mf_idx % D
                cur = jax.lax.dynamic_index_in_dim(ring, slot, 0,
                                                   keepdims=False)
                ring = jax.lax.dynamic_update_slice_in_dim(
                    ring, jnp.where(act_f, buf_f, cur)[None], slot, 0)
                # ---- B unit (uniform vjp of the same body) ----
                y_b, vjp_fn = jax.vjp(
                    lambda lt, bb: stage_fn(lt, bb, mb_idx), local, x_b)

                def head_branch(_):
                    return jax.value_and_grad(
                        head_fn, argnums=(0, 1))(local, y_b, mb_idx)

                def head_skip(_):
                    return (jnp.asarray(0.0, jnp.float32),
                            (jax.tree.map(jnp.zeros_like, local),
                             jnp.zeros_like(y_b)))

                lv, (gl_h, gy) = jax.lax.cond(
                    act_b & (stage == S - 1), head_branch, head_skip, None)
                ct = jnp.where(stage == S - 1, gy.astype(buf_ct.dtype),
                               buf_ct)
                gl_s, gx = vjp_fn(ct)
                gacc = jax.tree.map(
                    lambda acc, g1, g2: acc + jnp.where(
                        act_b, (g1.astype(jnp.float32)
                                + g2.astype(jnp.float32)),
                        jnp.zeros_like(acc)),
                    gacc, gl_s, gl_h)
                gx = jnp.where(act_b, gx, jnp.zeros_like(gx))
                # ---- handoffs (every tick, schedule-invariant) ----
                buf_f2 = jax.lax.ppermute(
                    y, PIPE_AXIS,
                    perm=[(i, i + 1) for i in range(S - 1)])
                buf_ct2 = jax.lax.ppermute(
                    gx.astype(boundary.dtype), PIPE_AXIS,
                    perm=[(i + 1, i) for i in range(S - 1)])
                return (buf_f2, buf_ct2, ring, gacc, loss_sum + lv), None

            buf0 = tag_seq(jnp.zeros(boundary.shape, boundary.dtype))
            ring0 = jnp.zeros((D,) + tuple(boundary.shape), boundary.dtype)
            gacc0 = jax.tree.map(
                lambda l: jnp.zeros(l.shape, jnp.float32), local)
            carry0 = (buf0, tag_seq(jnp.zeros(boundary.shape,
                                              boundary.dtype)),
                      ring0, gacc0, jnp.asarray(0.0, jnp.float32))
            (_, _, _, gacc, loss_sum), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T))

            loss = jax.lax.psum(loss_sum, PIPE_AXIS)
            grads = {}
            for k, v in gacc.items():
                if k in plan:
                    grads[k] = jax.tree.map(
                        lambda a: jnp.expand_dims(a, 0), v)
                else:
                    grads[k] = jax.tree.map(
                        lambda a: jax.lax.psum(a, PIPE_AXIS), v)
            return loss, grads

        sm = jax.shard_map(
            spmd, mesh=mesh,
            in_specs=(param_in_specs, P(), P(), P(), P()),
            out_specs=(P(), param_in_specs),
            axis_names={PIPE_AXIS},
            check_vma=False)
        return sm(place(params), micros_in, micros_lb, rng,
                  jnp.asarray(loss_scale, jnp.float32))


class PipelineEngine(DeepSpeedEngine):
    """DeepSpeedEngine whose step runs the compiled pipeline.

    (reference: deepspeed/runtime/pipe/engine.py:45 — also a subclass of the
    core engine, inheriting optimizer/precision/checkpoint machinery.)
    """

    def __init__(self, model: PipelineModule, config, mesh,
                 optimizer=None, lr_schedule=None, training_data=None,
                 collate_fn=None, seed: int = 0, params=None,
                 schedule: Optional[str] = None):
        if not isinstance(model, PipelineModule):
            raise TypeError("PipelineEngine requires a PipelineModule")
        if schedule is None:
            # config key pipeline.schedule (default "1f1b") — reachable
            # from the initialize() entry point, so users can fall back
            # to "gpipe" without constructing the engine directly
            schedule = getattr(
                getattr(config, "pipeline_config", None), "schedule",
                "1f1b")
        if schedule not in ("1f1b", "1f1b_uniform", "gpipe"):
            raise ValueError(
                f"pipeline schedule must be '1f1b', '1f1b_uniform', or "
                f"'gpipe', got {schedule!r}")
        from ..parallel.sequence import SEQ_AXIS
        if schedule == "1f1b" and dict(mesh.shape).get(SEQ_AXIS, 1) > 1:
            # The cond-based 1F1B stages diverge per tick (F vs B
            # parity), so seq-axis collectives inside the stage bodies
            # would execute on only some pipe ranks.  Verified
            # empirically (round 3): forcing it deadlocks at runtime —
            # the F and B cond branches lower to DISTINCT collective-
            # permute instances and each rendezvous waits forever (XLA
            # "expected 8 threads, only 4 arrived").  The uniform-tick
            # 1F1B runs BOTH units masked on every tick, making the
            # collective footprint schedule-invariant — 1F1B activation
            # liveness (a min(2S-1, M) boundary ring, not gpipe's O(M))
            # with seq collectives that rendezvous.
            log_dist(
                "pipeline: seq axis > 1 — using the uniform-tick 1F1B "
                "schedule (F+B units run masked every tick, so the seq "
                "collectives are schedule-invariant)", ranks=[0])
            schedule = "1f1b_uniform"
        pp = mesh_axis_size(mesh, PIPE_AXIS)
        if pp != model.num_stages:
            raise ValueError(
                f"mesh pipe axis ({pp}) != PipelineModule.num_stages "
                f"({model.num_stages})")
        if getattr(config.zero_config, "cpu_offload", False):
            # the reference never composed these either: its offload
            # rides the ZeRO-2 engine, which its pipeline engine bypasses
            # (reference runtime/pipe/engine.py drives fwd/bwd itself).
            # Here the offload tiers flatten the master into dp-sharded
            # pieces, a layout the pipe-sharded stacked params do not
            # fit.  Capacity for big models: pipeline stages already
            # hold 1/S of the params; compose with ZeRO-3 for the
            # optimizer state, or use the plain engine's offload +
            # param_streaming stack.
            raise ValueError(
                "cpu_offload × pipeline parallelism is not supported: "
                "use ZeRO-3 with the pipeline engine (stage-local + "
                "data-sharded state), or the plain engine's offload/"
                "param_streaming capacity stack")
        self.pipeline_module = model
        self.schedule = schedule
        num_micro = config.gradient_accumulation_steps
        adapter = _PipelinedTrainModule(model, mesh, num_micro)
        super().__init__(adapter, config, mesh=mesh, optimizer=optimizer,
                         lr_schedule=lr_schedule, params=params,
                         training_data=training_data, collate_fn=collate_fn,
                         seed=seed)
        self.num_stages = model.num_stages
        self.micro_batches = num_micro
        log_dist(
            f"PipelineEngine: stages={self.num_stages} "
            f"micro_batches={self.micro_batches} parts={model.parts} "
            f"schedule={schedule}",
            ranks=[0])

    def _scan_scaled_grads(self, params, batch, scaler, step_rng,
                           cast: bool = True, constrain: bool = True):
        """Under the 1F1B schedule the backward is hand-scheduled inside
        the pipelined program (value_and_grads) instead of produced by AD
        over the GPipe forward — activation liveness drops from O(M)
        stage-boundary buffers to a ring of min(S, M) (the reference
        TrainSchedule's buffer bound, runtime/pipe/schedule.py:243-247).
        Same contract as the base implementation: fp32 mean grads and the
        per-scan-iteration scaled losses."""
        if self.schedule not in ("1f1b", "1f1b_uniform"):
            return super()._scan_scaled_grads(
                params, batch, scaler, step_rng, cast=cast,
                constrain=constrain)
        from ..runtime import precision
        from ..runtime.zero import constrain_grads
        pp = (precision.cast_to_compute(params, self.compute_dtype)
              if cast else params)
        # the engine presents the batch as [1, local, ...] (its outer
        # grad-accum scan dim); the pipeline consumes all micros at once
        mb = jax.tree.map(lambda x: x[0], batch)
        rng = jax.random.fold_in(step_rng, 0)
        vag = (self.module.value_and_grads_uniform
               if self.schedule == "1f1b_uniform"
               else self.module.value_and_grads)
        scaled_loss, grads = vag(pp, mb, rng, scaler.loss_scale)
        if constrain:
            grads = constrain_grads(grads, self.zero_plan)
        inv = (1.0 / scaler.loss_scale).astype(jnp.float32)
        grads = jax.tree.map(lambda g: g * inv, grads)
        return grads, scaled_loss.reshape(1)

    def _batch_leading_reshape(self, x):
        """The pipeline consumes all micro-batches in one program — no outer
        grad-accum scan.  Present the batch as [1, local, ...] (the engine's
        scan dim) sharded over ``data`` on the sample dim; multi-host feeds
        per-process slices like the base engine."""
        import jax as _jax
        nproc = _jax.process_count()
        expect = self.train_batch_size // nproc
        if x.shape[0] != expect:
            raise ValueError(
                f"batch dim {x.shape[0]} != train_batch_size"
                f"{'/process_count' if nproc > 1 else ''} {expect}")
        return x.reshape((1,) + x.shape)

    @property
    def _scan_grad_acc(self) -> int:
        return 1  # all micro-batches live inside the pipelined program

    def eval_batch(self, batch=None, data_iter=None):
        """Forward-only pipelined evaluation (reference
        PipelineEngine.eval_batch, pipe/engine.py:305-363, which executes
        the InferenceSchedule).  Here the same compiled fill/drain scan
        runs with ``train=False`` — no backward is taken, so XLA compiles a
        forward-only program: the InferenceSchedule is the AD-less special
        case of the train program rather than a second schedule.  The batch
        is split into the engine's micro-batches exactly like training
        (reference :329-335 builds the same micro-batch iterator)."""
        if batch is None:
            if data_iter is None:
                raise ValueError(
                    "eval_batch needs a batch or a data_iter; it does not "
                    "fall back to the training iterator (that would consume "
                    "and advance the training data stream)")
            batch = next(data_iter)
        if isinstance(batch, DevicePlacedBatch):
            # a prefetched eval batch (engine.prefetch(..., for_eval=True))
            # carries the already-converted tree; unwrap it so the
            # divisibility check below sees the leaves, not the tag
            if batch.kind != "eval":
                raise ValueError(
                    f"eval_batch received a {batch.kind!r}-placed batch; "
                    "build the prefetcher with engine.prefetch(it, "
                    "for_eval=True)")
            batch = batch.tree

        def check(x):
            x = np.asarray(x)
            if x.shape[0] % self.micro_batches != 0:
                raise ValueError(
                    f"eval batch dim {x.shape[0]} must be divisible by "
                    f"micro_batches ({self.micro_batches})")
            return x
        batch = jax.tree.map(check, batch)
        return super().eval_batch(batch)

    def forward(self, batch):
        raise NotImplementedError(
            "the forward/backward/step facade is not supported on the "
            "pipeline engine — use train_batch/eval_batch (reference "
            "parity: those are the only entries there too, "
            "pipe/engine.py:229,305)")

    __call__ = forward
