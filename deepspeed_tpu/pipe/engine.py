"""Pipeline-parallel engine — the whole schedule in one compiled program.

The reference interprets schedules imperatively: a dispatch table maps
instructions to Python methods that issue NCCL ops and autograd calls
(reference: deepspeed/runtime/pipe/engine.py:1131-1157, p2p pair-group
broadcasts at runtime/pipe/p2p.py:31-55, shape-metadata handshake at
pipe/engine.py:653-764).  On TPU the entire pipelined training step is ONE
jit program (SURVEY.md §7 "hard parts" #3, option (b)):

  - ``shard_map`` over the ``pipe`` mesh axis, manual only on that axis
    (data/model stay under GSPMD, so ZeRO + tensor parallelism compose);
  - a ``lax.scan`` over M + S - 1 ticks; at each tick every stage runs its
    layer range (``lax.switch`` on ``axis_index('pipe')`` — heterogeneous
    stages supported, only stage-BOUNDARY activations must share a shape);
  - activation handoff is one ``ppermute`` per tick (static shapes: the
    reference's meta handshake has no equivalent here);
  - the backward schedule is not written at all: differentiating the scan
    transposes every ppermute and replays ticks in reverse — the fill/drain
    structure the reference hand-codes in TrainSchedule falls out of AD;
  - loss is computed on the last stage under ``lax.cond`` and shared via
    ``psum`` (reference _aggregate_total_loss, pipe/engine.py:373-403).

Gradient accumulation IS pipeline micro-batching here (as in the
reference's train_batch contract, pipe/engine.py:229-303): the engine
consumes ``gradient_accumulation_steps`` micro-batches per step, all live
in the pipeline at once.

Tied layers (e.g. embedding/LM-head): tied params live once in the param
tree; every stage's branch reads them, so AD sums their gradient
contributions across stages — replacing the tied-weight comm groups and
explicit allreduce (reference: runtime/pipe/module.py:405-474).

Parameter placement is STAGE-LOCAL (reference materializes only each
stage's own layers: runtime/pipe/module.py:197-249): homogeneous layers
are stacked into [num_stages, k, ...] leaves sharded over ``pipe``
(see PipelineModule.stack_plan), enter the shard_map with in_spec
``P('pipe')`` — so their gradient transpose is local (no psum over pipe,
no fp32 all-stage replica) and each chip stores ≈ total/num_stages param
bytes.  Only the pipe-replicated remainder (embedding/norm/tied, a small
fraction) crosses the boundary replicated in fp32.  Activation liveness is
bounded by whole-stage rematerialization per tick: the scan stores only
stage-BOUNDARY activations, the remat analogue of the reference's 1F1B
buffer bound min(stages - stage_id + 1, micro_batches)
(reference: runtime/pipe/schedule.py:243-247).

ZeRO composes on top: stages 1/2 shard master/opt-state and grads over
``data`` on the non-pipe dims; stage 3 additionally stores compute params
data-sharded — the boundary constraint is then the per-step param
all-gather.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, PIPE_AXIS, mesh_axis_size
from ..runtime.engine import DeepSpeedEngine
from ..runtime.module import TrainModule
from ..utils.logging import log_dist
from .module import PipelineModule


class _ReplicatedParamsView(dict):
    """Params visible to a 3-ary pipeline loss head.  The loss head is
    traced on every stage (lax.cond), so it may only read pipe-replicated
    params; reading a stage-local (stacked) layer fails here with a real
    explanation instead of a bare KeyError from deep inside jit."""

    def __missing__(self, key):
        raise KeyError(
            f"pipeline loss head tried to read param {key!r}, which is "
            "stage-local (stacked over the pipe axis). A 3-ary loss head "
            "runs on every stage and may only read pipe-replicated params: "
            f"tied layers or non-stacked resident layers ({list(self)}). "
            "Make the layer a TiedLayerSpec or compute the loss inside the "
            "last stage's layers instead.")


class _PipelinedTrainModule(TrainModule):
    """Adapts a PipelineModule to the engine's TrainModule protocol; its
    loss_fn runs the full GPipe-style pipelined forward."""

    def __init__(self, pipe_module: PipelineModule, mesh, num_micro: int):
        self.pm = pipe_module
        self.mesh = mesh
        self.num_micro = num_micro
        self.num_stages = pipe_module.num_stages
        if pipe_module.loss_fn is None:
            raise ValueError("PipelineModule needs loss_fn for training")
        # loss_fn arity: (outputs, labels) or (params, outputs, labels) —
        # the 3-ary form lets the loss head read params (e.g. a tied
        # embedding projection, the reference's TiedLayerSpec LM head).
        # Count only required positional params so `def mse(o, l, eps=1e-8)`
        # stays 2-ary.
        import inspect
        try:
            sig = inspect.signature(pipe_module.loss_fn)
            nargs = sum(
                1 for p in sig.parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                and p.default is p.empty)
        except (TypeError, ValueError):
            nargs = 2
        self._loss_takes_params = nargs >= 3

    def init(self, rng):
        return self.pm.init(rng)

    def param_partition_specs(self, params):
        # replicated over pipe; tensor-parallel ('model') placement comes
        # from the layers; ZeRO composes the data axis on top
        return self.pm.param_partition_specs(params)

    # -----------------------------------------------------------------
    def _boundary_struct(self, params, inputs_micro, rng):
        """Shape/dtype of activations at each stage boundary (must agree)."""
        pm = self.pm
        structs = []
        x = inputs_micro
        for s in range(self.num_stages):
            start, stop = pm.stage_layer_range(s)
            try:
                x = jax.eval_shape(
                    lambda p, xx: pm.forward_range(p, xx, rng, start, stop,
                                                   train=True),
                    params, x)
            except Exception as e:
                raise ValueError(
                    f"pipeline stage {s} (layers [{start},{stop})) cannot "
                    f"consume the previous stage's boundary activation — "
                    f"stage boundaries must share one shape: {e}") from e
            structs.append(x)
        # Every stage output must share one shape: boundaries feed the next
        # stage AND all stage bodies are branches of one lax.switch.
        first = structs[0]
        for i, st in enumerate(structs):
            if (st.shape, st.dtype) != (first.shape, first.dtype):
                raise ValueError(
                    "pipeline stage boundaries must share one activation "
                    f"shape; stage {i} output is {st.shape}/{st.dtype} vs "
                    f"{first.shape}/{first.dtype} — adjust the partition")
        return structs[0]

    def loss_fn(self, params, batch, rng, train: bool = True):
        if not (isinstance(batch, (tuple, list)) and len(batch) == 2):
            raise ValueError(
                "pipeline batch must be a (inputs, labels) pair")
        inputs, labels = batch
        pm, S, M = self.pm, self.num_stages, self.num_micro
        mesh = self.mesh
        plan = pm.stack_plan()

        def split_micro(tree):
            def r(x):
                if x.shape[0] % M != 0:
                    raise ValueError(
                        f"batch dim {x.shape[0]} not divisible by "
                        f"micro count {M}")
                x = x.reshape((M, x.shape[0] // M) + x.shape[1:])
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, P(None, DATA_AXIS)))
            return jax.tree.map(r, tree)

        micros_in = split_micro(inputs)
        micros_lb = split_micro(labels)

        sample_in = jax.tree.map(lambda x: jax.ShapeDtypeStruct(
            x.shape[1:], x.dtype), micros_in)
        boundary = self._boundary_struct(params, sample_in, rng)
        parts = [pm.stage_layer_range(s) for s in range(S)]

        # ALL params cross the shard_map boundary in fp32 so gradient
        # accumulation across the scan's ticks happens in fp32 (the per-tick
        # bf16 cotangent is cast up by the astype transpose before the scan
        # sums it — with M micro-batches a bf16 sum would lose ~2^-8
        # relative precision and overflow earlier under fp16 loss scaling).
        # Placement differs per top-level key:
        #  - STACKED params enter sharded over ``pipe`` (in_spec P('pipe')):
        #    their transpose is LOCAL (no psum over pipe) and the fp32 copy
        #    is stage-local and transient — each chip holds total/S, not a
        #    full replica.  Dims past the stage dim are constrained
        #    replicated — under ZeRO-3 this boundary constraint IS the
        #    per-step param all-gather over ``data``.
        #  - pipe-REPLICATED params (tied/resident — small) cross fully
        #    replicated: a replicated input's transpose is a psum over
        #    ``pipe`` (a bf16 psum also trips an XLA-CPU AllReducePromotion
        #    crash on the test mesh).  The constraint keeps every collective
        #    at the shard_map boundary — a data-axis all-gather inside the
        #    last-stage-only lax.cond loss head deadlocks the pipe ppermute
        #    rendezvous otherwise.
        param_dtypes = {k: jax.tree.map(lambda l: l.dtype, v)
                        for k, v in params.items()}

        def place(tree):
            out = {}
            for k, v in tree.items():
                spec = P(PIPE_AXIS) if k in plan else P()
                out[k] = jax.tree.map(
                    lambda l, spec=spec: jax.lax.with_sharding_constraint(
                        l.astype(jnp.float32)
                        if jnp.issubdtype(l.dtype, jnp.floating) else l,
                        NamedSharding(mesh, spec)), v)
            return out

        param_in_specs = {
            k: jax.tree.map(lambda _: P(PIPE_AXIS) if k in plan else P(),
                            v)
            for k, v in params.items()}

        def spmd(params_in, micros_in, micros_lb, rng):
            stage = jax.lax.axis_index(PIPE_AXIS)
            local = {}
            for k, v in params_in.items():
                # restore compute dtype; stacked slices arrive as [1, k, ...]
                v = jax.tree.map(lambda l, d: l.astype(d), v,
                                 param_dtypes[k])
                local[k] = (jax.tree.map(lambda a: jnp.squeeze(a, 0), v)
                            if k in plan else v)
            loss_params = _ReplicatedParamsView(pm.replicated_view(local))

            def branch(s):
                start, stop = parts[s]

                def stage_fwd(view, x, mrng):
                    return pm.forward_range(view, x, mrng, start, stop,
                                            train=train)
                if pm.stage_remat:
                    # store only stage-boundary activations per tick; the
                    # stage body recomputes in backward (1F1B's memory
                    # bound, remat-style)
                    stage_fwd = jax.checkpoint(stage_fwd)

                def run(buf, m_idx):
                    mrng = jax.random.fold_in(rng, m_idx)
                    if s == 0:
                        x = jax.tree.map(lambda a: a[m_idx], micros_in)
                    else:
                        x = buf
                    view = pm.stage_view(local, s, local=True)
                    return stage_fwd(view, x, mrng)
                return run

            branches = [branch(s) for s in range(S)]

            def tick(carry, t):
                buf, loss_sum = carry
                m = t - stage
                m_idx = jnp.clip(m, 0, M - 1)
                active = (m >= 0) & (m < M)
                y = jax.lax.switch(stage, branches, buf, m_idx)
                # Fill/drain ticks run the stage on recycled activations.
                # Zero their outputs: otherwise an inf/NaN produced from
                # garbage input survives into the scan's backward pass
                # (0 * inf = NaN) and poisons the real gradients.  With
                # outputs zeroed, inactive inputs are always zeros (buf0 is
                # zeros and the ring only carries masked values).
                y = jax.tree.map(
                    lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)

                def loss_branch(_):
                    lb = jax.tree.map(lambda a: a[m_idx], micros_lb)
                    if self._loss_takes_params:
                        # the loss head is traced on EVERY stage (lax.cond)
                        # — it may only read pipe-replicated params
                        return pm.loss_fn(loss_params, y,
                                          lb).astype(jnp.float32)
                    return pm.loss_fn(y, lb).astype(jnp.float32)

                lm = jax.lax.cond(active & (stage == S - 1), loss_branch,
                                  lambda _: jnp.asarray(0.0, jnp.float32),
                                  None)
                # forward handoff ring: stage s -> s+1 (no wraparound; the
                # last stage's output is consumed by the loss above)
                buf_next = jax.lax.ppermute(
                    y, PIPE_AXIS, perm=[(i, i + 1) for i in range(S - 1)])
                return (buf_next, loss_sum + lm), None

            buf0 = jnp.zeros(boundary.shape, boundary.dtype)
            (_, loss_sum), _ = jax.lax.scan(
                tick, (buf0, jnp.asarray(0.0, jnp.float32)),
                jnp.arange(M + S - 1))
            # only the last stage accumulated loss; share it
            return jax.lax.psum(loss_sum, PIPE_AXIS) / M

        sm = jax.shard_map(
            spmd, mesh=mesh,
            in_specs=(param_in_specs, P(), P(), P()),
            out_specs=P(),
            axis_names={PIPE_AXIS},
            check_vma=False)
        return sm(place(params), micros_in, micros_lb, rng)


class PipelineEngine(DeepSpeedEngine):
    """DeepSpeedEngine whose step runs the compiled pipeline.

    (reference: deepspeed/runtime/pipe/engine.py:45 — also a subclass of the
    core engine, inheriting optimizer/precision/checkpoint machinery.)
    """

    def __init__(self, model: PipelineModule, config, mesh,
                 optimizer=None, lr_schedule=None, training_data=None,
                 collate_fn=None, seed: int = 0, params=None):
        if not isinstance(model, PipelineModule):
            raise TypeError("PipelineEngine requires a PipelineModule")
        pp = mesh_axis_size(mesh, PIPE_AXIS)
        if pp != model.num_stages:
            raise ValueError(
                f"mesh pipe axis ({pp}) != PipelineModule.num_stages "
                f"({model.num_stages})")
        self.pipeline_module = model
        num_micro = config.gradient_accumulation_steps
        adapter = _PipelinedTrainModule(model, mesh, num_micro)
        super().__init__(adapter, config, mesh=mesh, optimizer=optimizer,
                         lr_schedule=lr_schedule, params=params,
                         training_data=training_data, collate_fn=collate_fn,
                         seed=seed)
        self.num_stages = model.num_stages
        self.micro_batches = num_micro
        log_dist(
            f"PipelineEngine: stages={self.num_stages} "
            f"micro_batches={self.micro_batches} parts={model.parts}",
            ranks=[0])

    def _batch_leading_reshape(self, x):
        """The pipeline consumes all micro-batches in one program — no outer
        grad-accum scan.  Present the batch as [1, local, ...] (the engine's
        scan dim) sharded over ``data`` on the sample dim; multi-host feeds
        per-process slices like the base engine."""
        import jax as _jax
        nproc = _jax.process_count()
        expect = self.train_batch_size // nproc
        if x.shape[0] != expect:
            raise ValueError(
                f"batch dim {x.shape[0]} != train_batch_size"
                f"{'/process_count' if nproc > 1 else ''} {expect}")
        return x.reshape((1,) + x.shape)

    @property
    def _scan_grad_acc(self) -> int:
        return 1  # all micro-batches live inside the pipelined program

    def eval_batch(self, batch=None, data_iter=None):
        """Forward-only pipelined evaluation (reference
        PipelineEngine.eval_batch, pipe/engine.py:305-363, which executes
        the InferenceSchedule).  Here the same compiled fill/drain scan
        runs with ``train=False`` — no backward is taken, so XLA compiles a
        forward-only program: the InferenceSchedule is the AD-less special
        case of the train program rather than a second schedule.  The batch
        is split into the engine's micro-batches exactly like training
        (reference :329-335 builds the same micro-batch iterator)."""
        if batch is None:
            if data_iter is None:
                raise ValueError(
                    "eval_batch needs a batch or a data_iter; it does not "
                    "fall back to the training iterator (that would consume "
                    "and advance the training data stream)")
            batch = next(data_iter)

        def check(x):
            x = np.asarray(x)
            if x.shape[0] % self.micro_batches != 0:
                raise ValueError(
                    f"eval batch dim {x.shape[0]} must be divisible by "
                    f"micro_batches ({self.micro_batches})")
            return x
        batch = jax.tree.map(check, batch)
        return super().eval_batch(batch)

    def forward(self, batch):
        raise NotImplementedError(
            "the forward/backward/step facade is not supported on the "
            "pipeline engine — use train_batch/eval_batch (reference "
            "parity: those are the only entries there too, "
            "pipe/engine.py:229,305)")

    __call__ = forward
