"""Declarative pipeline-parallel model description.

(reference: deepspeed/runtime/pipe/module.py:23-575 — LayerSpec lazy build,
TiedLayerSpec, partitioning by parameters/uniform/type:regex.)

A PipelineModule is a *declaration*: an ordered list of layer specs plus a
partitioning policy.  Stage assignment is pure math (parallel/partition.py);
execution lives in pipe/engine.py, which runs the stages under shard_map
over the ``pipe`` mesh axis with ppermute for activations.

Layer contract (functional, TPU-style): each built layer is an object with
``init(rng) -> params`` and ``apply(params, x, rng, train) -> x``.
"""
from __future__ import annotations

import re
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from ..parallel.partition import partition_balanced, partition_uniform
from ..utils.logging import logger


class LayerSpec:
    """Lazily-built layer (reference: pipe/module.py:23-68): stores the
    constructor + args so each stage materializes only its own layers."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    @property
    def name(self) -> str:
        return getattr(self.typename, "__name__", str(self.typename))

    def __repr__(self):
        return f"LayerSpec({self.name})"


class TiedLayerSpec(LayerSpec):
    """A layer whose params are shared with every other TiedLayerSpec of the
    same key (reference: pipe/module.py:71-82).  On TPU the tied params live
    once in the param tree under ``tied/<key>`` and every tied layer reads
    them; the gradient psum over stages replaces the tied-group allreduce
    (reference: pipe/module.py:405-418)."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


class PipelineModule:
    """Ordered layer list + stage partitioning.

    partition_method (reference: pipe/module.py:348-403):
      - 'uniform'          — equal layer counts
      - 'parameters'       — balance by parameter count
      - 'type:<regex>'     — balance count of layers whose class name matches
    """

    def __init__(self,
                 layers: Sequence[LayerSpec],
                 num_stages: int,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 seed_layers: bool = False,
                 base_seed: int = 1234,
                 stage_remat: Optional[bool] = None):
        self.specs: List[LayerSpec] = list(layers)
        for s in self.specs:
            if not isinstance(s, LayerSpec):
                raise TypeError(f"layers must be LayerSpec, got {type(s)}")
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        # Whole-stage rematerialization per pipeline tick (engine-consumed):
        # bounds stored activations to the stage-BOUNDARY tensors — the
        # remat analogue of the reference's 1F1B buffer bound
        # min(stages - stage_id + 1, micro_batches)
        # (reference: runtime/pipe/schedule.py:243-247).  None → on unless
        # the user asked for finer-grained checkpointing via
        # activation_checkpoint_interval.
        self.stage_remat = (stage_remat if stage_remat is not None
                            else activation_checkpoint_interval == 0)
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.parts = self._partition_layers()
        self._built_layers: Optional[List[Any]] = None
        self._stack_plan: Optional[Dict[str, List[List[int]]]] = None
        self._stack_index: Optional[Dict[int, Tuple[str, int, int]]] = None

    # ----- partitioning (pure math, testable without devices) -----
    def _count_layer_params(self, spec: LayerSpec) -> int:
        layer = spec.build()
        if hasattr(layer, "param_count"):
            return max(int(layer.param_count()), 1)
        if hasattr(layer, "init"):
            try:
                params = jax.eval_shape(
                    lambda: layer.init(jax.random.PRNGKey(0)))
                return max(sum(int(np_prod(l.shape))
                               for l in jax.tree.leaves(params)), 1)
            except Exception:
                return 1
        return 1

    def _partition_layers(self) -> List[int]:
        n = len(self.specs)
        method = self.partition_method.lower()
        if method == "uniform":
            parts = partition_uniform(n, self.num_stages)
        elif method == "parameters":
            weights = [self._count_layer_params(s) for s in self.specs]
            parts = partition_balanced(weights, self.num_stages)
        elif method.startswith("type:"):
            pat = method[len("type:"):]
            weights = [1 if re.search(pat, s.name, re.IGNORECASE) else 0
                       for s in self.specs]
            # avoid empty-weight degenerate case
            if sum(weights) == 0:
                weights = [1] * n
            parts = partition_balanced(weights, self.num_stages)
        else:
            raise ValueError(
                f"Unknown partition_method {self.partition_method!r}")
        logger.info("PipelineModule partitions: %s", parts)
        return parts

    def stage_layer_range(self, stage_id: int):
        return self.parts[stage_id], self.parts[stage_id + 1]

    # ----- build + functional forward -----
    def build_layers(self) -> List[Any]:
        if self._built_layers is None:
            self._built_layers = [s.build() for s in self.specs]
        return self._built_layers

    def tied_keys(self) -> List[str]:
        seen = []
        for s in self.specs:
            if isinstance(s, TiedLayerSpec) and s.key not in seen:
                seen.append(s.key)
        return seen

    # ----- stage-local parameter placement ---------------------------
    # The reference materializes only each stage's own layers per rank
    # (reference: runtime/pipe/module.py:197-249, partitioning :348-403) —
    # that is the memory point of pipeline parallelism.  The TPU-native
    # equivalent: layers whose param trees are structurally identical
    # across ALL stages (the homogeneous transformer blocks that dominate
    # param bytes) are STACKED into [num_stages, k, ...] leaves and
    # sharded over the ``pipe`` mesh axis, so each chip stores only its
    # own stage's slice.  Non-uniform layers (embedding, final norm, tied
    # heads) stay replicated over ``pipe`` — they are a small fraction of
    # the model and keep the design fully general.
    def _layer_param_struct(self, i: int):
        layer = self.build_layers()[i]
        if isinstance(self.specs[i], TiedLayerSpec):
            return None
        if not hasattr(layer, "init"):
            return None
        try:
            return jax.eval_shape(lambda: layer.init(jax.random.PRNGKey(0)))
        except Exception:
            return None

    def stack_plan(self) -> Dict[str, List[List[int]]]:
        """{stack_name: per-stage lists of layer indices}; a stack exists
        when every stage holds the same count >= 1 of layers with an
        identical param-tree fingerprint (structure + shapes + dtypes)."""
        if self._stack_plan is not None:
            return self._stack_plan
        plan: Dict[str, List[List[int]]] = {}
        if self.num_stages > 1:
            fps: Dict[int, tuple] = {}
            for i, spec in enumerate(self.specs):
                st = self._layer_param_struct(i)
                if st is None:
                    continue
                leaves, tdef = jax.tree.flatten(st)
                fps[i] = (spec.name, str(tdef),
                          tuple((tuple(l.shape), str(l.dtype))
                                for l in leaves))
            per_stage = []
            for s in range(self.num_stages):
                start, stop = self.stage_layer_range(s)
                d = defaultdict(list)
                for i in range(start, stop):
                    if i in fps:
                        d[fps[i]].append(i)
                per_stage.append(d)
            seen = set()
            for i in sorted(fps):
                key = fps[i]
                if key in seen:
                    continue
                seen.add(key)
                counts = [len(ps.get(key, [])) for ps in per_stage]
                if counts[0] >= 1 and all(c == counts[0] for c in counts):
                    plan[f"stack_{len(plan)}"] = [ps[key] for ps in per_stage]
        self._stack_plan = plan
        self._stack_index = {}
        for name, stages in plan.items():
            for s, idxs in enumerate(stages):
                for j, i in enumerate(idxs):
                    self._stack_index[i] = (name, s, j)
        return plan

    def stack_index(self) -> Dict[int, Tuple[str, int, int]]:
        """layer index -> (stack_name, stage, slot-within-stage)."""
        self.stack_plan()
        return self._stack_index

    def stage_view(self, params, stage: int, local: bool = False):
        """Per-stage flat view {'layer_<i>': ..., 'tied': ...} of a packed
        param tree.  ``local=False`` indexes global [S, k, ...] stacked
        leaves; ``local=True`` expects the stage's own [k, ...] slice (the
        shard_map-local view)."""
        plan = self.stack_plan()
        view = {}
        if "tied" in params:
            view["tied"] = params["tied"]
        start, stop = self.stage_layer_range(stage)
        for i in range(start, stop):
            key = f"layer_{i}"
            if key in params:
                view[key] = params[key]
        for name, stages in plan.items():
            src = params[name]
            for j, i in enumerate(stages[stage]):
                view[f"layer_{i}"] = jax.tree.map(
                    (lambda a, j=j: a[j]) if local
                    else (lambda a, j=j: a[stage, j]), src)
        return view

    def replicated_view(self, params):
        """The pipe-replicated subset (tied + resident layers) — the only
        params a 3-ary pipeline loss head may read (it is traced on every
        stage)."""
        plan = self.stack_plan()
        return {k: v for k, v in params.items() if k not in plan}

    def init(self, rng):
        """Init ALL layers' params, packed for stage-local placement:
        {'stack_<n>': stacked [S, k, ...] leaves, 'layer_<i>': resident,
        'tied': {key: ...}}.  Tied specs initialize once (first occurrence
        owns the params)."""
        import jax.numpy as jnp
        layers = self.build_layers()
        params = {}
        tied = {}
        for i, (spec, layer) in enumerate(zip(self.specs, layers)):
            lrng = (jax.random.fold_in(jax.random.PRNGKey(self.base_seed), i)
                    if self.seed_layers else jax.random.fold_in(rng, i))
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied:
                    tied[spec.key] = layer.init(lrng)
            elif hasattr(layer, "init"):
                p = layer.init(lrng)
                if p is not None:
                    params[f"layer_{i}"] = p
        for name, stages in self.stack_plan().items():
            rows = [jax.tree.map(lambda *xs: jnp.stack(xs),
                                 *[params.pop(f"layer_{i}") for i in idxs])
                    for idxs in stages]
            params[name] = jax.tree.map(lambda *xs: jnp.stack(xs), *rows)
        if tied:
            params["tied"] = tied
        return params

    def param_partition_specs(self, params):
        """Placement assembled from the layers: a layer class may define
        ``param_partition_specs()`` returning a spec tree for its own
        params (Megatron column/row splits); everything else replicates.
        Stacked leaves get ``P('pipe', None, *layer_spec)`` — the stage dim
        shards over the pipe axis (stage-local storage), tensor-parallel
        dims keep the layer's ``model``-axis placement, and ZeRO composes
        ``data`` on a remaining dim.  This is what makes pp×dp×tp (3D)
        work — the pipeline axis is manual (shard_map), the ``model`` axis
        placement declared here stays under GSPMD (reference analogue: the
        Megatron slice groups inside the pipeline grid,
        topology.py:344-364)."""
        from jax.sharding import PartitionSpec as P
        from ..parallel.mesh import PIPE_AXIS
        layers = self.build_layers()
        plan = self.stack_plan()
        specs = {}
        tied_specs = {}
        for i, (spec, layer) in enumerate(zip(self.specs, layers)):
            get = getattr(layer, "param_partition_specs", None)
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied_specs and spec.key in params.get(
                        "tied", {}):
                    tied_specs[spec.key] = (
                        get() if get is not None else jax.tree.map(
                            lambda _: P(), params["tied"][spec.key]))
            elif f"layer_{i}" in params:
                specs[f"layer_{i}"] = (
                    get() if get is not None else jax.tree.map(
                        lambda _: P(), params[f"layer_{i}"]))
        for name, stages in plan.items():
            i0 = stages[0][0]
            layer = layers[i0]
            get = getattr(layer, "param_partition_specs", None)
            struct = self._layer_param_struct(i0)
            base = (get() if get is not None
                    else jax.tree.map(lambda _: P(), struct))
            specs[name] = jax.tree.map(
                lambda p: P(PIPE_AXIS, None, *p), base,
                is_leaf=lambda x: isinstance(x, P))
        if tied_specs:
            specs["tied"] = tied_specs
        return specs

    def apply_layer(self, i: int, params, x, rng, train: bool = True):
        spec = self.specs[i]
        layer = self.build_layers()[i]
        lrng = jax.random.fold_in(rng, i)
        if isinstance(spec, TiedLayerSpec):
            p = params["tied"][spec.key]
            fn = spec.forward_fn
            if fn is not None:
                return fn(layer, p, x, lrng, train)
            return layer.apply(p, x, lrng, train)
        p = params.get(f"layer_{i}")
        if p is None and i in self.stack_index():
            # packed global tree (outside shard_map): index the stacked leaf
            name, s, j = self.stack_index()[i]
            if name in params:
                p = jax.tree.map(lambda a: a[s, j], params[name])
        if p is None:
            # stateless layer (e.g. reshape/activation)
            if hasattr(layer, "apply"):
                return layer.apply(None, x, lrng, train)
            return layer(x)
        return layer.apply(p, x, lrng, train)

    def forward_range(self, params, x, rng, start: int, stop: int,
                      train: bool = True):
        """Run layers [start, stop), with optional remat every
        activation_checkpoint_interval layers (reference:
        pipe/module.py:292-346)."""
        interval = self.activation_checkpoint_interval
        if interval and interval > 0:
            i = start
            while i < stop:
                j = min(i + interval, stop)

                def chunk(p, y, i=i, j=j):
                    for k in range(i, j):
                        y = self.apply_layer(k, p, y, rng, train)
                    return y
                x = jax.checkpoint(chunk)(params, x)
                i = j
        else:
            for i in range(start, stop):
                x = self.apply_layer(i, params, x, rng, train)
        return x

    def forward(self, params, x, rng, train: bool = True):
        return self.forward_range(params, x, rng, 0, len(self.specs), train)


def np_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out
