"""Singleton logger + rank-filtered log_dist.

(reference: deepspeed/utils/logging.py:37-60 — same surface, but "rank" is
``jax.process_index()`` instead of a torch.distributed rank.)
"""
from __future__ import annotations

import logging
import sys
from typing import Iterable, Optional

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


def _create_logger(name: str = "DeepSpeedTPU", level=logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    if not lg.handlers:
        lg.setLevel(level)
        lg.propagate = False
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(logging.Formatter(LOG_FORMAT))
        lg.addHandler(handler)
    return lg


logger = _create_logger()


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None,
             level=logging.INFO) -> None:
    """Log only on the listed process indices (-1 or None ⇒ all)."""
    rank = _process_index()
    if ranks is None or -1 in ranks or rank in ranks:
        logger.log(level, "[Rank %d] %s", rank, message)
