"""Wall-clock + throughput timers (reference: deepspeed/utils/timer.py).

``SynchronizedWallClockTimer`` — named timers whose stop() optionally
drains the async dispatch queue first (the reference cuda-synchronizes,
timer.py:26-103 there; here the sync is ``block_until_ready`` on a token
array, since jax dispatch is async the same way CUDA streams are).

``ThroughputTimer`` — samples/sec with warmup-step skip (timer.py:106-183).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax

from .logging import log_dist


def _synchronize():
    """Drain outstanding device work (≈ torch.cuda.synchronize).

    ``effects_barrier`` alone only waits for *effectful* computations; the
    per-device ``synchronize_all_activity`` is what actually drains pure
    jitted work from the execution stream.  A device without the PJRT
    sync hook must not short-circuit the loop (the old ``break`` left
    every later device undrained — unbounded timed sections); those
    devices instead get a dispatched token blocked to completion, which
    rides the per-device in-order execution stream behind any
    outstanding work."""
    try:
        jax.effects_barrier()
    except Exception:
        pass
    undrained = []
    for d in jax.local_devices():
        try:
            d.synchronize_all_activity()
        except Exception:  # backend without the PJRT sync hook
            undrained.append(d)
    for d in undrained:
        try:
            import jax.numpy as jnp
            # committed input -> the add executes ON d, queued behind any
            # outstanding programs on its (in-order) execution stream;
            # blocking on it therefore bounds the timed section
            token = jax.device_put(jnp.zeros((), jnp.float32), d)
            jax.block_until_ready(token + 1.0)
        except Exception:
            pass  # diagnostic path: never let timing kill the step


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0

    def start(self):
        assert not self.started, f"timer {self.name} already started"
        _synchronize()
        self._start = time.time()
        self.started = True

    def stop(self, reset: bool = False):
        assert self.started, f"timer {self.name} not started"
        _synchronize()
        if reset:
            self._elapsed = time.time() - self._start
        else:
            self._elapsed += time.time() - self._start
        self.started = False

    def reset(self):
        self.started = False
        self._elapsed = 0.0

    def elapsed(self, reset: bool = True) -> float:
        started = self.started
        if started:
            self.stop()
        out = self._elapsed
        if reset:
            self.reset()
        if started:
            self.start()
        return out


class SynchronizedWallClockTimer:
    """Group of named timers with a reference-style ``log``
    (timer.py:74-103)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage() -> str:
        from ..runtime.utils import memory_status
        return memory_status()

    def log(self, names: List[str], normalizer: float = 1.0,
            reset: bool = True, ranks: Optional[List[int]] = None):
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0
                parts.append(f"{name}: {ms / normalizer:.2f}")
        log_dist("time (ms) | " + " | ".join(parts), ranks=ranks or [0])


class ThroughputTimer:
    """samples/sec across steps, skipping warmup (reference
    timer.py:106-183: start_step counts, epoch bookkeeping trimmed to what
    the engine consumes)."""

    def __init__(self, batch_size: int, num_workers: int = 1,
                 start_step: int = 2, steps_per_output: int = 50,
                 monitor_memory: bool = False, logging_fn=None):
        self.batch_size = max(batch_size, 1)
        self.num_workers = num_workers
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.initialized = False
        self.local_step_count = 0
        self.total_step_count = 0
        self.counted_steps = 0      # steps actually timed (post-warmup)
        self.total_elapsed_time = 0.0
        self._start = 0.0

    def update_epoch_count(self):
        self.local_step_count = 0

    def start(self):
        self.initialized = True
        _synchronize()
        self._start = time.time()

    def stop(self, report_speed: bool = True):
        if not self.initialized:
            return
        self.local_step_count += 1
        self.total_step_count += 1
        if self.local_step_count < self.start_step:
            return  # warmup steps don't count toward throughput
        _synchronize()
        self.counted_steps += 1
        self.total_elapsed_time += time.time() - self._start
        if report_speed and \
                self.local_step_count % self.steps_per_output == 0:
            self.logging(
                f"step={self.total_step_count}, "
                f"samples/sec={self.avg_samples_per_sec():.1f}")

    def avg_samples_per_sec(self) -> float:
        # counted_steps survives update_epoch_count: the cumulative elapsed
        # time always divides by the cumulative number of timed steps
        if self.counted_steps <= 0 or self.total_elapsed_time == 0:
            return 0.0
        avg = self.total_elapsed_time / self.counted_steps
        return self.batch_size * self.num_workers / avg
