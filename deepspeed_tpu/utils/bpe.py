"""Byte-level BPE tokenizer — trainer + encoder for the real-data
convergence tier.

The reference framework trains its convergence models on pre-tokenized
WebText-style corpora produced by external Megatron tooling; this repo has
zero egress, so it carries its own small tokenizer.  Byte-level (GPT-2
style base alphabet: every byte is a token, so any UTF-8 text round-trips
exactly) with learned merges on top.

Trainer: classic pair-merge BPE over a word-frequency table, but with
*incremental* pair-count maintenance — an inverted index pair -> words
means each merge touches only the words containing that pair, so training
a 4k vocab over a multi-MB corpus takes seconds, not the O(merges x
corpus) of the naive loop.

Encoder: per-word merge-by-rank with an LRU-less dict cache (natural text
repeats words heavily, so the cache hit rate is ~95%+).

No code or vocab is taken from any existing tokenizer; the pre-tokenizer
regex is deliberately simpler than GPT-2's (letters / digits /
punctuation runs, each optionally space-prefixed).
"""
from __future__ import annotations

import json
import re
from collections import Counter
from typing import Dict, Iterable, List, Tuple

# runs of letters, digits, or other-non-space, each absorbing one
# preceding space (the leading-space convention keeps word identity
# stable mid-sentence); bare whitespace runs survive as their own words
_PRETOK = re.compile(r" ?[A-Za-z]+| ?[0-9]+| ?[^ A-Za-z0-9\s]+|\s+")


def _pretokenize(text: str) -> List[bytes]:
    return [m.group(0).encode("utf-8") for m in _PRETOK.finditer(text)]


class ByteBPE:
    """ids 0..255 are raw bytes; id 256+i is the result of ``merges[i]``."""

    def __init__(self, merges: List[Tuple[int, int]]):
        self.merges = [tuple(m) for m in merges]
        self.ranks: Dict[Tuple[int, int], int] = {
            tuple(m): i for i, m in enumerate(self.merges)}
        self._cache: Dict[bytes, Tuple[int, ...]] = {}

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    # ---------------- training ----------------

    @classmethod
    def train(cls, text: str, vocab_size: int,
              max_unique_words: int = 200_000) -> "ByteBPE":
        if vocab_size < 257:
            raise ValueError("vocab_size must exceed the 256 byte alphabet")
        word_freq = Counter(_pretokenize(text))
        if len(word_freq) > max_unique_words:
            word_freq = Counter(dict(word_freq.most_common(max_unique_words)))

        words: List[List[int]] = []   # symbol sequence per unique word
        freqs: List[int] = []
        for w, f in word_freq.items():
            words.append(list(w))
            freqs.append(f)

        pair_counts: Counter = Counter()
        pair_words: Dict[Tuple[int, int], set] = {}
        for wi, syms in enumerate(words):
            f = freqs[wi]
            for a, b in zip(syms, syms[1:]):
                pair_counts[(a, b)] += f
                pair_words.setdefault((a, b), set()).add(wi)

        merges: List[Tuple[int, int]] = []
        n_merges = vocab_size - 256
        for step in range(n_merges):
            if not pair_counts:
                break
            # deterministic tie-break on the pair ids themselves
            best = max(pair_counts.items(), key=lambda kv: (kv[1], kv[0]))[0]
            if pair_counts[best] < 2:
                break
            new_id = 256 + len(merges)
            merges.append(best)
            affected = pair_words.pop(best, set())
            pair_counts.pop(best, None)
            for wi in affected:
                syms = words[wi]
                f = freqs[wi]
                out: List[int] = []
                i = 0
                changed = False
                while i < len(syms):
                    if (i + 1 < len(syms)
                            and (syms[i], syms[i + 1]) == best):
                        # retire neighbor pair counts around the merge site
                        if out:
                            _dec(pair_counts, pair_words,
                                 (out[-1], syms[i]), f, wi)
                            _inc(pair_counts, pair_words,
                                 (out[-1], new_id), f, wi)
                        if i + 2 < len(syms):
                            _dec(pair_counts, pair_words,
                                 (syms[i + 1], syms[i + 2]), f, wi)
                            _inc(pair_counts, pair_words,
                                 (new_id, syms[i + 2]), f, wi)
                        out.append(new_id)
                        i += 2
                        changed = True
                    else:
                        out.append(syms[i])
                        i += 1
                if changed:
                    words[wi] = out
        return cls(merges)

    # ---------------- encoding ----------------

    def _bpe_word(self, word: bytes) -> Tuple[int, ...]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        syms = list(word)
        while len(syms) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(syms) - 1):
                r = self.ranks.get((syms[i], syms[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                break
            syms[best_i:best_i + 2] = [256 + best_rank]
        out = tuple(syms)
        if len(self._cache) < 1 << 20:
            self._cache[word] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for word in _pretokenize(text):
            ids.extend(self._bpe_word(word))
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        # expand merge ids back to byte sequences
        expand: Dict[int, bytes] = {}

        def to_bytes(i: int) -> bytes:
            if i < 256:
                return bytes([i])
            got = expand.get(i)
            if got is None:
                a, b = self.merges[i - 256]
                got = to_bytes(a) + to_bytes(b)
                expand[i] = got
            return got

        return b"".join(to_bytes(int(i)) for i in ids).decode(
            "utf-8", errors="replace")

    # ---------------- persistence ----------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"format": "deepspeed_tpu-bytebpe-v1",
                       "merges": [list(m) for m in self.merges]}, f)

    @classmethod
    def load(cls, path: str) -> "ByteBPE":
        with open(path) as f:
            blob = json.load(f)
        if blob.get("format") != "deepspeed_tpu-bytebpe-v1":
            raise ValueError(f"{path} is not a ByteBPE vocab file")
        return cls([tuple(m) for m in blob["merges"]])


def _inc(counts, index, pair, f, wi):
    counts[pair] += f
    index.setdefault(pair, set()).add(wi)


def _dec(counts, index, pair, f, wi):
    left = counts.get(pair)
    if left is None:
        return
    left -= f
    if left <= 0:
        counts.pop(pair, None)
        # the word may still contain the pair elsewhere; cheap to keep the
        # index entry — a stale wi is skipped naturally when re-scanned
    else:
        counts[pair] = left
