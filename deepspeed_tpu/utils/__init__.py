from .logging import logger, log_dist
