"""Training-scalar monitor (the reference's TensorBoard integration,
reference: deepspeed/runtime/engine.py:253-285,832-843,977-1030).

Writes to TensorBoard when the ``tensorboard`` package is importable
(torch ships the writer), else falls back to a JSONL event file with the
same (tag, value, step) triples — the data survives either way and the
engine code has one interface.

Lifecycle-hardened: ``flush()``/``close()`` are idempotent, a post-close
``add_scalar`` drops the point with one warning instead of dying on a
closed file handle, and the writer is a context manager.  The engine
closes its writer on shutdown (``DeepSpeedEngine.close`` + a GC
finalizer) so buffered scalars are never lost.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

from .logging import logger


class SummaryWriter:
    def __init__(self, output_path: str = "", job_name: str = "DeepSpeedJobName"):
        base = output_path or os.path.join(os.getcwd(), "runs")
        self.log_dir = os.path.join(base, job_name)
        os.makedirs(self.log_dir, exist_ok=True)
        self._tb = None
        self._jsonl = None
        self._closed = False
        self._warned_closed = False
        try:
            from torch.utils.tensorboard import SummaryWriter as TBWriter
            self._tb = TBWriter(log_dir=self.log_dir)
        except Exception:
            self._jsonl = open(
                os.path.join(self.log_dir, "events.jsonl"), "a")

    @property
    def closed(self) -> bool:
        return self._closed

    def _drop(self, tag: str) -> bool:
        """True when the writer is closed (the point is dropped)."""
        if not self._closed:
            return False
        if not self._warned_closed:
            self._warned_closed = True
            logger.warning(
                "SummaryWriter.add_scalar(%r) after close(): scalar "
                "dropped (further drops are silent)", tag)
        return True

    def add_scalar(self, tag: str, value: float, global_step: int):
        if self._drop(tag):
            return
        if self._tb is not None:
            self._tb.add_scalar(tag, value, global_step)
        else:
            self._jsonl.write(json.dumps(
                {"tag": tag, "value": float(value),
                 "step": int(global_step), "ts": time.time()}) + "\n")

    def flush(self):
        if self._closed:
            return
        if self._tb is not None:
            self._tb.flush()
        else:
            self._jsonl.flush()

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._tb is not None:
            self._tb.close()
        else:
            self._jsonl.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
