"""Training-scalar monitor (the reference's TensorBoard integration,
reference: deepspeed/runtime/engine.py:253-285,832-843,977-1030).

Writes to TensorBoard when the ``tensorboard`` package is importable
(torch ships the writer), else falls back to a JSONL event file with the
same (tag, value, step) triples — the data survives either way and the
engine code has one interface.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional


class SummaryWriter:
    def __init__(self, output_path: str = "", job_name: str = "DeepSpeedJobName"):
        base = output_path or os.path.join(os.getcwd(), "runs")
        self.log_dir = os.path.join(base, job_name)
        os.makedirs(self.log_dir, exist_ok=True)
        self._tb = None
        try:
            from torch.utils.tensorboard import SummaryWriter as TBWriter
            self._tb = TBWriter(log_dir=self.log_dir)
        except Exception:
            self._jsonl = open(
                os.path.join(self.log_dir, "events.jsonl"), "a")

    def add_scalar(self, tag: str, value: float, global_step: int):
        if self._tb is not None:
            self._tb.add_scalar(tag, value, global_step)
        else:
            self._jsonl.write(json.dumps(
                {"tag": tag, "value": float(value),
                 "step": int(global_step), "ts": time.time()}) + "\n")

    def flush(self):
        if self._tb is not None:
            self._tb.flush()
        else:
            self._jsonl.flush()

    def close(self):
        if self._tb is not None:
            self._tb.close()
        else:
            self._jsonl.close()
