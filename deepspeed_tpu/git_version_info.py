"""Build/version identity (reference: deepspeed/git_version_info.py —
version + git hash/branch + per-op compatibility map consumed by
ds_report and deepspeed.ops).

The reference bakes these at install time; here the git facts are read
lazily from the working tree when available (source checkouts are the
normal deployment for this framework) and fall back to "unknown".
"""
from __future__ import annotations

import subprocess

from .version import __version__ as version


def _git(*args: str) -> str:
    """Git facts about the checkout this package lives in — NOT whatever
    repo happens to enclose a site-packages install: the resolved toplevel
    must be an ancestor of the package directory."""
    import os
    # realpath on both sides: git prints the physical toplevel, so a
    # symlinked checkout must be compared physically too
    pkg_dir = os.path.dirname(os.path.realpath(__file__))
    try:
        top = subprocess.run(
            ("git", "-C", pkg_dir, "rev-parse", "--show-toplevel"),
            capture_output=True, text=True, timeout=5).stdout.strip()
        if not top or not (pkg_dir + os.sep).startswith(top + os.sep):
            return "unknown"
        out = subprocess.run(
            ("git", "-C", pkg_dir) + args, capture_output=True, text=True,
            timeout=5)
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def __getattr__(name):
    # Lazy: importing the package must not pay git subprocess roundtrips
    # or (worse) the cpu-op g++ build — these resolve on first access
    # (ds_report, version banners), then cache on the module.
    if name == "git_hash":
        value = _git("rev-parse", "--short", "HEAD")
    elif name == "git_branch":
        value = _git("rev-parse", "--abbrev-ref", "HEAD")
    elif name == "compatible_ops":
        value = _op_compat()
    else:
        raise AttributeError(name)
    globals()[name] = value
    return value


def _op_compat() -> dict:
    """Op-name → installable-here map (reference exposes compatible_ops
    for ds_report; the only native op on TPU is the host CPU Adam — the
    rest are XLA/Pallas and always available with jax)."""
    try:
        from .ops.op_builder import cpu_ops_available
        cpu_adam = bool(cpu_ops_available())
    except Exception:
        cpu_adam = False
    return {
        "cpu_adam": cpu_adam,
        "fused_adam": True,        # XLA-fused
        "fused_lamb": True,        # XLA-fused
        "transformer": True,       # XLA + Pallas flash attention
        "sparse_attn": True,       # Pallas block-sparse
        "utils": True,             # pytree flatten (no native op needed)
    }
