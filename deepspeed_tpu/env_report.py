"""``ds_report`` — environment / op-compatibility report (reference:
deepspeed/env_report.py + bin/ds_report): versions, devices, native-op
build status."""
from __future__ import annotations

import sys


def _device_line(deadline_s: int = 45) -> tuple:
    """Device enumeration with a hard deadline.

    ``jax.devices()`` blocks INDEFINITELY when a remote TPU runtime is
    wedged (the tunneled-platform failure mode this repo's bench guards
    against) — and a report tool that hangs is worse than useless when
    diagnosing exactly that situation.  The probe runs in a subprocess
    so a hung backend init cannot take the report down with it; the
    parent never initializes a backend itself.
    """
    import os
    import subprocess
    try:
        deadline_s = int(os.environ.get("DS_REPORT_DEVICE_TIMEOUT",
                                        str(deadline_s)))
    except ValueError:
        # the diagnostic tool must not die on a malformed knob — that is
        # the exact robustness this function exists for
        pass
    # honor JAX_PLATFORMS even where a sitecustomize force-registers a
    # remote platform (env alone is not enough there — the config update
    # must run before first device use)
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "d = jax.devices(); "
            "print(d[0].platform, len(d), "
            "getattr(d[0], 'device_kind', '?'), sep='|')")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=deadline_s)
    except subprocess.TimeoutExpired:
        return ("devices", f"UNREACHABLE (no response in {deadline_s}s "
                "— remote runtime down or wedged)")
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        why = tail[-1] if tail else "init failed"
        return ("devices", f"unavailable ({why})")
    try:
        platform, n, kind = r.stdout.strip().split("|")
        return ("devices", f"{n} × {kind} (platform {platform})")
    except ValueError:
        return ("devices", f"unparseable probe output {r.stdout!r}")


def collect_report() -> list:
    lines = []
    lines.append(("python", sys.version.split()[0]))
    for mod in ("jax", "jaxlib", "numpy", "optax", "flax"):
        try:
            m = __import__(mod)
            lines.append((mod, getattr(m, "__version__", "?")))
        except ImportError:
            lines.append((mod, "NOT INSTALLED"))
    lines.append(_device_line())
    from .ops.op_builder import cpu_ops_status
    lines.append(("native host ops", cpu_ops_status()))
    # per-op compatibility matrix (the reference ds_report's main table)
    from .git_version_info import compatible_ops
    for op, ok in sorted(compatible_ops.items()):
        lines.append((f"op {op}", "compatible" if ok else "UNAVAILABLE"))
    from . import __version__
    from .git_version_info import git_hash, git_branch
    lines.append(("deepspeed_tpu", f"{__version__} "
                  f"(git {git_hash}, {git_branch})"))
    return lines


def main():
    print("-" * 60)
    print("deepspeed_tpu environment report")
    print("-" * 60)
    for key, val in collect_report():
        print(f"{key:.<24} {val}")
    print("-" * 60)


if __name__ == "__main__":
    main()
