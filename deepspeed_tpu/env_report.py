"""``ds_report`` — environment / op-compatibility report (reference:
deepspeed/env_report.py + bin/ds_report): versions, devices, native-op
build status."""
from __future__ import annotations

import sys


def collect_report() -> list:
    lines = []
    lines.append(("python", sys.version.split()[0]))
    for mod in ("jax", "jaxlib", "numpy", "optax", "flax"):
        try:
            m = __import__(mod)
            lines.append((mod, getattr(m, "__version__", "?")))
        except ImportError:
            lines.append((mod, "NOT INSTALLED"))
    try:
        import jax
        devs = jax.devices()
        lines.append(("platform", devs[0].platform))
        lines.append(("devices", f"{len(devs)} × "
                      f"{getattr(devs[0], 'device_kind', '?')}"))
    except Exception as e:  # backend init can fail off-TPU
        lines.append(("devices", f"unavailable ({e})"))
    from .ops.op_builder import cpu_ops_status
    lines.append(("native host ops", cpu_ops_status()))
    # per-op compatibility matrix (the reference ds_report's main table)
    from .git_version_info import compatible_ops
    for op, ok in sorted(compatible_ops.items()):
        lines.append((f"op {op}", "compatible" if ok else "UNAVAILABLE"))
    from . import __version__
    from .git_version_info import git_hash, git_branch
    lines.append(("deepspeed_tpu", f"{__version__} "
                  f"(git {git_hash}, {git_branch})"))
    return lines


def main():
    print("-" * 60)
    print("deepspeed_tpu environment report")
    print("-" * 60)
    for key, val in collect_report():
        print(f"{key:.<24} {val}")
    print("-" * 60)


if __name__ == "__main__":
    main()
