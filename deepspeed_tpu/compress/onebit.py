"""1-bit Adam (error-feedback sign compression over the data axis).

Implementation lands with the compression milestone; this placeholder keeps
the engine's optimizer dispatch importable with a clear error.
"""
from __future__ import annotations


def onebit_adam(*args, **kwargs):
    raise NotImplementedError(
        "onebitadam is not implemented yet in this build; use 'adam'")
