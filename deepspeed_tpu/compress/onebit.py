"""1-bit Adam — error-feedback sign compression, TPU-native.

The reference implements 1-bit Adam (APMSqueeze) as a torch optimizer with a
two-phase MPI+cupy compressed allreduce (reference:
deepspeed/runtime/fp16/onebit_adam.py:104-228, custom_collectives.py:23-154):

  worker:  buf = momentum + worker_error
           scale = ||buf||_2 / sqrt(n); sign-compress; update worker_error
           chunk into world_size pieces; igather chunk r to server r
  server:  mean of workers' scaled signs for its chunk (+ server_error)
           re-compress with server_error feedback; allgather result

Here the same algorithm is expressed with XLA collectives over a named mesh
axis: the igather-to-servers becomes ``lax.all_to_all`` of bit-packed uint8
sign buffers (so the wire volume really is 1/32 of fp32, matching the
reference's cupy.packbits scheme), and the result allgather becomes
``lax.all_gather``.  One backend covers ICI and DCN — no MPI/NCCL split
(custom_collectives.py's cuda_aware fork disappears).

Two execution modes, chosen automatically at trace time:
  - inside ``shard_map`` with the data axis bound: the real multi-worker
    collective (each shard compresses its *local* momentum).
  - under plain ``jit`` with pre-averaged gradients (the standard engine
    path, where XLA already reduced the grads): the single-worker
    simulation, which is bit-identical to the real collective when all
    workers hold the same buffer (the worker mean equals each worker's own
    compressed value).

The optimizer state machine mirrors the reference step
(onebit_adam.py:230-374): steps 1..freeze_step run plain Adam updating both
moments; afterwards the variance is frozen and only the sign-compressed
momentum is exchanged.  Unlike the reference — which allocates error buffers
lazily and drops them on the bootstrap step (onebit_adam.py:356-359, a known
wart) — the error-feedback state lives in the optimizer pytree from step 0
and therefore survives checkpointing (SURVEY.md §7 "hard parts").

Note the reference computes a ``bias_correction`` flag but never applies it
in the update (onebit_adam.py:267,321-350); we reproduce the *actual*
behavior (no bias correction) rather than the dead flag.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]

# packbits-compatible big-endian bit weights (cupy.packbits default order)
_BIT_WEIGHTS = (128, 64, 32, 16, 8, 4, 2, 1)


def pack_signs(bits: jnp.ndarray) -> jnp.ndarray:
    """bool [..., 8k] → uint8 [..., k], big-endian like cupy.packbits."""
    w = jnp.asarray(_BIT_WEIGHTS, jnp.uint8)
    b = bits.reshape(bits.shape[:-1] + (-1, 8)).astype(jnp.uint8)
    return jnp.sum(b * w, axis=-1, dtype=jnp.uint8)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., k] → ±1 float32 [..., 8k] (0-bit → −1, 1-bit → +1)."""
    w = jnp.asarray(_BIT_WEIGHTS, jnp.uint8)
    bits = (packed[..., None] & w) > 0
    pm = bits.astype(jnp.float32) * 2.0 - 1.0
    return pm.reshape(packed.shape[:-1] + (-1,))


def _sign_compress(buf: jnp.ndarray, error: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Error-feedback sign compression of a flat buffer.

    Returns (sign ±1, scale, new_error).  Matches the reference's
    ``scale = ||buf||_2 / sqrt(n)`` and sign(0) → +1 convention
    (onebit_adam.py:122-128: sign().add_(1).bool() maps 0 to True).
    """
    buf = buf + error
    scale = jnp.linalg.norm(buf) / jnp.sqrt(jnp.asarray(buf.size, jnp.float32))
    sign = jnp.where(buf >= 0, 1.0, -1.0).astype(jnp.float32)
    new_error = buf - scale * sign
    return sign, scale, new_error


def padded_size(n: int, world: int) -> int:
    """Pad length so every per-server chunk is a whole number of bytes
    (the reference's ``corrected_tensor_size``, onebit_adam.py:294-300)."""
    q = world * 8
    return ((n + q - 1) // q) * q


def compressed_allreduce(x: jnp.ndarray,
                         worker_error: jnp.ndarray,
                         server_error: jnp.ndarray,
                         axis_name: str
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Two-phase error-compensated 1-bit allreduce over a mesh axis.

    Must run inside ``shard_map`` with ``axis_name`` bound.  ``x`` is this
    worker's flat fp32 buffer; ``worker_error`` has length
    ``padded_size(x.size, world)`` and ``server_error`` one world-th of
    that.  Returns (averaged buffer [x.size], new worker_error, new
    server_error).
    """
    world = jax.lax.axis_size(axis_name)
    n = x.size
    P = worker_error.size
    chunk = P // world
    assert P == padded_size(n, world) and server_error.size == chunk, (
        f"error-buffer sizes ({P}, {server_error.size}) do not match "
        f"padded_size({n}, {world})={padded_size(n, world)}")

    buf = jnp.pad(x.astype(jnp.float32), (0, P - n))
    sign, scale, new_we = _sign_compress(buf, worker_error)

    # Phase 1: igather-to-servers ≡ all_to_all of packed sign chunks.
    packed = pack_signs(sign.reshape(world, chunk) > 0)        # [world, chunk/8]
    recv = jax.lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0)
    scales = jax.lax.all_gather(scale, axis_name)              # [world]

    # Server: average workers' scaled signs for my chunk, re-compress.
    comp = jnp.mean(unpack_signs(recv) * scales[:, None], axis=0)
    ssign, sscale, new_se = _sign_compress(comp, server_error)

    # Phase 2: allgather of the servers' compressed chunks.
    spacked = pack_signs(ssign > 0)                            # [chunk/8]
    all_signs = jax.lax.all_gather(spacked, axis_name)         # [world, chunk/8]
    all_scales = jax.lax.all_gather(sscale, axis_name)         # [world]
    out = (unpack_signs(all_signs) * all_scales[:, None]).reshape(P)[:n]
    return out, new_we, new_se


def simulated_compressed_allreduce(x: jnp.ndarray,
                                   worker_error: jnp.ndarray,
                                   server_error: jnp.ndarray
                                   ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                              jnp.ndarray]:
    """The collective's fixed point when every worker holds the same buffer
    (the engine's pre-averaged-gradient path): worker compress → server
    compress, no communication.  ``server_error`` here spans the full
    padded buffer (world=1 chunking)."""
    n = x.size
    P = worker_error.size
    buf = jnp.pad(x.astype(jnp.float32), (0, P - n))
    sign, scale, new_we = _sign_compress(buf, worker_error)
    comp = scale * sign
    ssign, sscale, new_se = _sign_compress(comp, server_error)
    return (sscale * ssign)[:n], new_we, new_se


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray           # applied steps (i32)
    mu: optax.Updates            # momentum (fp32)
    nu: optax.Updates            # variance (fp32, frozen after freeze_step)
    worker_error: optax.Updates  # flat padded, per leaf
    server_error: optax.Updates  # flat padded/world, per leaf


def _axis_bound(axis_name: Optional[str]) -> bool:
    """True iff we are tracing inside a context (shard_map/pmap) where
    ``axis_name`` is a bound mesh axis — decided at trace time."""
    if axis_name is None:
        return False
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        # the one expected failure: axis not bound here (plain jit).  Any
        # other exception (typo'd axis colliding with a bound one, API
        # breakage) must surface, not silently select the simulated path.
        return False


def onebit_adam(lr: ScalarOrSchedule = 1e-3,
                betas: Tuple[float, float] = (0.9, 0.999),
                eps: float = 1e-8,
                weight_decay: float = 0.0,
                freeze_step: int = 100000,
                data_axis: Optional[str] = None,
                phase: Optional[str] = None
                ) -> optax.GradientTransformation:
    """1-bit Adam as an optax transformation.

    ``data_axis``: mesh axis for the compressed collective.  When the
    transform is traced inside ``shard_map`` with that axis bound, momentum
    is exchanged with the real 1-bit collective (error buffers must then be
    sized for that world via ``init_onebit_state``); otherwise (plain ``jit``
    with already-reduced grads) the equivalent single-worker compression is
    applied.  Warmup steps (1..freeze_step) are plain Adam, matching the
    reference's freeze transition (onebit_adam.py:366-369: compression
    starts on the step *after* ``step >= freeze_step``).

    ``phase``: ``None`` resolves warm-vs-frozen per step with ``lax.cond``
    (self-contained, but places collectives inside a conditional — a
    fragile path in TPU SPMD lowering).  ``'warm'`` / ``'frozen'`` fix the
    branch at trace time: the engine compiles TWO programs and selects
    host-side at the freeze boundary, so the frozen program contains *only*
    the uint8 collective (verifiable in its HLO) and no conditional
    collectives exist.
    """
    b1, b2 = betas
    if phase not in (None, "warm", "frozen"):
        raise ValueError(f"phase must be None|'warm'|'frozen', got {phase!r}")

    def init_fn(params):
        return init_onebit_state(params, 1)

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("onebit_adam requires params (weight decay)")
        count = state.count + 1
        use_collective = _axis_bound(data_axis)

        def leaf_update(g, p, mu, nu, we, se):
            g = g.astype(jnp.float32)

            def warm(_):
                # Collective mode receives *local* grads; during warmup the
                # reference relies on the engine's uncompressed allreduce
                # (it sets enable_backward_allreduce=False only at freeze,
                # onebit_adam.py:366-372), so the reduction happens here.
                ga = jax.lax.pmean(g, data_axis) if use_collective else g
                mu2 = b1 * mu + (1 - b1) * ga
                nu2 = b2 * nu + (1 - b2) * ga * ga
                return mu2, nu2, we, se

            def frozen(_):
                # local grad feeds the momentum; the compressed collective
                # is what crosses workers (onebit_adam.py:336-348)
                mu2 = b1 * mu + (1 - b1) * g
                flat = mu2.reshape(-1)
                if use_collective:
                    out, we2, se2 = compressed_allreduce(
                        flat, we, se, data_axis)
                else:
                    out, we2, se2 = simulated_compressed_allreduce(
                        flat, we, se)
                return out.reshape(mu2.shape), nu, we2, se2

            if phase == "warm":
                mu2, nu2, we2, se2 = warm(None)
            elif phase == "frozen":
                mu2, nu2, we2, se2 = frozen(None)
            else:
                mu2, nu2, we2, se2 = jax.lax.cond(
                    count <= freeze_step, warm, frozen, operand=None)
            upd = mu2 / (jnp.sqrt(nu2) + eps)
            if weight_decay > 0.0:
                upd = upd + weight_decay * p.astype(jnp.float32)
            step_lr = lr(count) if callable(lr) else jnp.asarray(
                lr, jnp.float32)
            return (-step_lr * upd).astype(p.dtype), mu2, nu2, we2, se2

        flat_g, treedef = jax.tree.flatten(grads)
        outs = [leaf_update(g, p, mu, nu, we, se) for g, p, mu, nu, we, se
                in zip(flat_g,
                       jax.tree.leaves(params),
                       jax.tree.leaves(state.mu),
                       jax.tree.leaves(state.nu),
                       jax.tree.leaves(state.worker_error),
                       jax.tree.leaves(state.server_error))]
        unflatten = lambda i: jax.tree.unflatten(
            treedef, [o[i] for o in outs])
        new_state = OnebitAdamState(
            count=count, mu=unflatten(1), nu=unflatten(2),
            worker_error=unflatten(3), server_error=unflatten(4))
        return unflatten(0), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def init_onebit_state(params, world: int) -> OnebitAdamState:
    """Error-buffer initialization for the real collective path: buffers
    sized for a data axis of ``world`` shards (shard_map users call this
    instead of ``tx.init``, whose world=1 sizing fits only the simulated
    path)."""
    zeros = lambda p: jnp.zeros(jnp.shape(p), jnp.float32)
    we = jax.tree.map(
        lambda p: jnp.zeros((padded_size(int(jnp.size(p)), world),),
                            jnp.float32), params)
    se = jax.tree.map(
        lambda p: jnp.zeros(
            (padded_size(int(jnp.size(p)), world) // world,),
            jnp.float32), params)
    return OnebitAdamState(
        count=jnp.zeros([], jnp.int32),
        mu=jax.tree.map(zeros, params),
        nu=jax.tree.map(zeros, params),
        worker_error=we,
        server_error=se,
    )
