"""Compressed data-parallel communication (reference feature slot:
deepspeed/runtime/fp16/onebit_adam.py + custom_collectives.py)."""
from .onebit import (OnebitAdamState, compressed_allreduce,
                     init_onebit_state, onebit_adam, pack_signs,
                     padded_size, simulated_compressed_allreduce,
                     unpack_signs)

__all__ = [
    "OnebitAdamState", "compressed_allreduce", "init_onebit_state",
    "onebit_adam", "pack_signs", "padded_size",
    "simulated_compressed_allreduce", "unpack_signs",
]
