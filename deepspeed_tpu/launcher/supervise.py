"""Shared subprocess-supervision helpers (docs/elastic.md,
docs/serving.md "serving fleet").

Two supervisors ride these: the elastic restart loop
(``launcher/elastic.py`` — relaunch a TRAINING world after host
failures) and the serving fleet router (``inference/fleet.py`` — keep N
ServeEngine replicas alive behind one front door).  Both need the same
machinery: SIGTERM-then-grace-then-SIGKILL process teardown, bounded
exponential backoff between relaunches, heartbeat-directory hygiene
between attempts, and a best-effort give-up flight record that survives
the dead fleet.  Before this module each supervisor hand-rolled its own
copy; now the semantics are one tested plane.

Everything here is deliberately jax-free (stdlib + the logger): a
supervisor must keep running when the worker runtime is the thing that
is broken.
"""
from __future__ import annotations

import glob
import json
import os
import subprocess
import time
from typing import Callable, Iterable, Optional, Tuple

from ..utils.logging import logger


def backoff_delay(base_s: float, max_s: float, attempt: int) -> float:
    """Bounded exponential backoff before relaunch ``attempt`` (1-based:
    the first RETRY waits ``base_s``), capped at ``max_s``."""
    if attempt < 1:
        return 0.0
    return min(float(base_s) * (2 ** (attempt - 1)), float(max_s))


def terminate_with_grace(
        procs: Iterable[Tuple[str, subprocess.Popen]],
        grace_s: float,
        remote_kill_fn: Optional[Callable[[str], None]] = None) -> None:
    """SIGTERM the survivors (workers may run their preemption save —
    the PR 5 hook), grace-wait, then SIGKILL the stubborn.  For
    transports whose local client does not forward signals (plain
    ssh/pdsh), ``remote_kill_fn`` then best-effort cleans the remnant
    on the host itself — otherwise a hung worker keeps its chips,
    coordinator port, and beat files into the next attempt.

    ``procs`` is ``[(tag, Popen), ...]`` — the tag is a host name for
    the elastic supervisor, a replica id for the fleet router; it only
    feeds ``remote_kill_fn`` and logs.
    """
    live = [(tag, p) for tag, p in procs if p.poll() is None]
    for _, p in live:
        try:
            p.terminate()
        except OSError:
            pass
    deadline = time.time() + float(grace_s)
    for _, p in live:
        try:
            p.wait(timeout=max(deadline - time.time(), 0.1))
        except subprocess.TimeoutExpired:
            try:
                p.kill()
                p.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
    if remote_kill_fn is not None:
        for tag in dict(live):
            try:
                remote_kill_fn(tag)
            except Exception as e:
                logger.warning("supervise: remote cleanup of %s "
                               "failed: %s", tag, e)


def sweep_heartbeat_files(directory: Optional[str],
                          prefix: str = "heartbeat_") -> None:
    """Clear stale beat files before a launch so liveness never judges
    this attempt by the previous attempt's files."""
    if not directory:
        return
    for f in glob.glob(os.path.join(directory, f"{prefix}*.json")):
        try:
            os.unlink(f)
        except OSError:
            pass


def dump_supervisor_flightrec(directory: Optional[str], *,
                              supervisor: str, reason: str, error: str,
                              restarts: int, max_restarts: int,
                              fallback: str, events, extra=None) -> None:
    """Best-effort give-up post-mortem next to the heartbeat files
    (``python -m deepspeed_tpu.telemetry diagnose <dir>`` reads it); a
    supervisor out of options must never die on a dump failure.  Same
    schema as the telemetry hub's flight records, written inline so the
    writer stays jax-free."""
    if not directory:
        return
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "flightrec_supervisor.json")
        payload = {
            "version": 1, "reason": reason, "step": None,
            "time": time.time(), "error": error,
            "stages": {supervisor: {
                "degraded": False, "failures": restarts,
                "max_failures": max_restarts,
                "fallback": fallback,
                "surfaced": error, "events": list(events)}},
            "extra": dict(extra or {}),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=repr)
        os.replace(tmp, path)
        logger.error("%s: flight record dumped to %s", supervisor, path)
    except OSError as e:
        logger.warning("%s: flight-record dump failed: %s",
                       supervisor, e)
