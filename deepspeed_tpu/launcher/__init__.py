"""Launcher — hostfile-driven multi-host TPU job dispatch (reference
feature slot: deepspeed/launcher/ + bin/ds)."""
from .runner import (encode_world_info, fetch_hostfile,
                     parse_inclusion_exclusion, parse_resource_filter)
from .launch import build_env, decode_world_info

__all__ = ["encode_world_info", "fetch_hostfile",
           "parse_inclusion_exclusion", "parse_resource_filter",
           "build_env", "decode_world_info"]
