"""Per-node launcher — decodes world info and starts the training process.

The reference spawns one subprocess per local GPU with RANK /
CUDA_VISIBLE_DEVICES (reference: deepspeed/launcher/launch.py:65-132).  On
TPU one process per host drives every local chip, so this sets up the
``jax.distributed`` env contract instead and execs the user script once:

  JAX_COORDINATOR_ADDRESS  = master_addr:master_port
  JAX_NUM_PROCESSES        = number of hosts
  JAX_PROCESS_ID           = this host's node_rank
plus the reference-compatible RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT
aliases some user scripts read.
"""
from __future__ import annotations

import argparse
import base64
import json
import os
import sys

from ..utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64 json of {host: [slots]}")
    parser.add_argument("--node_rank", type=int, required=True)
    parser.add_argument("--master_addr", type=str, required=True)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(encoded: str) -> dict:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


# env vars the MPI launchers leave behind, in resolution order
_MPI_RANK_VARS = ("OMPI_COMM_WORLD_RANK", "MV2_COMM_WORLD_RANK",
                  "PMI_RANK")


def resolve_node_rank(node_rank: int, env=None) -> int:
    """``--node_rank=-1`` means "ask the MPI environment": the openmpi /
    mvapich runners broadcast ONE identical command to every node
    (launcher/multinode_runner.py), so the per-node rank can only come
    from the transport's own rank variable."""
    if node_rank >= 0:
        return node_rank
    env = os.environ if env is None else env
    for var in _MPI_RANK_VARS:
        if var in env:
            return int(env[var])
    raise ValueError(
        "--node_rank=-1 requires an MPI rank variable in the "
        f"environment (one of {', '.join(_MPI_RANK_VARS)}); launch "
        "through mpirun or pass an explicit --node_rank")


def build_env(world_info: dict, node_rank: int, master_addr: str,
              master_port: int, base_env=None) -> dict:
    env = dict(base_env if base_env is not None else os.environ)
    hosts = list(world_info.keys())
    if not 0 <= node_rank < len(hosts):
        raise ValueError(
            f"node_rank {node_rank} out of range for {len(hosts)} hosts")
    slots = world_info[hosts[node_rank]]
    env.update({
        "JAX_COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
        "JAX_NUM_PROCESSES": str(len(hosts)),
        "JAX_PROCESS_ID": str(node_rank),
        # reference-compatible aliases (launch.py:101-110 there)
        "RANK": str(node_rank),
        "WORLD_SIZE": str(len(hosts)),
        "LOCAL_RANK": "0",
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
        # libtpu honors TPU_VISIBLE_CHIPS (TPU_VISIBLE_DEVICES on older
        # runtimes) — both set so slot filters actually partition the host
        "TPU_VISIBLE_CHIPS": ",".join(str(s) for s in slots),
        "TPU_VISIBLE_DEVICES": ",".join(str(s) for s in slots),
    })
    return env


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    node_rank = resolve_node_rank(args.node_rank)
    env = build_env(world_info, node_rank, args.master_addr,
                    args.master_port)
    args.node_rank = node_rank
    cmd = [sys.executable, args.user_script] + args.user_args
    logger.info("node %d/%d exec: %s", args.node_rank, len(world_info),
                " ".join(cmd))
    os.execvpe(cmd[0], cmd, env)


if __name__ == "__main__":
    main()
