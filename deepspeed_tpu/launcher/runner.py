"""``ds`` CLI front-end — multi-host TPU launcher.

Mirrors the reference launcher's resource model (reference:
deepspeed/launcher/runner.py:115-232: hostfile ``host slots=N`` lines,
``--include``/``--exclude`` NODE_SPEC[@NODE_SPEC...] filters, base64 world
info) with TPU launch semantics: one *process per host* (a TPU-VM process
drives all local chips through jax, unlike the reference's
process-per-GPU fork, launch.py:112-125 there), wired together via
``jax.distributed`` coordinator env vars instead of NCCL's MASTER_ADDR
rendezvous.  Multi-node dispatch shells out over ssh (pdsh if present),
matching the reference's PDSH runner (multinode_runner.py:35-75).
"""
from __future__ import annotations

import argparse
import base64
import collections
import json
import os
import subprocess
import sys
from copy import deepcopy
from typing import Dict, List, Optional

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ("JAX_", "XLA_", "TPU_", "LIBTPU", "PYTHON", "PATH",
               "LD_LIBRARY_PATH", "DEEPSPEED_TPU_")
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher: run a training script across "
        "TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile of 'hostname slots=N' lines "
                        "(slots = TPU chips on that host)")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="NODE_SPEC[@NODE_SPEC ...]; "
                        "NODE_SPEC=NAME[:SLOT[,SLOT...]]")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="same syntax as --include; mutually exclusive")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="limit to the first N nodes of the hostfile")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus",
                        help="chips per node to use (reference flag name "
                        "kept for CLI compatibility)")
    parser.add_argument("--master_addr", type=str, default="",
                        help="jax.distributed coordinator address "
                        "(default: first host)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=("pdsh", "ssh", "local", "openmpi",
                                 "mvapich"),
                        help="multi-node transport")
    parser.add_argument("--force_multi", action="store_true",
                        help="treat a single node as a multi-node launch")
    parser.add_argument("user_script", type=str,
                        help="training script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse ``hostname slots=N`` lines → OrderedDict (reference
    runner.py:115-140: same format, duplicate-host error)."""
    if not os.path.isfile(hostfile_path):
        logger.warning("Unable to find hostfile %s; proceeding with local "
                       "resources only", hostfile_path)
        return None
    resource_pool: Dict[str, int] = collections.OrderedDict()
    with open(hostfile_path) as fd:
        for line in fd:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(key)
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(
                    f"Hostfile line not formatted as 'host slots=N': "
                    f"{line!r}")
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def parse_resource_filter(host_info: Dict[str, List[int]],
                          include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Filter {host: [slots]} by include/exclude NODE_SPEC strings —
    the reference's exact semantics (runner.py:143-232): include builds
    from scratch, exclude removes, the two are mutually exclusive, empty
    hosts are dropped, hostfile ordering is preserved."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually "
                         "exclusive")
    if not include_str and not exclude_str:
        return host_info

    filtered: Dict[str, List[int]] = {}
    parse_str = include_str
    if exclude_str:
        filtered = deepcopy(host_info)
        parse_str = exclude_str

    for node_config in parse_str.split("@"):
        if ":" in node_config:
            hostname, slot_str = node_config.split(":")
            slots = [int(x) for x in slot_str.split(",")]
            if hostname not in host_info:
                raise ValueError(
                    f"Hostname '{hostname}' not found in hostfile")
            for s in slots:
                if s not in host_info[hostname]:
                    raise ValueError(
                        f"No slot '{s}' specified on host '{hostname}'")
            if include_str:
                filtered[hostname] = slots
            else:
                for s in slots:
                    filtered[hostname].remove(s)
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(
                    f"Hostname '{hostname}' not found in hostfile")
            filtered[hostname] = host_info[hostname] if include_str else []

    for hostname in list(filtered):
        filtered[hostname] = sorted(set(filtered[hostname]))
        if not filtered[hostname]:
            del filtered[hostname]

    return collections.OrderedDict(
        (h, filtered[h]) for h in host_info if h in filtered)


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              inclusion: str,
                              exclusion: str) -> Dict[str, List[int]]:
    active = collections.OrderedDict(
        (host, list(range(slots))) for host, slots in resource_pool.items())
    return parse_resource_filter(active, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    """base64(json) world info passed to every node (reference
    runner.py:245-248)."""
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def _export_env_lines(extra_env_file: str = DEEPSPEED_ENVIRONMENT_NAME
                      ) -> Dict[str, str]:
    """Env vars propagated to remote nodes: JAX/XLA/TPU families plus any
    KEY=VALUE lines from a .deepspeed_env file (reference
    runner.py:27-29,340-351)."""
    exports = {}
    for key, val in os.environ.items():
        if any(key.startswith(p) for p in EXPORT_ENVS):
            exports[key] = val
    for candidate in (os.path.join(os.path.expanduser("~"),
                                   extra_env_file), extra_env_file):
        if os.path.isfile(candidate):
            with open(candidate) as f:
                for line in f:
                    line = line.strip()
                    if line and "=" in line and not line.startswith("#"):
                        k, v = line.split("=", 1)
                        exports[k] = v
            break
    return exports


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if resource_pool is None:
        # single-host launch: exec in place with chip visibility
        env = os.environ.copy()
        if args.num_gpus > 0:
            chips = ",".join(str(i) for i in range(args.num_gpus))
            env["TPU_VISIBLE_CHIPS"] = chips
            env["TPU_VISIBLE_DEVICES"] = chips
        cmd = [sys.executable, args.user_script] + args.user_args
        logger.info("single-host launch: %s", " ".join(cmd))
        os.execvpe(cmd[0], cmd, env)
        return  # unreachable

    active = parse_inclusion_exclusion(resource_pool, args.include,
                                       args.exclude)
    if args.num_nodes > 0:
        active = collections.OrderedDict(
            list(active.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active = collections.OrderedDict(
            (h, s[:args.num_gpus]) for h, s in active.items())
    if not active:
        raise ValueError("no resources left after include/exclude filters")

    master_addr = args.master_addr or next(iter(active))
    world_info = encode_world_info(active)
    exports = _export_env_lines()

    if args.launcher in ("openmpi", "mvapich"):
        # MPI flavor: ONE mpirun command covers every node (reference
        # multinode_runner.py:78-189); ranks resolve node_rank from the
        # MPI environment (launch.py --node_rank=-1)
        from .multinode_runner import RUNNERS
        args.master_addr = master_addr
        runner = RUNNERS[args.launcher](args, world_info)
        runner.validate_args()
        if not runner.backend_exists():
            raise RuntimeError(
                f"launcher '{args.launcher}' selected but unavailable: "
                f"{runner.backend_missing_reason()}")
        cmd = runner.get_cmd(exports, active)
        logger.info("%s launch: %s", runner.name, " ".join(cmd))
        env = os.environ.copy()
        env.update(exports)
        return subprocess.call(cmd, env=env)

    # per-host fan-out: each node gets a distinct node_rank, so commands
    # differ per host and pdsh's single-command broadcast doesn't apply —
    # both transports dispatch one remote command per host, built by the
    # shared runner classes (one copy of the launch-command grammar)
    from .multinode_runner import PDSHRunner, SSHRunner
    args.master_addr = master_addr
    pdsh = PDSHRunner(args, world_info)
    fan_out = (pdsh if args.launcher == "pdsh" and pdsh.backend_exists()
               else SSHRunner(args, world_info))
    launch_cmds = fan_out.get_cmd(exports, active)

    if args.launcher == "local" or (len(active) == 1
                                    and not args.force_multi):
        host, remote = launch_cmds[0]
        logger.info("local launch on %s", host)
        return subprocess.call(remote, shell=True)

    transport = ["pdsh", "-w"] if fan_out.name == "pdsh" else ["ssh"]
    procs = [subprocess.Popen(transport + [host, remote])
             for host, remote in launch_cmds]
    return max(p.wait() for p in procs)


if __name__ == "__main__":
    sys.exit(main())
