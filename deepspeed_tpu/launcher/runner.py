"""``ds`` CLI front-end — multi-host TPU launcher.

Mirrors the reference launcher's resource model (reference:
deepspeed/launcher/runner.py:115-232: hostfile ``host slots=N`` lines,
``--include``/``--exclude`` NODE_SPEC[@NODE_SPEC...] filters, base64 world
info) with TPU launch semantics: one *process per host* (a TPU-VM process
drives all local chips through jax, unlike the reference's
process-per-GPU fork, launch.py:112-125 there), wired together via
``jax.distributed`` coordinator env vars instead of NCCL's MASTER_ADDR
rendezvous.  Multi-node dispatch shells out over ssh (pdsh if present),
matching the reference's PDSH runner (multinode_runner.py:35-75).
"""
from __future__ import annotations

import argparse
import base64
import collections
import json
import os
import subprocess
import sys
from copy import deepcopy
from typing import Dict, List, Optional

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
# DS_: the runtime's own knob/fault-injection family (DS_PREFETCH,
# DS_CKPT_*, DS_HEARTBEAT_DIR, ...) — an operator's escape hatch must
# reach every node, not just the launch host
EXPORT_ENVS = ("JAX_", "XLA_", "TPU_", "LIBTPU", "PYTHON", "PATH",
               "LD_LIBRARY_PATH", "DEEPSPEED_TPU_", "DS_")
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher: run a training script across "
        "TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile of 'hostname slots=N' lines "
                        "(slots = TPU chips on that host)")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="NODE_SPEC[@NODE_SPEC ...]; "
                        "NODE_SPEC=NAME[:SLOT[,SLOT...]]")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="same syntax as --include; mutually exclusive")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="limit to the first N nodes of the hostfile")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus",
                        help="chips per node to use (reference flag name "
                        "kept for CLI compatibility)")
    parser.add_argument("--master_addr", type=str, default="",
                        help="jax.distributed coordinator address "
                        "(default: first host)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=("pdsh", "ssh", "local", "openmpi",
                                 "mvapich"),
                        help="multi-node transport")
    parser.add_argument("--force_multi", action="store_true",
                        help="treat a single node as a multi-node launch")
    # ---- elastic training (docs/elastic.md) ----
    parser.add_argument("--elastic", action="store_true",
                        help="supervise the job: on worker failure or "
                        "missed heartbeats, kill the remnants, re-probe "
                        "the hosts, re-form the world from the survivors "
                        "at the reduced width, and relaunch resuming "
                        "from the newest verified checkpoint tag")
    parser.add_argument("--max-restarts", type=int, default=3,
                        dest="max_restarts",
                        help="relaunch budget before the supervisor "
                        "gives up with a typed error (0 = never restart)")
    parser.add_argument("--backoff-base", type=float, default=1.0,
                        dest="backoff_base",
                        help="exponential-backoff base seconds between "
                        "relaunches")
    parser.add_argument("--backoff-max", type=float, default=60.0,
                        dest="backoff_max",
                        help="backoff cap in seconds")
    parser.add_argument("--min-slots", type=int, default=1,
                        dest="min_slots",
                        help="smallest surviving chip count worth "
                        "resuming at; below it the supervisor gives up")
    parser.add_argument("--heartbeat-dir", type=str, default="",
                        dest="heartbeat_dir",
                        help="shared dir for per-host heartbeat files "
                        "(exported to workers as DS_HEARTBEAT_DIR; "
                        "default: a fresh temp dir — pass a shared-"
                        "filesystem path for multi-host liveness)")
    parser.add_argument("--heartbeat-timeout", type=float, default=0.0,
                        dest="heartbeat_timeout",
                        help="seconds without a heartbeat after which a "
                        "host counts as hung and the attempt is killed "
                        "and restarted (0 = exit-watching only)")
    parser.add_argument("--probe-cmd", type=str, default="",
                        dest="probe_cmd",
                        help="shell command template probing one host "
                        "between attempts, '{host}' substituted; exit "
                        "!= 0 marks the host dead, and an optional "
                        "'slots=N' on stdout resizes it (default: ssh "
                        "-o ConnectTimeout=5 <host> true; localhost is "
                        "always alive)")
    parser.add_argument("user_script", type=str,
                        help="training script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse ``hostname slots=N`` lines → OrderedDict (reference
    runner.py:115-140: same format, duplicate-host error)."""
    if not os.path.isfile(hostfile_path):
        logger.warning("Unable to find hostfile %s; proceeding with local "
                       "resources only", hostfile_path)
        return None
    resource_pool: Dict[str, int] = collections.OrderedDict()
    with open(hostfile_path) as fd:
        for line in fd:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(key)
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(
                    f"Hostfile line not formatted as 'host slots=N': "
                    f"{line!r}")
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def parse_resource_filter(host_info: Dict[str, List[int]],
                          include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Filter {host: [slots]} by include/exclude NODE_SPEC strings —
    the reference's exact semantics (runner.py:143-232): include builds
    from scratch, exclude removes, the two are mutually exclusive, empty
    hosts are dropped, hostfile ordering is preserved."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually "
                         "exclusive")
    if not include_str and not exclude_str:
        return host_info

    filtered: Dict[str, List[int]] = {}
    parse_str = include_str
    if exclude_str:
        filtered = deepcopy(host_info)
        parse_str = exclude_str

    which = "--include" if include_str else "--exclude"
    known = ", ".join(host_info) or "<empty hostfile>"
    for node_config in parse_str.split("@"):
        if not node_config:
            raise ValueError(
                f"{which} filter {parse_str!r} contains an empty "
                "NODE_SPEC (stray '@'?); expected "
                "NAME[:SLOT[,SLOT...]][@NAME...]")
        if ":" in node_config:
            parts = node_config.split(":")
            if len(parts) != 2:
                raise ValueError(
                    f"{which} NODE_SPEC {node_config!r} is malformed: "
                    "expected NAME or NAME:SLOT[,SLOT...] (one colon)")
            hostname, slot_str = parts
            try:
                slots = [int(x) for x in slot_str.split(",")]
            except ValueError:
                raise ValueError(
                    f"{which} NODE_SPEC {node_config!r} is malformed: "
                    f"slots must be comma-separated integers, got "
                    f"{slot_str!r}")
            if hostname not in host_info:
                raise ValueError(
                    f"{which} names hostname {hostname!r} which is not "
                    f"in the hostfile (hosts: {known}) — refusing to "
                    "silently ignore a filter that matches nothing")
            for s in slots:
                if s not in host_info[hostname]:
                    raise ValueError(
                        f"{which} names slot {s} on host {hostname!r}, "
                        f"which only has slots "
                        f"{host_info[hostname]}")
            if include_str:
                filtered[hostname] = slots
            else:
                for s in slots:
                    if s in filtered[hostname]:
                        filtered[hostname].remove(s)
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(
                    f"{which} names hostname {hostname!r} which is not "
                    f"in the hostfile (hosts: {known}) — refusing to "
                    "silently ignore a filter that matches nothing")
            filtered[hostname] = host_info[hostname] if include_str else []

    for hostname in list(filtered):
        filtered[hostname] = sorted(set(filtered[hostname]))
        if not filtered[hostname]:
            del filtered[hostname]

    return collections.OrderedDict(
        (h, filtered[h]) for h in host_info if h in filtered)


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              inclusion: str,
                              exclusion: str) -> Dict[str, List[int]]:
    active = collections.OrderedDict(
        (host, list(range(slots))) for host, slots in resource_pool.items())
    return parse_resource_filter(active, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    """base64(json) world info passed to every node (reference
    runner.py:245-248)."""
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def _export_env_lines(extra_env_file: str = DEEPSPEED_ENVIRONMENT_NAME
                      ) -> Dict[str, str]:
    """Env vars propagated to remote nodes: JAX/XLA/TPU families plus any
    KEY=VALUE lines from a .deepspeed_env file (reference
    runner.py:27-29,340-351)."""
    exports = {}
    for key, val in os.environ.items():
        if any(key.startswith(p) for p in EXPORT_ENVS):
            exports[key] = val
    for candidate in (os.path.join(os.path.expanduser("~"),
                                   extra_env_file), extra_env_file):
        if os.path.isfile(candidate):
            with open(candidate) as f:
                for line in f:
                    line = line.strip()
                    if line and "=" in line and not line.startswith("#"):
                        k, v = line.split("=", 1)
                        exports[k] = v
            break
    return exports


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if resource_pool is None and args.elastic:
        # elastic without a hostfile: supervise a localhost world (the
        # single-host exec below cannot be supervised — exec replaces
        # the supervisor)
        resource_pool = collections.OrderedDict(
            [("localhost", max(args.num_gpus, 1))])

    if resource_pool is None:
        if args.include or args.exclude:
            # a filter against a pool that does not exist can only be a
            # mistake (typo'd -H path is the common one) — silently
            # ignoring it would launch on resources the operator
            # explicitly tried to constrain
            raise ValueError(
                f"--include/--exclude were given but no hostfile exists "
                f"at {args.hostfile!r}; resource filters need a "
                "hostfile resource pool to filter")
        # single-host launch: exec in place with chip visibility
        env = os.environ.copy()
        if args.num_gpus > 0:
            chips = ",".join(str(i) for i in range(args.num_gpus))
            env["TPU_VISIBLE_CHIPS"] = chips
            env["TPU_VISIBLE_DEVICES"] = chips
        cmd = [sys.executable, args.user_script] + args.user_args
        logger.info("single-host launch: %s", " ".join(cmd))
        os.execvpe(cmd[0], cmd, env)
        return  # unreachable

    active = parse_inclusion_exclusion(resource_pool, args.include,
                                       args.exclude)
    if args.num_nodes > 0:
        active = collections.OrderedDict(
            list(active.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active = collections.OrderedDict(
            (h, s[:args.num_gpus]) for h, s in active.items())
    if not active:
        raise ValueError("no resources left after include/exclude filters")

    master_addr = args.master_addr or next(iter(active))
    world_info = encode_world_info(active)
    exports = _export_env_lines()

    if args.elastic:
        return _run_elastic(args, active, exports)

    if args.launcher in ("openmpi", "mvapich"):
        # MPI flavor: ONE mpirun command covers every node (reference
        # multinode_runner.py:78-189); ranks resolve node_rank from the
        # MPI environment (launch.py --node_rank=-1)
        from .multinode_runner import RUNNERS
        args.master_addr = master_addr
        runner = RUNNERS[args.launcher](args, world_info)
        runner.validate_args()
        if not runner.backend_exists():
            raise RuntimeError(
                f"launcher '{args.launcher}' selected but unavailable: "
                f"{runner.backend_missing_reason()}")
        cmd = runner.get_cmd(exports, active)
        logger.info("%s launch: %s", runner.name, " ".join(cmd))
        env = os.environ.copy()
        env.update(exports)
        return subprocess.call(cmd, env=env)

    # per-host fan-out: each node gets a distinct node_rank, so commands
    # differ per host and pdsh's single-command broadcast doesn't apply —
    # both transports dispatch one remote command per host, built by the
    # shared runner classes (one copy of the launch-command grammar)
    args.master_addr = master_addr
    fan_out, launch_cmds = _fan_out_cmds(args, active, exports)

    if args.launcher == "local" or (len(active) == 1
                                    and not args.force_multi):
        host, remote = launch_cmds[0]
        logger.info("local launch on %s", host)
        return subprocess.call(remote, shell=True)

    transport = ["pdsh", "-w"] if fan_out.name == "pdsh" else ["ssh"]
    procs = [subprocess.Popen(transport + [host, remote])
             for host, remote in launch_cmds]
    return max(p.wait() for p in procs)


def _fan_out_cmds(args, active, exports):
    """One (host, remote-command) pair per node via the shared runner
    classes — the single copy of the launch-command grammar, used by
    both the one-shot path and every elastic relaunch."""
    from .multinode_runner import PDSHRunner, SSHRunner
    world_info = encode_world_info(active)
    pdsh = PDSHRunner(args, world_info)
    fan_out = (pdsh if args.launcher == "pdsh" and pdsh.backend_exists()
               else SSHRunner(args, world_info))
    return fan_out, fan_out.get_cmd(exports, active)


def _build_probe(args):
    """Host-liveness probe for the elastic supervisor: --probe-cmd
    template (exit != 0 = dead; 'slots=N' on stdout resizes), else ssh
    (localhost / --launcher local always alive)."""
    import re

    if args.probe_cmd:
        def probe(host):
            r = subprocess.run(args.probe_cmd.format(host=host),
                               shell=True, capture_output=True,
                               text=True, timeout=60)
            if r.returncode != 0:
                return None
            m = re.search(r"slots=(\d+)", r.stdout)
            return list(range(int(m.group(1)))) if m else True
        return probe

    def probe(host):
        if args.launcher == "local" or host in ("localhost", "127.0.0.1"):
            return True
        r = subprocess.run(["ssh", "-o", "BatchMode=yes",
                            "-o", "ConnectTimeout=5", host, "true"],
                           capture_output=True, timeout=60)
        return True if r.returncode == 0 else None
    return probe


def _run_elastic(args, active, exports):
    """``ds --elastic``: supervise the launch with the restart loop in
    launcher/elastic.py — worker exits + missed heartbeats trigger
    kill → host re-probe → world re-formation at the surviving width →
    relaunch, with the resumed run walking the checkpoint fallback
    chain to the newest verified tag (docs/elastic.md)."""
    import tempfile

    from .elastic import (ELASTIC_RESTART_ENV, ELASTIC_SLOTS_ENV,
                          ElasticSupervisor, RestartPolicy)

    if args.launcher in ("openmpi", "mvapich"):
        raise ValueError(
            "--elastic supports the pdsh/ssh/local launchers only: "
            "mpirun owns process placement, so the supervisor cannot "
            "re-form a shrunk world under it")
    hb_dir = args.heartbeat_dir or tempfile.mkdtemp(prefix="ds_heartbeat_")
    if not args.heartbeat_dir:
        logger.info("elastic: heartbeat dir %s (pass --heartbeat-dir on "
                    "a SHARED filesystem for multi-host liveness)",
                    hb_dir)
    user_master = args.master_addr  # explicit flag pins the coordinator

    def launch(active_now, attempt):
        # re-derive the coordinator each attempt: the previous rank-0
        # host may be the one that died
        args.master_addr = user_master or next(iter(active_now))
        exp = dict(exports)
        exp[ELASTIC_RESTART_ENV] = str(attempt)
        exp[ELASTIC_SLOTS_ENV] = str(
            sum(len(s) for s in active_now.values()))
        exp["DS_HEARTBEAT_DIR"] = hb_dir
        fan_out, cmds = _fan_out_cmds(args, active_now, exp)
        host0 = cmds[0][0]
        # in-process launch ONLY when the (single) host IS this machine:
        # a remote world shrunk to one surviving host must still go over
        # the transport — the survivor is not the supervisor's machine
        if args.launcher == "local" or (
                len(active_now) == 1 and not args.force_multi
                and host0 in ("localhost", "127.0.0.1")):
            host, remote = cmds[0]
            logger.info("elastic: local launch on %s (attempt %d)",
                        host, attempt)
            return [(host, subprocess.Popen(remote, shell=True))]
        transport = ["pdsh", "-w"] if fan_out.name == "pdsh" else ["ssh"]
        return [(host, subprocess.Popen(transport + [host, remote]))
                for host, remote in cmds]

    def remote_kill(host):
        # best-effort remnant cleanup: SIGTERMing the local ssh/pdsh
        # client does not reach the remote worker (no pty, no signal
        # forwarding), so a hung host would keep its chips and beat
        # files — pkill the user script by path on the host itself
        if host in ("localhost", "127.0.0.1"):
            return
        import shlex
        subprocess.run(
            ["ssh", "-o", "BatchMode=yes", "-o", "ConnectTimeout=5",
             host, f"pkill -TERM -f {shlex.quote(args.user_script)}"],
            capture_output=True, timeout=30)

    supervisor = ElasticSupervisor(
        active, launch, probe_fn=_build_probe(args),
        policy=RestartPolicy(max_restarts=args.max_restarts,
                             backoff_base_s=args.backoff_base,
                             backoff_max_s=args.backoff_max,
                             min_slots=args.min_slots),
        heartbeat_dir=hb_dir,
        heartbeat_timeout_s=args.heartbeat_timeout,
        remote_kill_fn=(None if args.launcher == "local"
                        else remote_kill))
    return supervisor.run()


if __name__ == "__main__":
    sys.exit(main())
