"""Multinode runners — pdsh/ssh fan-out and MPI-style single-command
launchers.

The reference ships OpenMPI and MVAPICH runners that build one ``mpirun``
command covering every node (reference:
deepspeed/launcher/multinode_runner.py:78-189, with CUDA-aware MCA/MV2
env plumbing).  The TPU equivalents here keep the command grammar —
``mpirun -n <nodes> --hostfile <path> -x ENV ... python -m
deepspeed_tpu.launcher.launch ...`` — but place ONE process per host
(a TPU host drives all its local chips through one jax process, so the
reference's process-per-GPU slot math does not apply) and let each rank
derive its node_rank from the MPI environment at runtime
(``--node_rank=-1``; see launcher/launch.py) since mpirun broadcasts a
single identical command line.

The pdsh/ssh runners wrap the per-host dispatch the ``ds`` front-end has
always used, so every launcher flavor shares one interface.
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import sys
from abc import ABC, abstractmethod
from typing import Dict, List


class MultiNodeRunner(ABC):
    """One launch strategy: builds the command(s) that start training on
    every node of the resource pool."""

    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.world_info_base64 = world_info_base64

    @property
    @abstractmethod
    def name(self) -> str:
        ...

    @abstractmethod
    def backend_exists(self) -> bool:
        """Is the transport binary available on this host?"""
        ...

    def backend_missing_reason(self) -> str:
        """Operator-facing reason when backend_exists() is False — must
        name the ACTUAL failed requirement, not a generic PATH claim."""
        return f"required binary for launcher {self.name!r} not on PATH"

    def validate_args(self):
        """Reference parity: MPI launchers reject per-host resource
        filters — mpirun owns placement (reference
        multinode_runner.py:92-99)."""

    def _launch_parts(self, node_rank) -> List[str]:
        a = self.args
        return [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                f"--world_info={self.world_info_base64}",
                f"--node_rank={node_rank}",
                f"--master_addr={a.master_addr}",
                f"--master_port={a.master_port}",
                a.user_script] + list(a.user_args)


class PDSHRunner(MultiNodeRunner):
    name = "pdsh"

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]):
        """One (host, remote-command) pair per node — node_rank differs
        per host, so there is no single broadcastable command."""
        env_str = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in sorted(environment.items()))
        cmds = []
        for rank, host in enumerate(active_resources):
            parts = self._launch_parts(rank)
            remote = (env_str + " "
                      + " ".join(shlex.quote(p) for p in parts)).strip()
            cmds.append((host, remote))
        return cmds


class SSHRunner(PDSHRunner):
    name = "ssh"

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None


class OpenMPIRunner(MultiNodeRunner):
    """``mpirun`` over the hostfile, one process per host (reference
    OpenMPIRunner, multinode_runner.py:78-134 — minus the CUDA/IB MCA
    tuning, which has no TPU analogue; jax.distributed rides TCP to the
    coordinator and XLA owns the ICI/DCN fabric)."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def backend_missing_reason(self) -> str:
        return "mpirun is not on PATH"

    def validate_args(self):
        a = self.args
        if getattr(a, "include", "") or getattr(a, "exclude", ""):
            raise ValueError(
                f"{self.name} launcher does not support "
                "--include/--exclude filters: mpirun owns process "
                "placement (edit the hostfile instead; reference "
                "multinode_runner.py:92-99 rejects these the same way)")

    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        a = self.args
        n = len(active_resources)
        cmd = ["mpirun", "-n", str(n), "--map-by", "ppr:1:node"]
        if a.hostfile and os.path.isfile(a.hostfile):
            cmd += ["--hostfile", a.hostfile]
        for k, v in sorted(environment.items()):
            cmd += ["-x", f"{k}={v}"]
        # node_rank resolved per-rank from OMPI_COMM_WORLD_RANK
        return cmd + self._launch_parts(-1)


class MVAPICHRunner(OpenMPIRunner):
    """MVAPICH flavor (reference MVAPICHRunner,
    multinode_runner.py:137-189) — minus the GDR/CUDA knobs, which do
    not exist on TPU hosts.  MVAPICH2's Hydra process manager speaks a
    DIFFERENT dialect than OpenMPI's orterun: ``-ppn`` instead of
    ``--map-by ppr``, ``-env K V`` instead of ``-x K=V``, and a PLAIN
    one-host-per-line hostfile instead of the slots grammar — the
    reference likewise writes its own hostfile (multinode_runner.py:
    158-167)."""

    name = "mvapich"

    # the reference force-enables these for its fabric; the TPU build
    # keeps only the transport-neutral ones
    MV2_DEFAULTS = {
        "MV2_SMP_USE_CMA": "0",
        "MV2_DEBUG_SHOW_BACKTRACE": "1",
    }

    def backend_exists(self) -> bool:
        """Require MVAPICH specifically, not any mpirun: the Hydra
        dialect below (``-ppn``, ``-env K V``, plain hostfile) makes
        OpenMPI's orterun die with a usage error, so accepting a generic
        mpirun would swap a clear 'backend not found' for a cryptic
        launch failure.  Like the reference (multinode_runner.py:147-156)
        we identify the flavor via ``mpiname``."""
        mpiname = shutil.which("mpiname")
        if mpiname is None or shutil.which("mpirun") is None:
            return False
        try:
            out = subprocess.run([mpiname], capture_output=True,
                                 text=True, timeout=10).stdout
        except (OSError, subprocess.SubprocessError):
            return False
        return "mvapich" in out.lower()

    def backend_missing_reason(self) -> str:
        if shutil.which("mpirun") is None:
            return "mpirun is not on PATH"
        if shutil.which("mpiname") is None:
            return ("mpirun is on PATH but mpiname is not, so the "
                    "MVAPICH flavor cannot be confirmed (this runner's "
                    "Hydra dialect breaks other MPIs — for OpenMPI use "
                    "--launcher openmpi)")
        return ("mpirun is on PATH but mpiname does not report MVAPICH "
                "— for OpenMPI clusters use --launcher openmpi")

    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        import atexit
        import tempfile
        n = len(active_resources)
        # Hydra's hostfile is one host per line (no slots grammar).
        # mkstemp: unique per launch (concurrent launches cannot clobber
        # each other's host lists) and O_EXCL|0600 (no symlink/pre-create
        # games in the shared tmp dir); cleaned up when the launcher
        # process exits — it outlives mpirun, so no accumulation.
        fd, hf_path = tempfile.mkstemp(prefix="ds_mvapich_hostfile_",
                                       suffix=".txt", text=True)
        with os.fdopen(fd, "w") as hf:
            hf.write("\n".join(active_resources) + "\n")
        atexit.register(lambda p=hf_path: os.path.exists(p) and
                        os.unlink(p))
        cmd = ["mpirun", "-n", str(n), "-ppn", "1",
               "-hostfile", hf_path]
        env = dict(self.MV2_DEFAULTS)
        env.update(environment)
        for k, v in sorted(env.items()):
            cmd += ["-env", k, v]
        return cmd + self._launch_parts(-1)


RUNNERS = {cls.name: cls for cls in
           (PDSHRunner, SSHRunner, OpenMPIRunner, MVAPICHRunner)}
