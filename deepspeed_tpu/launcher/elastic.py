"""Elastic restart supervisor — ``ds --elastic`` (docs/elastic.md).

The reference launcher is fire-and-forget: a dead worker takes the job
down and a human relaunches it.  This module closes ROADMAP item 2's
"multi-day run on preemptible pods" loop: the supervisor launches the
job, watches worker exits AND per-host heartbeats (a host can hang with
its process alive — wedged collective, dead NIC), and on failure kills
the remnants, **re-probes the hosts**, re-forms the world from the
survivors at the reduced width, and relaunches.  The relaunched run
resumes from the newest VERIFIED checkpoint tag via the existing
fallback chain (``load_checkpoint(tag=None)`` walks corrupt/vanished
tags back — runtime/resilience.py), and the reshard-on-load checkpoint
format makes the dp-width change free; the data-iterator plane makes
the resume sample-exact.

Restart discipline: bounded attempts with exponential backoff, and a
typed :class:`ElasticGiveUpError` when the budget is exhausted or the
surviving world is smaller than ``min_slots`` — a supervisor that
retries forever against a dead cluster is worse than one that fails
loudly.

The supervisor itself is deliberately jax-free (it imports only stdlib
+ the heartbeat reader): it must keep running when the worker runtime
is the thing that is broken.
"""
from __future__ import annotations

import collections
import subprocess
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from ..telemetry.heartbeat import StragglerMonitor, read_heartbeats
from ..utils.logging import logger
from .supervise import (backoff_delay, dump_supervisor_flightrec,
                        sweep_heartbeat_files, terminate_with_grace)

#: env vars the supervisor exports to every worker attempt
ELASTIC_RESTART_ENV = "DS_ELASTIC_RESTART"
ELASTIC_SLOTS_ENV = "DS_ELASTIC_WORLD_SLOTS"

#: probe_fn return sentinel: host alive, keep its current slots
KEEP_SLOTS = True


class ElasticGiveUpError(RuntimeError):
    """The supervisor is out of options: restart budget exhausted, or
    the surviving world fell below ``min_slots``.  Carries the restart
    count and the last failure reason so orchestrators can act on it."""

    def __init__(self, message: str, restarts: int = 0,
                 last_failure: str = ""):
        super().__init__(message)
        self.restarts = restarts
        self.last_failure = last_failure


class RestartPolicy(NamedTuple):
    """Bounded-restart discipline.  ``max_restarts`` counts RELAUNCHES
    (0 = one attempt, never restart); backoff is exponential from
    ``backoff_base_s``, capped at ``backoff_max_s``.  ``min_slots`` is
    the smallest total chip count worth resuming at — below it the
    supervisor gives up instead of limping (a dp1 "fleet" resuming a
    dp512 run is usually a paging alert, not a training run)."""
    max_restarts: int = 3
    backoff_base_s: float = 1.0
    backoff_max_s: float = 60.0
    min_slots: int = 1


class ElasticSupervisor:
    """Launch → watch (exits + heartbeats) → kill → re-probe → re-form →
    relaunch, bounded by a :class:`RestartPolicy`.

    ``resources``  {host: [slot, ...]} — the initial active world
                   (hostfile order preserved; it IS the rank order).
    ``launch_fn``  (active_resources, attempt) -> [(host, Popen), ...]
                   — starts one worker process handle per host.  The
                   supervisor owns the handles from then on.
    ``probe_fn``   host -> None (dead) | True (alive, keep slots) |
                   [slot, ...] (alive at a CHANGED slot set — partial
                   chip loss).  Called only between attempts.
    ``heartbeat_dir`` / ``heartbeat_timeout_s`` — liveness: a host
                   whose newest beat is older than the timeout while
                   the job still runs is HUNG; the attempt is killed
                   and restarted (the stale host must then fail its
                   probe to be dropped — hung-but-probeable hosts get
                   another chance at the reduced backoff cost).
                   Stragglers (slow, not dead) are logged via
                   :class:`StragglerMonitor`, never killed here —
                   killing on slowness is an operator policy, not a
                   supervisor default.
    """

    def __init__(self, resources: Dict[str, List[int]],
                 launch_fn: Callable[[Dict[str, List[int]], int],
                                     List[Tuple[str, subprocess.Popen]]],
                 probe_fn: Optional[Callable[[str], object]] = None,
                 policy: RestartPolicy = RestartPolicy(),
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_timeout_s: float = 0.0,
                 straggler_ratio: float = 2.0,
                 poll_interval_s: float = 0.2,
                 term_grace_s: float = 10.0,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 remote_kill_fn: Optional[Callable[[str], None]] = None):
        if not resources:
            raise ValueError("elastic supervisor needs a non-empty "
                             "resource pool")
        self.active: Dict[str, List[int]] = collections.OrderedDict(
            (h, list(s)) for h, s in resources.items())
        self.launch_fn = launch_fn
        self.probe_fn = probe_fn if probe_fn is not None else (
            lambda host: KEEP_SLOTS)
        self.policy = policy
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.term_grace_s = float(term_grace_s)
        # sleep_fn virtualizes the BACKOFF waits (the test seam); the
        # _watch poll uses real time — Popen.poll and heartbeat mtimes
        # advance on the wall clock, not a fake one
        self.sleep_fn = sleep_fn
        self.remote_kill_fn = remote_kill_fn
        self._straggler = StragglerMonitor(
            ratio=straggler_ratio,
            stale_after_s=max(heartbeat_timeout_s, 1.0))
        self.restarts = 0  # relaunches performed so far
        #: supervisor-side flight recorder (docs/observability.md):
        #: bounded ring of launch/failure/probe events, dumped as
        #: flightrec_supervisor.json on ElasticGiveUpError so the
        #: post-mortem survives the dead fleet.  Kept jax-free (no
        #: telemetry hub import) — same schema, written inline.
        self.events: collections.deque = collections.deque(maxlen=256)

    def _record(self, kind: str, **fields) -> None:
        ev = {"t": time.time(), "kind": kind}
        ev.update(fields)
        self.events.append(ev)

    def _dump_flight_record(self, reason: str, error: str) -> None:
        dump_supervisor_flightrec(
            self.heartbeat_dir, supervisor="supervisor", reason=reason,
            error=error, restarts=self.restarts,
            max_restarts=self.policy.max_restarts,
            fallback="give up (typed ElasticGiveUpError)",
            events=self.events,
            extra={"active_world": {h: list(s) for h, s
                                    in self.active.items()}})

    # -- policy helpers -------------------------------------------------
    def total_slots(self) -> int:
        return sum(len(s) for s in self.active.values())

    def _check_viable(self, last_failure: str) -> None:
        slots = self.total_slots()
        if not self.active or slots < self.policy.min_slots:
            msg = (
                f"elastic: surviving world has {slots} slot(s) across "
                f"{len(self.active)} host(s), below min_slots="
                f"{self.policy.min_slots} — giving up after "
                f"{self.restarts} restart(s); last failure: "
                f"{last_failure or 'n/a'}")
            self._record("give_up", error=msg)
            self._dump_flight_record("ElasticGiveUpError: world below "
                                     "min_slots", msg)
            raise ElasticGiveUpError(msg, restarts=self.restarts,
                                     last_failure=last_failure)

    # -- the run loop ---------------------------------------------------
    def run(self) -> int:
        """Supervise until a clean exit (returns 0) or a typed give-up.
        Every relaunch resumes from the newest verified tag via the
        worker's own ``load_checkpoint(tag=None)`` fallback chain."""
        last_failure = ""
        while True:
            self._sweep_heartbeats()
            logger.info(
                "elastic: launching attempt %d on %d host(s) / %d "
                "slot(s): %s", self.restarts, len(self.active),
                self.total_slots(),
                ", ".join(f"{h}:{len(s)}"
                          for h, s in self.active.items()))
            self._record("launch", attempt=self.restarts,
                         hosts=len(self.active),
                         slots=self.total_slots())
            procs = self.launch_fn(self.active, self.restarts)
            rc, reason = self._watch(procs)
            if rc == 0:
                logger.info("elastic: job completed cleanly after %d "
                            "restart(s)", self.restarts)
                return 0
            last_failure = reason
            self._record("failure", attempt=self.restarts, rc=rc,
                         error=reason)
            logger.warning("elastic: attempt %d FAILED: %s",
                           self.restarts, reason)
            if self.restarts >= self.policy.max_restarts:
                msg = (f"elastic: giving up after {self.restarts} "
                       f"restart(s) (max_restarts="
                       f"{self.policy.max_restarts}); last failure: "
                       f"{reason}")
                self._record("give_up", error=msg)
                self._dump_flight_record(
                    "ElasticGiveUpError: restart budget exhausted", msg)
                raise ElasticGiveUpError(msg, restarts=self.restarts,
                                         last_failure=reason)
            self.restarts += 1
            self._reprobe()
            self._check_viable(last_failure)
            delay = backoff_delay(self.policy.backoff_base_s,
                                  self.policy.backoff_max_s,
                                  self.restarts)
            logger.info("elastic: backing off %.1fs before relaunch "
                        "(attempt %d/%d)", delay, self.restarts,
                        self.policy.max_restarts)
            if delay > 0:
                self.sleep_fn(delay)

    # -- one attempt ----------------------------------------------------
    def _watch(self, procs) -> Tuple[Optional[int], str]:
        """Poll worker exits and heartbeats until the attempt resolves:
        (0, "") on a fully clean exit; (rc/None, reason) on any worker
        failure or missed heartbeats — the remnants are killed first,
        so a half-dead job can never wedge a barrier forever."""
        while True:
            states = [(host, p, p.poll()) for host, p in procs]
            failed = [(h, rc) for h, _, rc in states
                      if rc is not None and rc != 0]
            if failed:
                self._kill(procs)
                host, rc = failed[0]
                return rc, (f"worker on {host} exited rc={rc}"
                            + (f" (+{len(failed) - 1} more)"
                               if len(failed) > 1 else ""))
            if all(rc == 0 for _, _, rc in states):
                return 0, ""
            # staleness applies only while EVERY worker still runs: once
            # one exits 0 the job is in its shutdown skew window (e.g.
            # rank 0 writing the final checkpoint after the others left)
            # and the finished workers' beats going stale is healthy,
            # not a hang
            stale = ([] if any(rc == 0 for _, _, rc in states)
                     else self._heartbeat_check())
            if stale:
                self._kill(procs)
                return None, ("missed heartbeats from "
                              + ", ".join(stale)
                              + f" (> {self.heartbeat_timeout_s:.0f}s "
                              "stale; host hung)")
            time.sleep(self.poll_interval_s)

    def _heartbeat_check(self) -> List[str]:
        """Hosts whose newest beat went stale (only hosts that have
        beaten at least once this attempt — the dir is swept before
        each launch, and startup/compile time must not count)."""
        if not self.heartbeat_dir or self.heartbeat_timeout_s <= 0:
            return []
        beats = read_heartbeats(self.heartbeat_dir)
        if not beats:
            return []
        rep = self._straggler.update(beats)
        if rep["new_stragglers"]:
            logger.warning(
                "elastic: straggler(s) %s — step time > %.1fx the fleet "
                "median of %.3fs (not killing; straggler policy is the "
                "operator's)", ", ".join(rep["new_stragglers"]),
                self._straggler.ratio, rep["median_step_s"] or 0.0)
        now = time.time()
        return sorted(k for k, r in beats.items()
                      if now - float(r.get("time", 0))
                      > self.heartbeat_timeout_s)

    def _kill(self, procs) -> None:
        """SIGTERM → grace → SIGKILL + remote cleanup, via the shared
        supervision helper (launcher/supervise.py)."""
        terminate_with_grace(procs, self.term_grace_s,
                             remote_kill_fn=self.remote_kill_fn)

    def _sweep_heartbeats(self) -> None:
        sweep_heartbeat_files(self.heartbeat_dir)

    def _reprobe(self) -> None:
        """Re-form the world from the hosts that still answer: dead
        hosts drop out (the relaunch shrinks dp), resized hosts keep
        their surviving slots.  Order is preserved — it IS rank order,
        and the new rank-0 host becomes the coordinator."""
        survivors = collections.OrderedDict()
        for host, slots in self.active.items():
            try:
                r = self.probe_fn(host)
            except Exception as e:
                logger.warning("elastic: probe of %s raised %s — "
                               "treating as dead", host, e)
                r = None
            if r is None or r is False:
                logger.warning("elastic: host %s failed its probe — "
                               "dropped from the world", host)
                continue
            if isinstance(r, (list, tuple)):
                new_slots = [int(x) for x in r]
                if new_slots != slots:
                    logger.warning(
                        "elastic: host %s resized %d -> %d slot(s)",
                        host, len(slots), len(new_slots))
                survivors[host] = new_slots
            else:
                survivors[host] = slots
        self.active = survivors
