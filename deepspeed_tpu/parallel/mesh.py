"""Device-mesh construction — the TPU replacement for the reference's
process-group zoo (reference: deepspeed/runtime/pipe/topology.py:252-455 and
the NCCL init at runtime/engine.py:125-145).

One ``jax.sharding.Mesh`` with named axes replaces all NCCL communicators:
  - ``data``  axis ↔ DP groups (gradient psum / ZeRO reduce-scatter)
  - ``model`` axis ↔ Megatron slice groups (TP collectives)
  - ``pipe``  axis ↔ stage p2p pair groups (ppermute)
Axis order places ``pipe`` outermost (slow links OK — p2p is latency-bound,
low volume) and ``model`` innermost (fastest ICI — TP collectives are in the
critical path of every matmul), matching the scaling-book recipe and the
reference's own axis-ordering rationale (topology.py:235-243).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
DEFAULT_AXES: Tuple[str, str, str, str] = (
    PIPE_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)


def build_mesh(pp: int = 1,
               dp: Optional[int] = None,
               tp: int = 1,
               sp: int = 1,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build a (pipe, data, seq, model) mesh over the available devices.

    ``dp=None`` absorbs whatever device count remains after pp×sp×tp.
    ``sp`` is the sequence/context-parallel axis consumed by
    parallel/sequence.py (ring / Ulysses attention); it sits between data
    (slow OK) and model (fastest ICI) because ring rotations are
    bandwidth-hungry but latency-tolerant.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        if n % (pp * tp * sp) != 0:
            raise ValueError(
                f"device count {n} not divisible by pp*sp*tp="
                f"{pp * sp * tp}")
        dp = n // (pp * tp * sp)
    if pp * dp * sp * tp != n:
        raise ValueError(
            f"pp*dp*sp*tp = {pp}*{dp}*{sp}*{tp} != device count {n}")
    dev_array = np.asarray(devices).reshape(pp, dp, sp, tp)
    return Mesh(dev_array, DEFAULT_AXES)


def single_device_mesh() -> Mesh:
    return build_mesh(pp=1, dp=1, tp=1, devices=jax.devices()[:1])


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, rank: int = 1, batch_dim: int = 0) -> NamedSharding:
    """Batch sharding over the data axis for an array of given rank."""
    spec = [None] * rank
    spec[batch_dim] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))
