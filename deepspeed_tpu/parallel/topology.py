"""N-D cartesian process topology.

Plays the role of the reference's ``ProcessTopology`` /
``PipelineParallelGrid`` (reference: deepspeed/runtime/pipe/topology.py:12-455)
but re-founded on the JAX mesh model: an axis here IS a mesh axis name, and
"process groups" are replaced by axis-local collectives.  The pure
rank↔coordinate math is kept because pipeline-stage assignment, checkpoint
naming, and tests all need it without any hardware.

Axis order convention (outermost → innermost) follows the reference's
rationale (topology.py:235-243 there): the innermost axis maps to adjacent
ranks, which on TPU means the fastest ICI links — so ``data`` (the
bandwidth-hungry gradient axis) goes innermost and ``pipe`` (latency-bound
p2p) outermost, with DCN carrying the outermost splits on multi-slice.
"""
from __future__ import annotations

from collections import namedtuple
from itertools import product
from typing import Dict, List, Sequence


class ProcessTopology:
    """Maps n-dimensional cartesian coordinates to linear (row-major) ranks."""

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        if len(set(axes)) != len(axes):
            raise ValueError(f"duplicate axis names in {axes}")
        for d in dims:
            if not isinstance(d, int) or d < 1:
                raise ValueError(f"axis dims must be positive ints, got {dims}")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self._coord_to_rank: Dict[tuple, int] = {}
        self._rank_to_coord: List[tuple] = []
        for rank, coord in enumerate(product(*(range(d) for d in self.dims))):
            c = self.ProcessCoord(*coord)
            self._coord_to_rank[c] = rank
            self._rank_to_coord.append(c)

    def world_size(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def get_rank(self, **coords) -> int:
        if sorted(coords.keys()) != sorted(self.axes):
            raise ValueError(
                f"get_rank requires all axes {self.axes}, got {list(coords)}")
        return self._coord_to_rank[self.ProcessCoord(**coords)]

    def get_coord(self, rank: int):
        return self._rank_to_coord[rank]

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def get_rank_repr(self, rank: int, omit_axes=("data",), inner_sep="_",
                      outer_sep="-") -> str:
        """Checkpoint-path naming: e.g. rank → 'pipe_00-model_00'."""
        coord = self.get_coord(rank)
        parts = []
        for ax, idx in zip(self.axes, coord):
            if ax in omit_axes:
                continue
            parts.append(f"{ax}{inner_sep}{idx:02d}")
        return outer_sep.join(parts)

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that vary only along ``axis`` — the rank-sets that
        would form one communicator in the reference; on TPU this is exactly
        the set of ranks a collective over mesh axis ``axis`` spans."""
        if axis not in self.axes:
            return []
        other = [a for a in self.axes if a != axis]
        lists = []
        for combo in product(*(range(self.get_dim(a)) for a in other)):
            fixed = dict(zip(other, combo))
            lists.append([self.get_rank(**{axis: i, **fixed})
                          for i in range(self.get_dim(axis))])
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """All ranks whose coordinates match the given axis=value constraints."""
        def match(rank):
            coord = self.get_coord(rank)
            return all(getattr(coord, ax) == v for ax, v in filter_kwargs.items())
        return [r for r in range(self.world_size()) if match(r)]

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return self.filter_match(**{axis: idx})

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """2-D pipe × data topology (reference: topology.py:235-243)."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3-D pipe × data × model topology (reference: topology.py:246-249)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class ParallelGrid:
    """mpu-style facade over a topology for one SPMD participant.

    The reference's ``PipelineParallelGrid`` (topology.py:252-455 there)
    builds a zoo of torch process groups; here the same queries are answered
    from pure coordinate math, and "group" handles are mesh axis names.
    """

    def __init__(self, topology: ProcessTopology, rank: int = 0):
        self._topo = topology
        self.global_rank = rank
        self.world_size = topology.world_size()

    # --- generic ---
    def _axis_info(self, axis: str):
        if axis in self._topo.axes:
            coord = self._topo.get_coord(self.global_rank)
            return getattr(coord, axis), self._topo.get_dim(axis)
        return 0, 1

    # --- pipe ---
    def get_pipe_parallel_rank(self):
        return self._axis_info("pipe")[0]

    def get_pipe_parallel_world_size(self):
        return self._axis_info("pipe")[1]

    def get_pipe_parallel_group(self):
        return "pipe"

    def get_stage_id(self):
        return self.get_pipe_parallel_rank()

    def is_first_stage(self):
        return self.get_pipe_parallel_rank() == 0

    def is_last_stage(self):
        return self.get_pipe_parallel_rank() == self.get_pipe_parallel_world_size() - 1

    # --- data ---
    def get_data_parallel_rank(self):
        return self._axis_info("data")[0]

    def get_data_parallel_world_size(self):
        return self._axis_info("data")[1]

    def get_data_parallel_group(self):
        return "data"

    # --- model (tensor) ---
    def get_model_parallel_rank(self):
        return self._axis_info("model")[0]

    def get_model_parallel_world_size(self):
        return self._axis_info("model")[1]

    def get_model_parallel_group(self):
        return "model"

    # reference alias: "slice" == model/tensor axis (topology.py:344-364)
    get_slice_parallel_rank = get_model_parallel_rank
    get_slice_parallel_world_size = get_model_parallel_world_size
    get_slice_parallel_group = get_model_parallel_group

    def get_global_rank(self):
        return self.global_rank

    def stage_to_global(self, stage_id: int, **kwargs) -> int:
        """Global rank of the same (data, model) coordinate at another stage."""
        coord = self._topo.get_coord(self.global_rank)
        d = coord._asdict()
        d.update(kwargs)
        d["pipe"] = stage_id
        return self._topo.get_rank(**d)
