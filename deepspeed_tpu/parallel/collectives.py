"""Named-axis collective wrappers for use inside ``shard_map``-ed code.

The TPU-native communication backend (reference inventory: SURVEY.md §2.5;
reference backend = torch.distributed/NCCL at deepspeed/runtime/engine.py:130
plus pair-group broadcast p2p at runtime/pipe/p2p.py:31-55).  Mapping:

  dist.all_reduce      → psum / pmean        (XLA all-reduce over ICI)
  dist.reduce_scatter  → reduce_scatter      (lax.psum_scatter)
  dist.all_gather      → all_gather
  pipe p2p send/recv   → ppermute_shift      (neighbor exchange on the ring)
  dist.broadcast       → pbroadcast_from

Under jit+GSPMD most of these are implicit in sharding annotations; these
explicit forms exist for shard_map regions (pipeline schedules, 1-bit Adam)
where manual placement is the point.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis: str):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis_name=axis)


def pmax(x, axis: str):
    return lax.pmax(x, axis_name=axis)


def reduce_scatter(x, axis: str, scatter_dimension: int = 0, tiled: bool = True):
    """Sum-reduce over ``axis`` and leave each participant with its shard —
    the ZeRO gradient-partition primitive (reference: stage1.py:583,
    stage2.py:675-738 reimplemented as one XLA op)."""
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def all_gather(x, axis: str, gather_dimension: int = 0, tiled: bool = True):
    """Reassemble shards along ``axis`` — the ZeRO param all-gather
    (reference: stage2.py:1438-1471)."""
    return lax.all_gather(x, axis_name=axis, axis=gather_dimension, tiled=tiled)


def ppermute_shift(x, axis: str, shift: int = 1, wrap: bool = True):
    """Send to the ``+shift`` neighbor along ``axis`` (pipeline p2p; replaces
    the pair-group broadcast trick at reference runtime/pipe/p2p.py:31-55).
    With ``wrap=False`` the first ``shift`` participants receive zeros."""
    n = lax.axis_size(axis)
    if wrap:
        perm = [(i, (i + shift) % n) for i in range(n)]
    else:
        perm = [(i, i + shift) for i in range(n) if 0 <= i + shift < n]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def pbroadcast_from(x, axis: str, root: int = 0):
    """Broadcast the root participant's value to all along ``axis``."""
    idx = lax.axis_index(axis)
    zero = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(zero, axis_name=axis)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)
