from .topology import (
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    ParallelGrid,
)
from .partition import partition_uniform, partition_balanced
from .mesh import (
    build_mesh,
    single_device_mesh,
    mesh_axis_size,
    replicated,
    data_sharded,
    PIPE_AXIS,
    DATA_AXIS,
    SEQ_AXIS,
    MODEL_AXIS,
    DEFAULT_AXES,
)
from .sequence import ring_attention, ulysses_attention
from . import collectives
