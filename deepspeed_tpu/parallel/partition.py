"""Balanced partitioning of weighted items into contiguous parts.

Used for pipeline layer assignment (reference: deepspeed/runtime/utils.py:295-377
``partition_uniform``/``partition_balanced``).  The balanced variant here is a
binary search on the bottleneck capacity with a greedy feasibility sweep —
O(n log(sum(weights))) — rather than the reference's probe machinery; output
contract is identical: ``parts`` of length ``num_parts+1`` with
``parts[p] .. parts[p+1]`` the half-open item range of part ``p``.
"""
from __future__ import annotations

from typing import List, Sequence


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    parts = [0] * (num_parts + 1)
    if num_parts == 0:
        return parts
    base = num_items // num_parts
    extra = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + base + (1 if p < extra else 0)
    assert parts[-1] == num_items
    return parts


def _feasible(weights: Sequence[float], num_parts: int, cap: float) -> bool:
    """Can weights be split into <= num_parts contiguous chunks each <= cap?"""
    count, acc = 1, 0.0
    for w in weights:
        if w > cap:
            return False
        if acc + w > cap:
            count += 1
            acc = w
            if count > num_parts:
                return False
        else:
            acc += w
    return True


def partition_balanced(weights: Sequence[float], num_parts: int,
                       eps: float = 1e-3) -> List[int]:
    """Minimize the max part weight over contiguous partitions."""
    n = len(weights)
    if n == 0 or num_parts <= 0:
        return [0] * (num_parts + 1)
    if num_parts >= n:
        # one item per part, trailing empty parts
        parts = list(range(n + 1)) + [n] * (num_parts - n)
        return parts

    lo, hi = max(weights), sum(weights)
    while hi - lo > eps * max(1.0, lo):
        mid = (lo + hi) / 2
        if _feasible(weights, num_parts, mid):
            hi = mid
        else:
            lo = mid
    cap = hi

    # Greedy sweep at the found capacity.  Feasibility guarantees <= num_parts
    # chunks; the must_split guard keeps enough items in reserve that every
    # remaining part ends up non-empty.
    parts = [0]
    acc = 0.0
    for i, w in enumerate(weights):
        interior_remaining = (num_parts - 1) - (len(parts) - 1)
        if interior_remaining > 0 and i > parts[-1]:
            must_split = (n - i) <= interior_remaining
            if must_split or acc + w > cap:
                parts.append(i)
                acc = 0.0
        acc += w
    parts.append(n)
    assert len(parts) == num_parts + 1, (parts, num_parts)
    assert all(parts[i] < parts[i + 1] for i in range(num_parts))
    return parts


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    out, acc = [], 0.0
    for w in weights:
        acc += w
        out.append(acc)
    return out
