"""Multi-host process-group initialization.

The reference engine owns distributed init: ``DeepSpeedEngine.__init__``
calls ``dist.init_process_group('nccl')``, with MPI discovery feeding the
env (reference: deepspeed/runtime/engine.py:125-145, 202-239).  The TPU
equivalent is ``jax.distributed.initialize()`` consuming the env contract
our per-node launcher exports (launcher/launch.py:49-63):

  JAX_COORDINATOR_ADDRESS   host:port of process 0
  JAX_NUM_PROCESSES         number of host processes
  JAX_PROCESS_ID            this process's rank

``deepspeed_tpu.initialize()`` calls :func:`init_distributed`
automatically, so a script launched with ``bin/ds --hostfile ...`` joins
the job-wide process group with no extra code — same UX as the reference
(engine.py:130-139).  Direct engine users on a pod can call it themselves.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from ..utils.logging import log_dist

_initialized = False


def is_initialized() -> bool:
    return _initialized


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None
                     ) -> bool:
    """Join the multi-process JAX runtime if the launcher env contract (or
    explicit arguments) describe one.  Returns True iff
    ``jax.distributed.initialize`` was called.  Safe to call repeatedly
    and in single-process runs (no-op there, like the reference's
    ``dist.is_initialized()`` guard, engine.py:131-134)."""
    global _initialized
    if _initialized:
        return False
    coord = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = (num_processes if num_processes is not None
             else int(os.environ.get("JAX_NUM_PROCESSES", "0") or 0))
    pid = (process_id if process_id is not None
           else int(os.environ.get("JAX_PROCESS_ID", "0") or 0))
    if not coord or nproc <= 1:
        return False
    import jax
    # user already joined the runtime themselves (reference analogue:
    # dist.is_initialized() short-circuit, engine.py:131-134)
    try:
        from jax._src.distributed import global_state
        if getattr(global_state, "client", None) is not None:
            _initialized = True
            return False
    except ImportError:
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nproc,
            process_id=pid,
            local_device_ids=local_device_ids)
    except RuntimeError as e:
        # jax refuses to join after the XLA backend initialized (any
        # jax.devices()/build_mesh call does that) — surface an actionable
        # error instead of jax's generic one.  Only rewrite THAT failure;
        # coordinator-connection/timeout RuntimeErrors pass through.
        if "backend" in str(e).lower() and "initial" in str(e).lower():
            raise RuntimeError(
                "deepspeed_tpu found a multi-host launcher env "
                f"(JAX_NUM_PROCESSES={nproc}) but the XLA backend is "
                "already initialized, so this process cannot join the "
                "job-wide runtime. Call deepspeed_tpu.init_distributed() "
                "(or deepspeed_tpu.initialize()) BEFORE any jax.devices()/"
                "build_mesh()/array call.") from e
        raise
    _initialized = True
    log_dist(
        f"jax.distributed initialized: process {pid}/{nproc} "
        f"coordinator={coord}", ranks=[0])
    return True
