"""Sequence / context parallelism — ring attention and Ulysses all-to-all.

The reference (v0.3.2) has no sequence parallelism; its long-sequence
feature is block-sparse attention (SURVEY.md §2.4: this is the modern
equivalent occupying that feature slot, built mesh-native from day one).

Two schemes over a named mesh axis (run inside ``shard_map`` with the
sequence dimension sharded):

  ring_attention(q, k, v, axis_name, causal=True)
      Blockwise-softmax attention where K/V shards rotate around the ring
      via ``ppermute`` while each device keeps its query shard (Ring
      Attention; the online-softmax accumulation is the flash-attention
      recurrence).  Peak memory O(T_local² + T_local·D) per device;
      communication N-1 rotations of the local K/V shard over ICI.

  ulysses_attention(q, k, v, axis_name, causal=True)
      DeepSpeed-Ulysses-style: ``all_to_all`` re-shards [seq → heads], each
      device computes full-sequence attention for H/N heads — through the
      Pallas flash kernel by default (O(T_global·D) per-device attention
      memory; ``local_impl='dense'`` keeps the fp32 einsum path for
      debugging) — then ``all_to_all`` back.  Requires num_heads %
      ring_size == 0; communication 2 all-to-alls of the activations.

Both support attention-probability dropout via the flash kernel's
position-hashed keep mask over GLOBAL coordinates (seeded by a
replicated uint32), so the realization is layout-independent.

Both are differentiable (ppermute/all_to_all transpose to themselves under
AD) and validated against dense full-sequence attention in
tests/test_sequence_parallel.py.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

SEQ_AXIS = "seq"

_NEG = -1e30


def _block_scores(q, k, sm_scale):
    """[B,H,Tq,D] x [B,H,Tk,D] → fp32 scores [B,H,Tq,Tk]."""
    return jnp.einsum("bhqd,bhkd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * sm_scale


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str = SEQ_AXIS,
                   causal: bool = True,
                   sm_scale: Optional[float] = None,
                   dropout_rate: float = 0.0,
                   dropout_seed=None,
                   rank=None) -> jnp.ndarray:
    """Ring attention over a sharded sequence.

    q, k, v: this shard's slice [B, H, T_local, D] (sequence dim sharded
    over ``axis_name``).  Returns the local output shard [B, H, T_local, D].

    ``dropout_rate`` > 0 applies attention-probability dropout using the
    flash kernel's position-hashed keep mask (global coordinates —
    shard-layout-independent), seeded by ``dropout_seed`` (uint32 scalar,
    replicated).

    ``rank``: this device's index on ``axis_name``.  Defaults to
    ``jax.lax.axis_index`` — but inside a NESTED shard_map (the pipeline
    engine's 'pipe'-manual region) axis_index lowers to an
    sdy.manual_computation over the complement axes, which re-binds the
    ancestor's manual axis and fails MLIR verification; callers there
    pass the rank as an operand (a P(axis)-sharded iota).
    """
    B, H, T, D = q.shape
    n = jax.lax.axis_size(axis_name)
    idx = (jax.lax.axis_index(axis_name) if rank is None
           else jnp.reshape(rank, ()).astype(jnp.int32))
    scale = float(D) ** -0.5 if sm_scale is None else sm_scale
    if dropout_rate > 0.0:
        assert dropout_seed is not None, \
            "dropout_rate > 0 requires dropout_seed"
        from ..ops.pallas.flash_attention import dropout_keep_mask

    q32 = q.astype(jnp.float32)
    pos_local = jnp.arange(T)
    q_pos = idx * T + pos_local                      # global query positions

    perm = [(i, (i + 1) % n) for i in range(n)]      # rotate shards forward

    def accumulate(o, m, l, kc, vc, step):
        """Online-softmax (flash recurrence) over the chunk that
        originated on rank (idx - step) mod n."""
        src = jnp.mod(idx - step, n)
        k_pos = src * T + pos_local
        s = _block_scores(q32, kc.astype(jnp.float32), scale)
        if causal:
            mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
            s = jnp.where(mask, s, _NEG)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(-1)
        pd = p
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(
                q_pos.astype(jnp.uint32)[None, None, :, None],
                k_pos.astype(jnp.uint32)[None, None, None, :],
                jnp.arange(B * H, dtype=jnp.uint32).reshape(B, H, 1, 1),
                dropout_seed, dropout_rate)
            pd = p * keep.astype(p.dtype) / (1.0 - dropout_rate)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", pd, vc.astype(jnp.float32))
        return o_new, m_new, l_new

    def body(carry, step):
        o, m, l, kc, vc = carry
        o, m, l = accumulate(o, m, l, kc, vc, step)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, m, l, kc, vc), None

    o0 = jnp.zeros((B, H, T, D), jnp.float32)
    m0 = jnp.full((B, H, T), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    carry = (o0, m0, l0, k, v)
    if n > 1:
        # scan covers the n-1 steps that need a rotation afterwards...
        carry, _ = jax.lax.scan(body, carry, jnp.arange(n - 1))
    # ...and the last chunk is consumed without the wasted final rotation
    o, m, l, kc, vc = carry
    o, m, l = accumulate(o, m, l, kc, vc, n - 1)
    # causal first-token rows always see at least their own position → l>0
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str = SEQ_AXIS,
                      causal: bool = True,
                      sm_scale: Optional[float] = None,
                      dropout_rate: float = 0.0,
                      dropout_seed=None,
                      local_impl: str = "flash",
                      rank=None) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme).

    q, k, v: [B, H, T_local, D] with the sequence sharded over
    ``axis_name``; H must be divisible by the axis size.  Internally each
    device attends the FULL sequence for H/n heads — by default through
    the Pallas flash kernel (``local_impl='flash'``), so per-device
    attention memory is O(T_global·D) rather than the O(T_global²)
    scores the dense path materialises; ``local_impl='dense'`` keeps the
    einsum path for debugging.  Dropout uses the same position-hashed
    mask as ring_attention with GLOBAL head indices, so dense, ring, and
    Ulysses realizations agree for one seed.
    """
    B, H, T, D = q.shape
    n = jax.lax.axis_size(axis_name)
    idx = (jax.lax.axis_index(axis_name) if rank is None
           else jnp.reshape(rank, ()).astype(jnp.int32))
    assert H % n == 0, (
        f"ulysses needs heads ({H}) divisible by sequence shards ({n})")
    assert local_impl in ("flash", "dense"), local_impl
    if dropout_rate > 0.0:
        assert dropout_seed is not None, \
            "dropout_rate > 0 requires dropout_seed"

    def seq2head(x):
        # [B, H, T_local, D] → [B, H/n, T_global, D].  Tiled all_to_all:
        # head dim splits n ways, received seq chunks concatenate in
        # device order (= sequence order).  The tiled form's AD transpose
        # is the same-shape tiled all_to_all — the untiled axis-
        # inserting form mis-lowered under grad for B > 1.
        x = x.reshape(B, H, T, D)
        x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                               tiled=True)          # [B, H/n, n·T, D]
        return x

    def head2seq(x):
        # [B, H/n, T_global, D] → [B, H, T_local, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    scale = float(D) ** -0.5 if sm_scale is None else sm_scale
    # this device holds global heads idx*(H/n) .. (idx+1)*(H/n)-1
    heads = (jnp.uint32(idx) * jnp.uint32(H // n)
             + jnp.arange(H // n, dtype=jnp.uint32))
    bh_global = (jnp.arange(B, dtype=jnp.uint32)[:, None] * jnp.uint32(H)
                 + heads[None, :])                      # [B, H/n]
    if local_impl == "flash":
        from ..ops.pallas.flash_attention import flash_attention
        # bh_global is affine in the flattened local grid row g:
        # g = b*(H/n) + j  →  b*H + idx*(H/n) + j
        #   = idx*(H/n) + (g // (H/n))*H + g % (H/n)
        # so it ships as (traced base, static period, static stride) —
        # the kernel's scalar-operand form (see _grid_bh there).
        og = flash_attention(qg, kg, vg, causal=causal, sm_scale=scale,
                             dropout_rate=dropout_rate,
                             dropout_seed=dropout_seed,
                             bh_affine=(jnp.uint32(idx) *
                                        jnp.uint32(H // n), H // n, H))
        return head2seq(og)
    s = _block_scores(qg.astype(jnp.float32), kg.astype(jnp.float32), scale)
    if causal:
        Tg = s.shape[-1]
        mask = jnp.tril(jnp.ones((Tg, Tg), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        from ..ops.pallas.flash_attention import dense_keep_mask
        Tg = p.shape[-1]
        keep = dense_keep_mask(B, H // n, Tg, Tg, dropout_seed,
                               dropout_rate, bh_ids=bh_global.reshape(-1))
        p = p * keep.astype(p.dtype) / (1.0 - dropout_rate)
    og = jnp.einsum("bhqk,bhkd->bhqd", p,
                    vg.astype(jnp.float32)).astype(q.dtype)
    return head2seq(og)
