"""Span tracing → Chrome/Perfetto trace-event JSON.

Spans are HOST-side intervals: ``span()`` stamps ``time.perf_counter``
at enter/exit and appends one complete ("ph": "X") event — no device
sync anywhere in this module.  For compiled-step work that means a span
measures *dispatch* latency, which is exactly the point: the engine
emits a ``train/steps_interval`` span at its periodic ``steps_per_print``
materialization, and that synced interval is the ground truth the
per-step dispatch spans are read against (the same discipline as
``engine._report``; see docs/observability.md).  Unlike the
``wall_clock_breakdown`` timers, tracing never adds a
``block_until_ready`` to the step path.

The exported file loads in ``chrome://tracing`` / Perfetto and in
``json.loads`` — every event carries ``ph``/``ts``/``name`` (the
acceptance contract tests assert).

Causal tracing (docs/observability.md): a :class:`TraceContext` is the
lightweight identity that rides an item across a stage boundary (a
prefetched batch through its channel, a checkpoint job into the writer,
a serve request through its queue), and the ``flow_start`` /
``flow_step`` / ``flow_end`` methods emit Chrome *flow events*
(``ph: s/t/f``) that draw causal arrows between the spans enclosing
them — producer thread to consumer thread.  Flow events are plain
host-side appends emitted INSIDE already-open spans, so the tested
zero-added-device-syncs contract is untouched.  Chrome binds a flow by
the (cat, id, name) triple; emit every phase of one flow with the same
name.  ``flush_flows`` (called by ``export``) terminates flows still
open at shutdown so an aborted run's arrows don't dangle.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_ids = itertools.count(1)


def _next_id() -> int:
    # itertools.count.__next__ is atomic under the GIL
    return next(_ids)


class TraceContext:
    """Process-wide-unique identity for one unit of work crossing a
    stage boundary.  ``trace_id`` is the Chrome flow id; ``span_id`` /
    ``parent_id`` give nested hand-offs (``child()``) a lineage without
    any global registry.  Deliberately tiny: it is attached to every
    prefetched batch and serve request on hot paths."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: int, span_id: int = 0,
                 parent_id: int = 0):
        self.trace_id = int(trace_id)
        self.span_id = int(span_id)
        self.parent_id = int(parent_id)

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=_next_id())

    def child(self) -> "TraceContext":
        """A hand-off one hop further down the same flow."""
        return TraceContext(self.trace_id, span_id=_next_id(),
                            parent_id=self.span_id)

    def __repr__(self):
        return (f"TraceContext(trace_id={self.trace_id}, "
                f"span_id={self.span_id}, parent_id={self.parent_id})")


class AsyncSpan:
    """An open Chrome *async* event pair (``ph: b``/``e``), for
    intervals that overlap other instances of themselves and cross
    threads — per-request serving lifetimes.  Complete (``X``) events
    assume a per-thread call stack and mis-render overlapping,
    non-nested slices; async events are matched by (cat, id, name) and
    render on their own track.  The ``b`` is emitted at construction on
    the opening thread; ``end()`` (idempotent) emits the ``e`` wherever
    the interval actually closes."""

    __slots__ = ("_tracer", "name", "cat", "id", "_done")

    def __init__(self, tracer: "TraceRecorder", name: str, cat: str,
                 span_id: int, args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.id = int(span_id)
        self._done = False
        tracer._emit_async("b", name, cat, self.id, args)

    def end(self, **extra_args):
        if self._done:
            return
        self._done = True
        self._tracer._emit_async("e", self.name, self.cat, self.id,
                                 extra_args or None)


class SpanHandle:
    """An open span; ``end()`` closes it (idempotent).  Used where a
    ``with`` block cannot bracket the interval — e.g. a span opened at
    dispatch and closed at the next periodic sync."""

    __slots__ = ("_tracer", "name", "cat", "args", "_start", "_done")

    def __init__(self, tracer: "TraceRecorder", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._start = tracer._now_us()
        self._done = False

    def end(self, **extra_args):
        if self._done:
            return
        self._done = True
        args = dict(self.args or {})
        args.update(extra_args)
        self._tracer._emit_complete(self.name, self.cat, self._start,
                                    self._tracer._now_us() - self._start,
                                    args or None)


class TraceRecorder:
    """Thread-safe, bounded trace-event buffer.

    ``max_events`` bounds memory for long runs; overflow increments a
    drop counter that ``export`` records as metadata instead of silently
    truncating (the no-silent-caps rule)."""

    def __init__(self, process_name: str = "deepspeed_tpu",
                 pid: int = 0, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._dropped = 0
        self._origin = time.perf_counter()
        self.pid = pid
        self.process_name = process_name
        self.max_events = max_events
        self._tids: Dict[int, int] = {}
        #: flows started but not yet finished: flow_id -> (name, cat);
        #: flush_flows terminates them so arrows never dangle
        self._open_flows: Dict[int, Tuple[str, str]] = {}

    # -- clock / ids ----------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._origin) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
            return tid

    # -- recording ------------------------------------------------------
    def _append(self, ev: dict, force: bool = False) -> bool:
        """``force`` bypasses the cap — used ONLY for flow terminators,
        whose count is bounded by the flow starts already admitted (a
        dropped ``f`` would leave an ``s`` dangling and make diagnose
        report phantom in-flight work on a healthy capped run)."""
        with self._lock:
            if not force and len(self._events) >= self.max_events:
                self._dropped += 1
                return False
            self._events.append(ev)
            return True

    def _emit_complete(self, name: str, cat: str, ts_us: float,
                       dur_us: float, args: Optional[dict]):
        ev = {"name": name, "cat": cat, "ph": "X", "pid": self.pid,
              "tid": self._tid(), "ts": round(ts_us, 3),
              "dur": round(max(dur_us, 0.0), 3)}
        if args:
            ev["args"] = args
        self._append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "runtime", **args):
        handle = SpanHandle(self, name, cat, args or None)
        try:
            yield handle
        finally:
            handle.end()

    def begin(self, name: str, cat: str = "runtime", **args) -> SpanHandle:
        return SpanHandle(self, name, cat, args or None)

    def _emit_async(self, ph: str, name: str, cat: str, span_id: int,
                    args: Optional[dict]):
        ev = {"name": name, "cat": cat, "ph": ph, "id": int(span_id),
              "pid": self.pid, "tid": self._tid(),
              "ts": round(self._now_us(), 3)}
        if args:
            ev["args"] = args
        self._append(ev)

    def async_begin(self, name: str, span_id: int, cat: str = "runtime",
                    **args) -> AsyncSpan:
        """Open an async (``b``/``e``) interval — overlap-safe and
        cross-thread; use for per-request lifetimes where many
        instances of the same name run concurrently."""
        return AsyncSpan(self, name, cat, span_id, args or None)

    def instant(self, name: str, cat: str = "runtime", **args):
        ev = {"name": name, "cat": cat, "ph": "i", "s": "p",
              "pid": self.pid, "tid": self._tid(),
              "ts": round(self._now_us(), 3)}
        if args:
            ev["args"] = args
        self._append(ev)

    # -- flow events (causal arrows between spans) ----------------------
    @staticmethod
    def _flow_id(ctx) -> int:
        return ctx if isinstance(ctx, int) else int(ctx.trace_id)

    def _emit_flow(self, ph: str, name: str, cat: str, ctx,
                   args: Optional[dict]) -> bool:
        ev = {"name": name, "cat": cat, "ph": ph, "id": self._flow_id(ctx),
              "pid": self.pid, "tid": self._tid(),
              "ts": round(self._now_us(), 3)}
        if ph == "f":
            ev["bp"] = "e"  # bind to the enclosing slice, like s/t do
        if args:
            ev["args"] = args
        # terminators ride past the cap: an admitted "s" must never be
        # left dangling because its "f" arrived after the buffer filled
        return self._append(ev, force=(ph == "f"))

    def flow_start(self, name: str, ctx, cat: str = "flow", **args):
        """Open a causal flow INSIDE the producer's span (``ph: s`` —
        the arrow's tail binds to the enclosing slice).  ``ctx`` is a
        :class:`TraceContext` or a bare int flow id."""
        if self._emit_flow("s", name, cat, ctx, args or None):
            with self._lock:
                self._open_flows[self._flow_id(ctx)] = (name, cat)

    def flow_step(self, name: str, ctx, cat: str = "flow", **args):
        """Intermediate hand-off (``ph: t``) — e.g. each decode tick a
        serve request participates in."""
        self._emit_flow("t", name, cat, ctx, args or None)

    def flow_end(self, name: str, ctx, cat: str = "flow", **args):
        """Terminate the flow INSIDE the consumer's span (``ph: f`` with
        ``bp: e`` — the arrowhead binds to the enclosing slice)."""
        with self._lock:
            self._open_flows.pop(self._flow_id(ctx), None)
        self._emit_flow("f", name, cat, ctx, args or None)

    def flush_flows(self) -> int:
        """Terminate every still-open flow (a poisoned stage, a request
        in flight at shutdown) so the trace has no dangling arrows;
        ``export`` calls this.  Returns the number flushed."""
        with self._lock:
            pending = list(self._open_flows.items())
            self._open_flows.clear()
        for fid, (name, cat) in pending:
            self._emit_flow("f", name, cat, fid, {"flushed": True})
        return len(pending)

    def counter(self, name: str, values: Dict[str, float],
                cat: str = "runtime"):
        """Chrome counter-track event ("ph": "C") — HBM over time renders
        as a filled graph in the trace viewer."""
        self._append({"name": name, "cat": cat, "ph": "C", "pid": self.pid,
                      "tid": 0, "ts": round(self._now_us(), 3),
                      "args": {k: float(v) for k, v in values.items()}})

    # -- introspection / export -----------------------------------------
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def export(self, path: str):
        """Write the Chrome trace-event JSON object form."""
        self.flush_flows()
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        meta = [{"name": "process_name", "ph": "M", "pid": self.pid,
                 "tid": 0, "ts": 0,
                 "args": {"name": self.process_name}}]
        payload = {"traceEvents": meta + events,
                   "displayTimeUnit": "ms"}
        if dropped:
            payload["otherData"] = {"dropped_events": dropped}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path
