"""Span tracing → Chrome/Perfetto trace-event JSON.

Spans are HOST-side intervals: ``span()`` stamps ``time.perf_counter``
at enter/exit and appends one complete ("ph": "X") event — no device
sync anywhere in this module.  For compiled-step work that means a span
measures *dispatch* latency, which is exactly the point: the engine
emits a ``train/steps_interval`` span at its periodic ``steps_per_print``
materialization, and that synced interval is the ground truth the
per-step dispatch spans are read against (the same discipline as
``engine._report``; see docs/observability.md).  Unlike the
``wall_clock_breakdown`` timers, tracing never adds a
``block_until_ready`` to the step path.

The exported file loads in ``chrome://tracing`` / Perfetto and in
``json.loads`` — every event carries ``ph``/``ts``/``name`` (the
acceptance contract tests assert).
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, List, Optional


class SpanHandle:
    """An open span; ``end()`` closes it (idempotent).  Used where a
    ``with`` block cannot bracket the interval — e.g. a span opened at
    dispatch and closed at the next periodic sync."""

    __slots__ = ("_tracer", "name", "cat", "args", "_start", "_done")

    def __init__(self, tracer: "TraceRecorder", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._start = tracer._now_us()
        self._done = False

    def end(self, **extra_args):
        if self._done:
            return
        self._done = True
        args = dict(self.args or {})
        args.update(extra_args)
        self._tracer._emit_complete(self.name, self.cat, self._start,
                                    self._tracer._now_us() - self._start,
                                    args or None)


class TraceRecorder:
    """Thread-safe, bounded trace-event buffer.

    ``max_events`` bounds memory for long runs; overflow increments a
    drop counter that ``export`` records as metadata instead of silently
    truncating (the no-silent-caps rule)."""

    def __init__(self, process_name: str = "deepspeed_tpu",
                 pid: int = 0, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._dropped = 0
        self._origin = time.perf_counter()
        self.pid = pid
        self.process_name = process_name
        self.max_events = max_events
        self._tids: Dict[int, int] = {}

    # -- clock / ids ----------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._origin) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
            return tid

    # -- recording ------------------------------------------------------
    def _append(self, ev: dict):
        with self._lock:
            if len(self._events) >= self.max_events:
                self._dropped += 1
                return
            self._events.append(ev)

    def _emit_complete(self, name: str, cat: str, ts_us: float,
                       dur_us: float, args: Optional[dict]):
        ev = {"name": name, "cat": cat, "ph": "X", "pid": self.pid,
              "tid": self._tid(), "ts": round(ts_us, 3),
              "dur": round(max(dur_us, 0.0), 3)}
        if args:
            ev["args"] = args
        self._append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "runtime", **args):
        handle = SpanHandle(self, name, cat, args or None)
        try:
            yield handle
        finally:
            handle.end()

    def begin(self, name: str, cat: str = "runtime", **args) -> SpanHandle:
        return SpanHandle(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "runtime", **args):
        ev = {"name": name, "cat": cat, "ph": "i", "s": "p",
              "pid": self.pid, "tid": self._tid(),
              "ts": round(self._now_us(), 3)}
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, values: Dict[str, float],
                cat: str = "runtime"):
        """Chrome counter-track event ("ph": "C") — HBM over time renders
        as a filled graph in the trace viewer."""
        self._append({"name": name, "cat": cat, "ph": "C", "pid": self.pid,
                      "tid": 0, "ts": round(self._now_us(), 3),
                      "args": {k: float(v) for k, v in values.items()}})

    # -- introspection / export -----------------------------------------
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def export(self, path: str):
        """Write the Chrome trace-event JSON object form."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        meta = [{"name": "process_name", "ph": "M", "pid": self.pid,
                 "tid": 0, "ts": 0,
                 "args": {"name": self.process_name}}]
        payload = {"traceEvents": meta + events,
                   "displayTimeUnit": "ms"}
        if dropped:
            payload["otherData"] = {"dropped_events": dropped}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path
