"""Per-host heartbeats + straggler detection (docs/elastic.md).

Every training process writes a tiny JSON heartbeat file each step
(atomic tmp+replace, so readers never see a torn record) into a shared
directory — the liveness channel the elastic supervisor watches: a host
whose beat goes stale is hung (wedged collective, dead NIC) even though
its process is still "running", and the supervisor treats that as a
failure.  The same records carry the host-side wall time between beats,
which the :class:`StragglerMonitor` compares against the fleet median —
a host consistently slower than ``ratio`` × median is flagged
(``straggler_detected_total``), because in SPMD training the whole
fleet steps at the pace of its slowest member.

Writers must never take the training loop down: a failed beat degrades
to a one-time warning.  Stdlib only (the supervisor imports this
without jax).
"""
from __future__ import annotations

import json
import os
import socket
import statistics
import time
from typing import Dict, Optional

from ..utils.logging import logger

HEARTBEAT_PREFIX = "heartbeat_"

#: env var the elastic supervisor sets for its workers — the engine
#: starts beating when it is present, no config needed
HEARTBEAT_DIR_ENV = "DS_HEARTBEAT_DIR"


class HeartbeatWriter:
    """One process's heartbeat: ``beat(step)`` atomically rewrites
    ``<dir>/heartbeat_<process_index>.json`` with the current step, wall
    time, and the delta since the previous beat (the per-host step
    time the straggler math consumes)."""

    def __init__(self, directory: str, process_index: int = 0,
                 host: Optional[str] = None):
        self.directory = directory
        self.process_index = int(process_index)
        self.host = host or socket.gethostname()
        self.path = os.path.join(
            directory, f"{HEARTBEAT_PREFIX}{self.process_index}.json")
        self._last_t: Optional[float] = None
        self._warned = False
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError as e:
            logger.warning("heartbeat dir %s could not be created (%s); "
                           "heartbeats disabled", directory, e)
            self._warned = True

    def beat(self, step: int, step_s: Optional[float] = None,
             extra: Optional[dict] = None) -> bool:
        """Emit one heartbeat; returns False when the write failed (a
        beat must never take training down — degraded liveness is the
        monitor's problem to notice, via staleness).

        ``extra`` rides additional gauges in the same record — the
        serving fleet's replicas report ``serve_active_slots``, request
        queue depth, ``serve_free_pages`` and the speculation accept
        ratio this way, and the fleet router's join-shortest-queue
        balancer reads them back (docs/serving.md "serving fleet").
        Core liveness keys always win a collision, so a gauge can never
        mask staleness; readers that predate the richer schema keep
        working because they only key on the core fields."""
        now = time.time()
        if step_s is None and self._last_t is not None:
            step_s = now - self._last_t
        self._last_t = now
        rec = dict(extra or {})
        rec.update({"host": self.host,
                    "process_index": self.process_index,
                    "step": int(step), "time": now,
                    "step_s": (round(float(step_s), 6)
                               if step_s is not None else None)})
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.path)  # atomic: no torn reads
            return True
        except OSError as e:
            if not self._warned:
                logger.warning(
                    "heartbeat write to %s failed (%s); training "
                    "continues, liveness monitoring is degraded",
                    self.path, e)
                self._warned = True
            return False


def read_heartbeats(directory: str) -> Dict[str, dict]:
    """All heartbeat records under ``directory``, keyed by
    ``host/process_index``.  Unparseable or mid-replace files are
    skipped (the writer's next beat heals them)."""
    out: Dict[str, dict] = {}
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if not (name.startswith(HEARTBEAT_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict) or "time" not in rec:
            continue
        key = f"{rec.get('host', '?')}/{rec.get('process_index', name)}"
        out[key] = rec
    return out


def beat_ages(beats: Dict[str, dict],
              now: Optional[float] = None) -> Dict[str, float]:
    """Seconds since each host's last beat, keyed like
    :func:`read_heartbeats` (``host/process_index``).  The engine
    exports these as the ``heartbeat_age_s`` gauge so supervisor-visible
    staleness is also operator-visible (the summarize liveness row);
    ages clamp at 0 for clock skew between writer and reader."""
    now = time.time() if now is None else now
    return {key: max(0.0, now - float(rec.get("time", 0.0)))
            for key, rec in beats.items()}


class StragglerMonitor:
    """Pure fleet-health policy over a heartbeat snapshot.

    ``update(beats, now)`` returns a report:

      - ``stale``: hosts whose last beat is older than
        ``stale_after_s`` — the supervisor's liveness signal (a stale
        host is hung, not merely slow);
      - ``stragglers``: hosts whose per-step time exceeds ``ratio`` ×
        the fleet median (needs >= ``min_fleet`` hosts reporting step
        times — a median of one is noise);
      - ``new_stragglers``: flagged now but not in the previous update —
        what the ``straggler_detected_total`` counter counts, so a host
        limping for 100 intervals is one detection, not 100.
    """

    def __init__(self, ratio: float = 2.0, stale_after_s: float = 60.0,
                 min_fleet: int = 2):
        if not ratio > 1.0:
            raise ValueError(
                f"straggler ratio must be > 1.0 (it multiplies the "
                f"fleet median), got {ratio!r}")
        self.ratio = float(ratio)
        self.stale_after_s = float(stale_after_s)
        self.min_fleet = int(min_fleet)
        self.flagged_total = 0
        self._flagged_prev: set = set()

    def update(self, beats: Dict[str, dict],
               now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        stale = sorted(k for k, r in beats.items()
                       if now - float(r.get("time", 0)) > self.stale_after_s)
        # stale hosts are dead/hung, not slow: their frozen last step_s
        # must not sit in the fleet median (or the straggler set) forever
        step_times = {k: float(r["step_s"]) for k, r in beats.items()
                      if r.get("step_s") and k not in stale}
        median = (statistics.median(step_times.values())
                  if step_times else None)
        stragglers = []
        if median and len(step_times) >= self.min_fleet:
            stragglers = sorted(k for k, t in step_times.items()
                                if t > self.ratio * median)
        new = [k for k in stragglers if k not in self._flagged_prev]
        self.flagged_total += len(new)
        self._flagged_prev = set(stragglers)
        return {"hosts": len(beats), "stale": stale,
                "stragglers": stragglers, "new_stragglers": new,
                "median_step_s": median}
