"""TelemetryHub — the facade the engine owns.

One hub per engine wires registry + tracer + compile monitor + memory
sampler + exporters together and exposes exactly two cadences:

  ``record_step``  — every ``train_batch``; host-only (counter bump,
                     histogram observe, buffered JSONL write).  MUST
                     never touch a device buffer: the engine's async
                     dispatch overlap is the thing being measured.
  ``on_sync``      — at the engine's existing sync points (the periodic
                     ``steps_per_print`` metrics materialization).  This
                     is where the synced step-time histogram, memory
                     gauges, compile samples, Prometheus scrape file,
                     and flushes happen — telemetry rides the drain the
                     engine was already paying for.

``close()`` is idempotent and exports the Chrome trace.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

from .compile_monitor import CompileMonitor
from .exporters import JsonlExporter, SummaryWriterBridge, write_prometheus
from .memory import MemorySampler
from .registry import MetricsRegistry
from .tracing import TraceRecorder

EVENTS_FILE = "events.jsonl"
TRACE_FILE = "trace.json"
PROM_FILE = "metrics.prom"
FLIGHTREC_PREFIX = "flightrec_"
FLIGHTREC_VERSION = 1


def write_flight_record(directory: str, stages, step: int, reason: str,
                        error=None, extra: Optional[dict] = None) -> str:
    """Dump the fault plane's recent history as ``flightrec_<step>.json``
    (docs/observability.md: the flightrec schema).  ``stages`` maps
    stage name -> an object exposing ``flight_snapshot()`` (the
    :class:`~..runtime.stages.Stage` record).  tmp+rename so a reader
    (or a second dump racing a crash) never sees a torn record; the
    caller decides the trigger (poison, degradation, SIGTERM, anomaly,
    on demand)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{FLIGHTREC_PREFIX}{int(step)}.json")
    payload = {
        "version": FLIGHTREC_VERSION,
        "reason": reason,
        "step": int(step),
        "time": time.time(),
        "error": repr(error) if error is not None else None,
        "stages": {name: st.flight_snapshot()
                   for name, st in dict(stages).items()},
    }
    if extra:
        payload["extra"] = extra
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, default=repr)
    os.replace(tmp, path)
    return path


class TelemetryHub:
    def __init__(self, output_path: str, *,
                 trace: bool = True,
                 compile_events: bool = True,
                 memory: bool = True,
                 storm_threshold: int = 3,
                 summary_writer=None,
                 process_index: int = 0):
        self.output_path = output_path
        os.makedirs(output_path, exist_ok=True)
        self.registry = MetricsRegistry()
        self.tracer = (TraceRecorder(pid=process_index)
                       if trace else None)
        self.jsonl = JsonlExporter(os.path.join(output_path, EVENTS_FILE))
        self.compile_monitor = None
        if compile_events:
            self.compile_monitor = CompileMonitor(
                self.registry, storm_threshold=storm_threshold)
            self.compile_monitor.install()
        self.memory_sampler = MemorySampler(self.registry) if memory else None
        self.bridge = (SummaryWriterBridge(self.registry, summary_writer)
                       if summary_writer is not None else None)

        self.steps_total = self.registry.counter(
            "train_steps_total", "train_batch calls")
        self.dispatch_seconds = self.registry.histogram(
            "train_dispatch_seconds",
            "host-side train_batch latency (enqueue, NOT device step "
            "time — see train_step_seconds)")
        self.step_seconds = self.registry.histogram(
            "train_step_seconds",
            "synced per-step wall time (interval average at each "
            "steps_per_print materialization)")
        self._interval_span = None
        self._closed = False

    # -- per-step (host-only, no syncs) ---------------------------------
    def record_step(self, step: int, dispatch_s: float,
                    samples: Optional[int] = None):
        self.steps_total.inc()
        self.dispatch_seconds.observe(dispatch_s)
        data = {"step": int(step), "dispatch_s": float(dispatch_s)}
        if samples is not None:
            data["samples"] = int(samples)
        self.jsonl.write_event("step", data)

    def track_program(self, name: str, fn) -> bool:
        if self.compile_monitor is None:
            return False
        return self.compile_monitor.track(name, fn)

    def span(self, name: str, cat: str = "runtime", **args):
        """Context manager; a no-op context when tracing is disabled."""
        if self.tracer is None:
            import contextlib
            return contextlib.nullcontext()
        return self.tracer.span(name, cat, **args)

    # -- at the engine's existing sync points ---------------------------
    def on_sync(self, step: int, *, interval_s: Optional[float] = None,
                steps: Optional[int] = None,
                samples_per_step: Optional[int] = None,
                scalars: Optional[dict] = None):
        if self._closed:
            return
        avg = None
        if interval_s is not None and steps:
            avg = interval_s / steps
            self.step_seconds.observe(avg)
        sync_data = {"step": int(step)}
        if interval_s is not None:
            sync_data["interval_s"] = float(interval_s)
        if steps is not None:
            sync_data["steps"] = int(steps)
        if avg is not None:
            sync_data["step_avg_s"] = avg
        if samples_per_step is not None:
            sync_data["samples_per_step"] = int(samples_per_step)
            if avg:
                sync_data["samples_per_sec"] = samples_per_step / avg
        if scalars:
            sync_data["scalars"] = {k: float(v) for k, v in scalars.items()}
        self.jsonl.write_event("sync", sync_data)

        if self.tracer is not None:
            if self._interval_span is not None:
                self._interval_span.end(steps=steps)
            self._interval_span = self.tracer.begin(
                "train/steps_interval", cat="train")

        if self.memory_sampler is not None:
            stats = self.memory_sampler.sample()
            self.jsonl.write_event("memory", {"step": int(step),
                                              "stats": stats})
            if self.tracer is not None:
                for dev in stats.get("devices", [])[:8]:
                    if dev.get("bytes_in_use") is not None:
                        self.tracer.counter(
                            f"hbm/device{dev.get('id')}",
                            {"bytes_in_use": dev["bytes_in_use"]})
        if self.compile_monitor is not None:
            self.compile_monitor.sample()

        self.jsonl.write_snapshot(self.registry, step=step)
        self.jsonl.flush()
        try:
            write_prometheus(self.registry,
                             os.path.join(self.output_path, PROM_FILE))
        except OSError:
            # scrape file is best-effort on the training path; the JSONL
            # exporter degrades itself with a warning on the same class
            # of failure
            pass
        if self.bridge is not None:
            self.bridge.push(step)

    def dump_flight_record(self, stages, step: int, reason: str,
                           error=None,
                           extra: Optional[dict] = None) -> str:
        """Flight-record dump into this hub's output directory; see
        :func:`write_flight_record`.  Safe to call after ``close()``
        (post-mortems happen at shutdown)."""
        return write_flight_record(self.output_path, stages, step,
                                   reason, error=error, extra=extra)

    # -- shutdown -------------------------------------------------------
    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._interval_span is not None:
            self._interval_span.end()
            self._interval_span = None
        if self.compile_monitor is not None:
            self.compile_monitor.sample()
            self.compile_monitor.uninstall()
        try:
            write_prometheus(self.registry,
                             os.path.join(self.output_path, PROM_FILE))
        except OSError:
            pass
        self.jsonl.write_snapshot(self.registry)
        self.jsonl.close()
        if self.tracer is not None:
            try:
                self.tracer.export(
                    os.path.join(self.output_path, TRACE_FILE))
            except OSError:
                pass
