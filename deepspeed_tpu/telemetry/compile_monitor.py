"""Compile-event tracking — the runtime complement to jaxlint JL005.

Two sources, both feeding the one registry:

  - ``jax.monitoring`` listeners (graceful no-op when the API is
    absent): every XLA compile increments ``jax_compiles_total`` and
    observes ``jax_compile_seconds`` — process-wide, catches compiles
    from ANY program including library internals.
  - ``track(name, fn)``: per-program retrace counting via the jit
    cache size of registered compiled steps.  ``sample()`` (called at
    the engine's periodic sync) turns cache growth into
    ``recompiles_total{program=...}`` — cache entries beyond the first
    are retraces, the production signal that a shape/static-arg leak is
    recompiling the hot path (JL005's runtime shadow).

A recompile storm (>= ``storm_threshold`` retraces of one program seen
within a single sample window) logs a loud warning with the program
name — the failure mode is a silent 40s/step trickle otherwise.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ..utils.logging import logger
from .registry import MetricsRegistry

_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileMonitor:
    def __init__(self, registry: MetricsRegistry, storm_threshold: int = 3):
        self.registry = registry
        self.storm_threshold = max(int(storm_threshold), 1)
        self.compiles = registry.counter(
            "jax_compiles_total", "XLA backend compiles (jax.monitoring)")
        self.compile_seconds = registry.histogram(
            "jax_compile_seconds", "XLA backend compile durations")
        self.recompiles = registry.counter(
            "recompiles_total",
            "retraces of tracked jitted programs (cache entries beyond "
            "the first)")
        self._tracked: List[Tuple[str, object]] = []
        self._seen_sizes: Dict[str, int] = {}
        self._warned_storm: set = set()
        self._installed = False
        self._listener = None

    # -- jax.monitoring hook --------------------------------------------
    def install(self) -> bool:
        """Register the duration listener; returns False (and stays a
        no-op) when jax.monitoring is unavailable."""
        if self._installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        if not hasattr(monitoring, "register_event_duration_secs_listener"):
            return False

        def on_duration(event: str, duration: float, **kwargs):
            if event == _COMPILE_DURATION_EVENT:
                self.compiles.inc()
                self.compile_seconds.observe(duration)

        try:
            monitoring.register_event_duration_secs_listener(on_duration)
        except Exception:
            return False
        self._listener = on_duration
        self._installed = True
        return True

    def uninstall(self):
        """Best-effort listener removal (the public API has no
        unregister; the private helper exists on the jax versions we
        support and a leaked listener is only a few ns per event)."""
        if not self._installed:
            return
        self._installed = False
        try:
            from jax._src import monitoring as _mon
            _mon._unregister_event_duration_listener_by_callback(
                self._listener)
        except Exception:
            pass
        self._listener = None

    # -- per-program retrace tracking -----------------------------------
    def track(self, name: str, fn) -> bool:
        """Register a compiled callable for retrace counting.  Accepts
        anything; silently skips objects without a jit cache (the
        chunked offload paths hand the engine plain Python drivers)."""
        if not hasattr(fn, "_cache_size"):
            return False
        self._tracked.append((name, fn))
        self._seen_sizes.setdefault(name, 0)
        return True

    def sample(self):
        """Fold current cache sizes into ``recompiles_total``.  Rides
        the caller's sync cadence — reading ``_cache_size`` is a host
        dict ``len()``, never a device sync."""
        for name, fn in self._tracked:
            try:
                size = int(fn._cache_size())
            except Exception:
                continue
            prev = self._seen_sizes.get(name, 0)
            if size <= prev:
                continue
            # entries beyond the first are retraces
            new_retraces = max(size - 1, 0) - max(prev - 1, 0)
            self._seen_sizes[name] = size
            if new_retraces <= 0:
                continue
            self.recompiles.inc(new_retraces, program=name)
            if (new_retraces >= self.storm_threshold
                    and name not in self._warned_storm):
                self._warned_storm.add(name)
                logger.warning(
                    "recompile storm: program %r retraced %d times within "
                    "one sample window (total cache entries: %d). A shape "
                    "or static-arg is varying per call — see jaxlint JL005 "
                    "and docs/observability.md.", name, new_retraces, size)

    def tracked_programs(self) -> List[str]:
        return [name for name, _ in self._tracked]
