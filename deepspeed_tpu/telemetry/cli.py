"""``python -m deepspeed_tpu.telemetry summarize events.jsonl``

Offline report over the JSONL event stream the hub writes: p50/p95/p99
step time, samples/sec, peak HBM.  This module is pure stdlib, but the
``-m`` entry point imports the ``deepspeed_tpu`` package (which imports
jax) — on a box without the runtime stack, copy this one file and run
it directly: ``python cli.py summarize events.jsonl``.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    if v < 1e-3:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.2f}ms"
    return f"{v:.3f}s"


def _fmt_bytes(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.2f}{unit}"
        v /= 1024
    return f"{v:.2f}TiB"


def summarize(path: str, out=None) -> dict:
    # resolve stdout at call time (a definition-time default would pin
    # the stream captured before any test/redirect wrapping)
    out = out if out is not None else sys.stdout
    steps = 0
    dispatch: List[float] = []
    synced: List[float] = []
    sps: List[float] = []
    overlap: List[float] = []
    pf_hits: List[float] = []
    pf_wait: List[float] = []
    ck_save: List[float] = []
    ck_hidden: List[float] = []
    sv_tps: List[float] = []
    sv_p50: List[float] = []
    sv_p99: List[float] = []
    stragglers: Optional[float] = None
    peak_hbm: Optional[float] = None
    host_rss: Optional[float] = None
    bad_lines = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_lines += 1
                continue
            kind = rec.get("kind")
            if kind == "step":
                steps += 1
                if rec.get("dispatch_s") is not None:
                    dispatch.append(float(rec["dispatch_s"]))
            elif kind == "sync":
                if rec.get("step_avg_s") is not None:
                    # one synced average per interval; weight by the
                    # interval's step count so percentiles are per-step
                    n = int(rec.get("steps") or 1)
                    synced.extend([float(rec["step_avg_s"])] * n)
                if rec.get("samples_per_sec") is not None:
                    sps.append(float(rec["samples_per_sec"]))
                scalars = rec.get("scalars") or {}
                ov = scalars.get("offload_overlap_ratio")
                if ov is not None:
                    # weight by the interval's step count, same as the
                    # step-time percentiles — a 1-step straggler interval
                    # must not count like a full one
                    overlap.extend([float(ov)]
                                   * int(rec.get("steps") or 1))
                ph = scalars.get("prefetch_hit_ratio")
                if ph is not None:
                    # async input pipeline: same step-count weighting
                    pf_hits.extend([float(ph)]
                                   * int(rec.get("steps") or 1))
                pw = scalars.get("prefetch_wait_s")
                if pw is not None:
                    pf_wait.extend([float(pw)]
                                   * int(rec.get("steps") or 1))
                cs = scalars.get("ckpt_save_s")
                if cs is not None:
                    # per-save figures (one mean per interval, unweighted
                    # like samples_per_sec — saves, not steps, are the unit)
                    ck_save.append(float(cs))
                ch = scalars.get("ckpt_async_overlap_s")
                if ch is not None:
                    ck_hidden.append(float(ch))
                tps = scalars.get("serve_tokens_per_s")
                if tps is not None:
                    # serving engine flushes (one rate per interval,
                    # unweighted like samples_per_sec)
                    sv_tps.append(float(tps))
                sp50 = scalars.get("serve_token_p50_s")
                if sp50 is not None:
                    sv_p50.append(float(sp50))
                sp99 = scalars.get("serve_token_p99_s")
                if sp99 is not None:
                    sv_p99.append(float(sp99))
                sg = scalars.get("straggler_detected_total")
                if sg is not None:
                    # cumulative counter: the last/maximum value is the
                    # run's total detections
                    stragglers = max(stragglers or 0.0, float(sg))
            elif kind == "memory":
                stats = rec.get("stats") or {}
                for dev in stats.get("devices", []):
                    p = dev.get("peak_bytes_in_use")
                    if p is not None:
                        peak_hbm = max(peak_hbm or 0, float(p))
                rss = stats.get("host_rss_bytes")
                if rss is not None:
                    host_rss = max(host_rss or 0, float(rss))

    source = "synced intervals"
    times = sorted(synced)
    if not times:
        # dispatch latency is enqueue time, not device step time — still
        # report it, loudly labelled (the JL006 bug class)
        source = "DISPATCH-ONLY (no sync events; async enqueue latency, " \
                 "not device step time)"
        times = sorted(dispatch)
    p50 = _percentile(times, 0.50)
    p95 = _percentile(times, 0.95)
    p99 = _percentile(times, 0.99)
    avg_sps = sum(sps) / len(sps) if sps else None

    avg_overlap = sum(overlap) / len(overlap) if overlap else None
    avg_pf_hit = sum(pf_hits) / len(pf_hits) if pf_hits else None
    avg_pf_wait = sum(pf_wait) / len(pf_wait) if pf_wait else None
    avg_ck_save = sum(ck_save) / len(ck_save) if ck_save else None
    avg_ck_hidden = sum(ck_hidden) / len(ck_hidden) if ck_hidden else None
    avg_sv_tps = sum(sv_tps) / len(sv_tps) if sv_tps else None
    # latency percentiles: the LAST flush covers the whole run's bounded
    # latency window (the engine computes them cumulatively)
    last_sv_p50 = sv_p50[-1] if sv_p50 else None
    last_sv_p99 = sv_p99[-1] if sv_p99 else None

    report = {
        "steps": steps,
        "step_time_source": source,
        "p50_s": p50, "p95_s": p95, "p99_s": p99,
        "samples_per_sec": avg_sps,
        "offload_overlap_ratio": avg_overlap,
        "prefetch_hit_ratio": avg_pf_hit,
        "prefetch_wait_s": avg_pf_wait,
        "ckpt_save_s": avg_ck_save,
        "ckpt_async_overlap_s": avg_ck_hidden,
        "serve_tokens_per_s": avg_sv_tps,
        "serve_token_p50_s": last_sv_p50,
        "serve_token_p99_s": last_sv_p99,
        "straggler_detected_total": stragglers,
        "peak_hbm_bytes": peak_hbm,
        "host_rss_bytes": host_rss,
        "bad_lines": bad_lines,
    }
    print(f"telemetry summary: {path}", file=out)
    print(f"  steps recorded     {steps}", file=out)
    print(f"  step time ({source})", file=out)
    print(f"    p50 {_fmt_s(p50)}  p95 {_fmt_s(p95)}  p99 {_fmt_s(p99)}",
          file=out)
    if avg_sps is not None:
        print(f"  samples/sec        {avg_sps:.1f}", file=out)
    if avg_overlap is not None:
        # streaming offload pipeline: 1.0 = the H2D param re-upload is
        # fully hidden under the host Adam; 0 = serial (all tail)
        print(f"  offload H2D overlap {avg_overlap * 100:.0f}% hidden "
              "under host Adam", file=out)
    if avg_pf_hit is not None:
        # async input pipeline: hit = batch already device-resident
        # when the step asked; wait = the exposed input stall per step
        wait_txt = (f"  wait {_fmt_s(avg_pf_wait)}/step"
                    if avg_pf_wait is not None else "")
        print(f"  input prefetch     hit {avg_pf_hit * 100:.0f}%"
              f"{wait_txt}", file=out)
    if avg_ck_save is not None:
        # checkpointing: exposed = step-loop stall per save (sync: the
        # whole serialize; async: just the snapshot D2H); hidden = the
        # background write time the async writer kept off the hot path
        hid_txt = (f"  hidden {_fmt_s(avg_ck_hidden)}/save (async)"
                   if avg_ck_hidden is not None else "")
        print(f"  checkpoint         exposed {_fmt_s(avg_ck_save)}/save"
              f"{hid_txt}", file=out)
    if avg_sv_tps is not None:
        # serving engine (docs/serving.md): throughput + per-token
        # latency (first token of a request = its time to first token)
        lat_txt = ""
        if last_sv_p50 is not None:
            lat_txt = (f"  token p50 {_fmt_s(last_sv_p50)}"
                       f"  p99 {_fmt_s(last_sv_p99)}")
        print(f"  serving            {avg_sv_tps:.1f} tok/s{lat_txt}",
              file=out)
    if stragglers is not None:
        # elastic fleet health: hosts flagged slower than the configured
        # multiple of the fleet-median step time (docs/elastic.md)
        print(f"  stragglers         {int(stragglers)} host(s) flagged "
              "(step time > ratio x fleet median)", file=out)
    print(f"  peak HBM           {_fmt_bytes(peak_hbm)}", file=out)
    if host_rss is not None:
        print(f"  peak host RSS      {_fmt_bytes(host_rss)}", file=out)
    if bad_lines:
        print(f"  (skipped {bad_lines} unparseable lines)", file=out)
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry",
        description="offline reports over telemetry event files")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_sum = sub.add_parser("summarize",
                           help="p50/p95/p99 step time, samples/sec, "
                                "peak HBM from an events.jsonl")
    p_sum.add_argument("events", help="path to events.jsonl")
    args = parser.parse_args(argv)
    if args.cmd == "summarize":
        try:
            summarize(args.events)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
