"""``python -m deepspeed_tpu.telemetry summarize events.jsonl`` and
``python -m deepspeed_tpu.telemetry diagnose <dir>``

Offline reports over the artifacts the hub writes: ``summarize`` turns
an events.jsonl into p50/p95/p99 step time, samples/sec, serving
latency attribution (queue/prefill/decode), liveness, and peak HBM;
``diagnose`` correlates a flight-record dump (``flightrec_<step>.json``)
with events.jsonl and trace.json into a post-mortem — which stage
failed first, the queue-depth trajectory, and the original exception
(docs/observability.md).  A serving-FLEET directory (a router's
events.jsonl + ``replica_<id>/`` telemetry subdirs — docs/serving.md
"serving fleet") additionally correlates per-replica flight records
and the router's request ledger: first-failing replica, failover
count, and dangling (submitted-but-never-completed) requests.  Both tolerate a torn final line (a killed
run) and REPORT the skipped count instead of silently dropping it.
This module is pure stdlib, but the ``-m`` entry point imports the
``deepspeed_tpu`` package (which imports jax) — on a box without the
runtime stack, copy this one file and run it directly:
``python cli.py summarize events.jsonl``.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _slo_ok(ttft: Optional[float], tpot: Optional[float],
            slo_ttft_s: float, slo_tpot_s: float) -> bool:
    """THE goodput verdict (docs/serving.md "workload plane"): a
    request is good only if its first token landed within the TTFT SLO
    and its decode cadence held the TPOT SLO.  A request that never
    produced a token fails; a one-token request has no decode phase
    and passes TPOT vacuously.  One copy — telemetry/goodput.py and
    the record-derived goodput row below share it."""
    if ttft is None or ttft > slo_ttft_s:
        return False
    return tpot is None or tpot <= slo_tpot_s


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    if v < 1e-3:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.2f}ms"
    return f"{v:.3f}s"


def _fmt_bytes(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.2f}{unit}"
        v /= 1024
    return f"{v:.2f}TiB"


def summarize(path: str, out=None) -> dict:
    # resolve stdout at call time (a definition-time default would pin
    # the stream captured before any test/redirect wrapping)
    out = out if out is not None else sys.stdout
    steps = 0
    dispatch: List[float] = []
    synced: List[float] = []
    sps: List[float] = []
    overlap: List[float] = []
    off_h2d: List[float] = []
    off_adam: List[float] = []
    disk_overlap: List[float] = []
    disk_read: List[float] = []
    disk_write: List[float] = []
    pf_hits: List[float] = []
    pf_wait: List[float] = []
    ck_save: List[float] = []
    ck_hidden: List[float] = []
    sv_tps: List[float] = []
    sv_p50: List[float] = []
    sv_p99: List[float] = []
    sv_page_util: List[float] = []
    sv_free_pages: Optional[float] = None
    sv_prefix_hit: Optional[float] = None
    sv_prefix_tokens: Optional[float] = None
    sv_cow: Optional[float] = None
    sv_spec_accept: Optional[float] = None
    sv_spec_mal: Optional[float] = None
    sv_param_bytes: Optional[float] = None
    sv_kv_bytes: Optional[float] = None
    # multi-tenant adapter plane (docs/serving.md "multi-tenant
    # serving"): residency is a gauge (last flush = the run's answer);
    # hits/faults/evictions are cumulative counters
    sv_adapters_resident: Optional[float] = None
    sv_adapter_bytes: Optional[float] = None
    sv_adapter_hits: Optional[float] = None
    sv_adapter_faults: Optional[float] = None
    sv_adapter_evictions: Optional[float] = None
    # KV tier plane (docs/serving.md "KV tiering"): parked sessions is
    # a gauge (last flush = the run's answer), spill/fetch bytes are
    # cumulative, resume p99 is the last flush's window percentile
    sv_kv_parked: Optional[float] = None
    sv_kv_spill_bytes: Optional[float] = None
    sv_kv_fetch_bytes: Optional[float] = None
    sv_kv_resume_p99: Optional[float] = None
    # goodput plane (docs/serving.md "workload plane"): the SLOs and
    # the live tracker's verdict arrive as sync scalars; the
    # per-request phases below recompute the same verdict offline
    sv_goodput: Optional[float] = None
    sv_goodput_n: Optional[float] = None
    sv_slo_ttft: Optional[float] = None
    sv_slo_tpot: Optional[float] = None
    # per-request serving records (kind: serve_request) — the
    # queue/prefill/decode latency attribution split
    sv_requests = 0
    sv_failed = 0
    sv_queue_wait: List[float] = []
    sv_ttft: List[float] = []
    sv_decode: List[float] = []
    sv_tpot: List[float] = []
    #: (ttft, tpot, errored) per request for the record-derived
    #: goodput row; arrival_s is optional (absent in pre-PR-17
    #: artifacts — everything here tolerates that)
    sv_phases: List[tuple] = []
    sv_arrivals: List[float] = []
    stragglers: Optional[float] = None
    #: last metrics snapshot's heartbeat_age_s gauges (liveness row)
    beat_ages: Dict[str, float] = {}
    peak_hbm: Optional[float] = None
    host_rss: Optional[float] = None
    bad_lines = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_lines += 1
                continue
            kind = rec.get("kind")
            if kind == "step":
                steps += 1
                if rec.get("dispatch_s") is not None:
                    dispatch.append(float(rec["dispatch_s"]))
            elif kind == "sync":
                if rec.get("step_avg_s") is not None:
                    # one synced average per interval; weight by the
                    # interval's step count so percentiles are per-step
                    n = int(rec.get("steps") or 1)
                    synced.extend([float(rec["step_avg_s"])] * n)
                if rec.get("samples_per_sec") is not None:
                    sps.append(float(rec["samples_per_sec"]))
                scalars = rec.get("scalars") or {}
                ov = scalars.get("offload_overlap_ratio")
                if ov is not None:
                    # weight by the interval's step count, same as the
                    # step-time percentiles — a 1-step straggler interval
                    # must not count like a full one
                    overlap.extend([float(ov)]
                                   * int(rec.get("steps") or 1))
                    # attribution split for the overlap ratio: per-step
                    # H2D upload and CPU-Adam time, same weighting
                    n = int(rec.get("steps") or 1)
                    if scalars.get("offload_h2d_s") is not None:
                        off_h2d.extend(
                            [float(scalars["offload_h2d_s"])] * n)
                    if scalars.get("offload_cpu_adam_s") is not None:
                        off_adam.extend(
                            [float(scalars["offload_cpu_adam_s"])] * n)
                dv = scalars.get("offload_disk_overlap_ratio")
                if dv is not None:
                    # disk tier (runtime/disk_offload.py): same
                    # step-count weighting as the H2D overlap row
                    n = int(rec.get("steps") or 1)
                    disk_overlap.extend([float(dv)] * n)
                    if scalars.get("disk_read_s") is not None:
                        disk_read.extend(
                            [float(scalars["disk_read_s"])] * n)
                    if scalars.get("disk_write_s") is not None:
                        disk_write.extend(
                            [float(scalars["disk_write_s"])] * n)
                ph = scalars.get("prefetch_hit_ratio")
                if ph is not None:
                    # async input pipeline: same step-count weighting
                    pf_hits.extend([float(ph)]
                                   * int(rec.get("steps") or 1))
                pw = scalars.get("prefetch_wait_s")
                if pw is not None:
                    pf_wait.extend([float(pw)]
                                   * int(rec.get("steps") or 1))
                cs = scalars.get("ckpt_save_s")
                if cs is not None:
                    # per-save figures (one mean per interval, unweighted
                    # like samples_per_sec — saves, not steps, are the unit)
                    ck_save.append(float(cs))
                ch = scalars.get("ckpt_async_overlap_s")
                if ch is not None:
                    ck_hidden.append(float(ch))
                tps = scalars.get("serve_tokens_per_s")
                if tps is not None:
                    # serving engine flushes (one rate per interval,
                    # unweighted like samples_per_sec)
                    sv_tps.append(float(tps))
                sp50 = scalars.get("serve_token_p50_s")
                if sp50 is not None:
                    sv_p50.append(float(sp50))
                sp99 = scalars.get("serve_token_p99_s")
                if sp99 is not None:
                    sv_p99.append(float(sp99))
                # paged KV pool (docs/serving.md): utilization averages
                # over flushes; free pages / prefix stats are cumulative
                # — the LAST flush is the run's answer
                pu = scalars.get("serve_page_utilization")
                if pu is not None:
                    sv_page_util.append(float(pu))
                fp = scalars.get("serve_free_pages")
                if fp is not None:
                    sv_free_pages = float(fp)
                pr = scalars.get("serve_prefix_hit_ratio")
                if pr is not None:
                    sv_prefix_hit = float(pr)
                pt = scalars.get("serve_prefix_hit_tokens")
                if pt is not None:
                    sv_prefix_tokens = float(pt)
                cw = scalars.get("serve_page_cow_total")
                if cw is not None:
                    sv_cow = float(cw)
                # speculative decoding (docs/serving.md): both scalars
                # are cumulative over the run — the LAST flush is the
                # run's answer
                sa = scalars.get("serve_spec_accept_ratio")
                if sa is not None:
                    sv_spec_accept = float(sa)
                sm = scalars.get("serve_spec_mean_accepted_len")
                if sm is not None:
                    sv_spec_mal = float(sm)
                # serving memory plane (docs/serving.md "quantized
                # serving"): static per engine — the last flush is the
                # run's answer
                pb = scalars.get("serve_param_bytes")
                if pb is not None:
                    sv_param_bytes = float(pb)
                kb = scalars.get("serve_kv_bytes")
                if kb is not None:
                    sv_kv_bytes = float(kb)
                # adapter pool (docs/serving.md "multi-tenant
                # serving"): last flush is the run's answer for all
                # five — residency is a point-in-time gauge, the rest
                # are cumulative
                ar = scalars.get("serve_adapters_resident")
                if ar is not None:
                    sv_adapters_resident = float(ar)
                ab = scalars.get("serve_adapter_bytes")
                if ab is not None:
                    sv_adapter_bytes = float(ab)
                ah = scalars.get("serve_adapter_hits_total")
                if ah is not None:
                    sv_adapter_hits = float(ah)
                af = scalars.get("serve_adapter_faults_total")
                if af is not None:
                    sv_adapter_faults = float(af)
                ae = scalars.get("serve_adapter_evictions_total")
                if ae is not None:
                    sv_adapter_evictions = float(ae)
                # KV tier (docs/serving.md "KV tiering")
                kp = scalars.get("serve_kv_parked_sessions")
                if kp is not None:
                    sv_kv_parked = float(kp)
                ks = scalars.get("serve_kv_spill_bytes_total")
                if ks is not None:
                    sv_kv_spill_bytes = float(ks)
                kf = scalars.get("serve_kv_fetch_bytes_total")
                if kf is not None:
                    sv_kv_fetch_bytes = float(kf)
                kr = scalars.get("serve_kv_resume_p99_s")
                if kr is not None:
                    sv_kv_resume_p99 = float(kr)
                # goodput scalars (telemetry/goodput.py flush): all
                # cumulative — the LAST flush is the run's answer
                gp = scalars.get("serve_goodput")
                if gp is not None:
                    sv_goodput = float(gp)
                gn = scalars.get("serve_goodput_requests")
                if gn is not None:
                    sv_goodput_n = float(gn)
                gt = scalars.get("serve_slo_ttft_s")
                if gt is not None:
                    sv_slo_ttft = float(gt)
                gd = scalars.get("serve_slo_tpot_s")
                if gd is not None:
                    sv_slo_tpot = float(gd)
                sg = scalars.get("straggler_detected_total")
                if sg is not None:
                    # cumulative counter: the last/maximum value is the
                    # run's total detections
                    stragglers = max(stragglers or 0.0, float(sg))
            elif kind == "serve_request":
                sv_requests += 1
                if rec.get("error"):
                    sv_failed += 1
                if rec.get("queue_wait_s") is not None:
                    sv_queue_wait.append(float(rec["queue_wait_s"]))
                if rec.get("ttft_s") is not None:
                    sv_ttft.append(float(rec["ttft_s"]))
                for t in rec.get("token_times_s") or []:
                    sv_decode.append(float(t))
                # phase attribution for the goodput row: mean time per
                # output token over the request's decode phase, plus
                # the open-loop arrival stamp (optional — pre-PR-17
                # records don't carry arrival_s and must still parse)
                tpot = None
                dn = rec.get("decode_tokens")
                if dn:
                    tpot = float(rec.get("decode_s_sum") or 0.0) \
                        / int(dn)
                    sv_tpot.append(tpot)
                ttft = rec.get("ttft_s")
                sv_phases.append(
                    (float(ttft) if ttft is not None else None,
                     tpot, bool(rec.get("error"))))
                if rec.get("arrival_s") is not None:
                    sv_arrivals.append(float(rec["arrival_s"]))
            elif kind == "metrics":
                # liveness: keep the LAST snapshot's per-host beat ages
                ages = {m["labels"].get("host", "?"): float(m["value"])
                        for m in rec.get("metrics") or []
                        if m.get("name") == "heartbeat_age_s"
                        and m.get("value") is not None}
                if ages:
                    beat_ages = ages
            elif kind == "memory":
                stats = rec.get("stats") or {}
                for dev in stats.get("devices", []):
                    p = dev.get("peak_bytes_in_use")
                    if p is not None:
                        peak_hbm = max(peak_hbm or 0, float(p))
                rss = stats.get("host_rss_bytes")
                if rss is not None:
                    host_rss = max(host_rss or 0, float(rss))

    source = "synced intervals"
    times = sorted(synced)
    if not times:
        # dispatch latency is enqueue time, not device step time — still
        # report it, loudly labelled (the JL006 bug class)
        source = "DISPATCH-ONLY (no sync events; async enqueue latency, " \
                 "not device step time)"
        times = sorted(dispatch)
    p50 = _percentile(times, 0.50)
    p95 = _percentile(times, 0.95)
    p99 = _percentile(times, 0.99)
    avg_sps = sum(sps) / len(sps) if sps else None

    avg_overlap = sum(overlap) / len(overlap) if overlap else None
    avg_off_h2d = sum(off_h2d) / len(off_h2d) if off_h2d else None
    avg_off_adam = sum(off_adam) / len(off_adam) if off_adam else None
    avg_disk_overlap = (sum(disk_overlap) / len(disk_overlap)
                        if disk_overlap else None)
    avg_disk_read = sum(disk_read) / len(disk_read) if disk_read else None
    avg_disk_write = (sum(disk_write) / len(disk_write)
                      if disk_write else None)
    avg_pf_hit = sum(pf_hits) / len(pf_hits) if pf_hits else None
    avg_pf_wait = sum(pf_wait) / len(pf_wait) if pf_wait else None
    avg_ck_save = sum(ck_save) / len(ck_save) if ck_save else None
    avg_ck_hidden = sum(ck_hidden) / len(ck_hidden) if ck_hidden else None
    avg_sv_tps = sum(sv_tps) / len(sv_tps) if sv_tps else None
    # latency percentiles: the LAST flush covers the whole run's bounded
    # latency window (the engine computes them cumulatively)
    last_sv_p50 = sv_p50[-1] if sv_p50 else None
    last_sv_p99 = sv_p99[-1] if sv_p99 else None
    # the per-request attribution split: same interpolation as the
    # registry's reservoirs, so these reconstruct the histogram p50/p99
    sv_queue_wait.sort()
    sv_ttft.sort()
    sv_decode.sort()
    sv_tpot.sort()
    # record-derived goodput: when the SLO scalars are present, rescore
    # every completion record with the same verdict the live tracker
    # used — the two must agree, and an artifact with records but no
    # tracker flush still gets a goodput answer
    rec_goodput = None
    ttft_miss = tpot_miss = None
    if sv_slo_ttft is not None and sv_slo_tpot is not None and sv_phases:
        good = 0
        ttft_miss = tpot_miss = 0
        for ttft, tpot, errored in sv_phases:
            if ttft is None or ttft > sv_slo_ttft:
                ttft_miss += 1
            if tpot is not None and tpot > sv_slo_tpot:
                tpot_miss += 1
            if not errored and _slo_ok(ttft, tpot, sv_slo_ttft,
                                       sv_slo_tpot):
                good += 1
        rec_goodput = good / len(sv_phases)

    report = {
        "steps": steps,
        "step_time_source": source,
        "p50_s": p50, "p95_s": p95, "p99_s": p99,
        "samples_per_sec": avg_sps,
        "offload_overlap_ratio": avg_overlap,
        "offload_h2d_s": avg_off_h2d,
        "offload_cpu_adam_s": avg_off_adam,
        "offload_disk_overlap_ratio": avg_disk_overlap,
        "disk_read_s": avg_disk_read,
        "disk_write_s": avg_disk_write,
        "prefetch_hit_ratio": avg_pf_hit,
        "prefetch_wait_s": avg_pf_wait,
        "ckpt_save_s": avg_ck_save,
        "ckpt_async_overlap_s": avg_ck_hidden,
        "serve_tokens_per_s": avg_sv_tps,
        "serve_token_p50_s": last_sv_p50,
        "serve_token_p99_s": last_sv_p99,
        "serve_requests": sv_requests,
        "serve_requests_failed": sv_failed,
        "serve_queue_wait_p50_s": _percentile(sv_queue_wait, 0.50),
        "serve_queue_wait_p99_s": _percentile(sv_queue_wait, 0.99),
        "serve_ttft_p50_s": _percentile(sv_ttft, 0.50),
        "serve_ttft_p99_s": _percentile(sv_ttft, 0.99),
        "serve_decode_p50_s": _percentile(sv_decode, 0.50),
        "serve_decode_p99_s": _percentile(sv_decode, 0.99),
        "serve_tpot_p50_s": _percentile(sv_tpot, 0.50),
        "serve_tpot_p99_s": _percentile(sv_tpot, 0.99),
        "serve_goodput": sv_goodput,
        "serve_goodput_requests": sv_goodput_n,
        "serve_goodput_from_records": rec_goodput,
        "serve_slo_ttft_s": sv_slo_ttft,
        "serve_slo_tpot_s": sv_slo_tpot,
        "serve_slo_ttft_miss": ttft_miss,
        "serve_slo_tpot_miss": tpot_miss,
        "serve_arrival_span_s": (max(sv_arrivals) - min(sv_arrivals)
                                 if sv_arrivals else None),
        "serve_page_utilization": (sum(sv_page_util) / len(sv_page_util)
                                   if sv_page_util else None),
        "serve_free_pages": sv_free_pages,
        "serve_prefix_hit_ratio": sv_prefix_hit,
        "serve_prefix_hit_tokens": sv_prefix_tokens,
        "serve_page_cow_total": sv_cow,
        "serve_spec_accept_ratio": sv_spec_accept,
        "serve_spec_mean_accepted_len": sv_spec_mal,
        "serve_param_bytes": sv_param_bytes,
        "serve_kv_bytes": sv_kv_bytes,
        "serve_adapters_resident": sv_adapters_resident,
        "serve_adapter_bytes": sv_adapter_bytes,
        "serve_adapter_hits_total": sv_adapter_hits,
        "serve_adapter_faults_total": sv_adapter_faults,
        "serve_adapter_evictions_total": sv_adapter_evictions,
        "serve_kv_parked_sessions": sv_kv_parked,
        "serve_kv_spill_bytes_total": sv_kv_spill_bytes,
        "serve_kv_fetch_bytes_total": sv_kv_fetch_bytes,
        "serve_kv_resume_p99_s": sv_kv_resume_p99,
        "liveness_hosts": len(beat_ages) or None,
        "liveness_max_age_s": (max(beat_ages.values())
                               if beat_ages else None),
        "straggler_detected_total": stragglers,
        "peak_hbm_bytes": peak_hbm,
        "host_rss_bytes": host_rss,
        "bad_lines": bad_lines,
    }
    print(f"telemetry summary: {path}", file=out)
    print(f"  steps recorded     {steps}", file=out)
    print(f"  step time ({source})", file=out)
    print(f"    p50 {_fmt_s(p50)}  p95 {_fmt_s(p95)}  p99 {_fmt_s(p99)}",
          file=out)
    if avg_sps is not None:
        print(f"  samples/sec        {avg_sps:.1f}", file=out)
    if avg_overlap is not None:
        # streaming offload pipeline: 1.0 = the H2D param re-upload is
        # fully hidden under the host Adam; 0 = serial (all tail)
        io_txt = ""
        if avg_off_h2d is not None and avg_off_adam is not None:
            io_txt = (f"  (H2D {_fmt_s(avg_off_h2d)} vs Adam "
                      f"{_fmt_s(avg_off_adam)})/step")
        print(f"  offload H2D overlap {avg_overlap * 100:.0f}% hidden "
              f"under host Adam{io_txt}", file=out)
    if avg_disk_overlap is not None:
        # disk tier: 1.0 = all per-leaf state reads/writes ran under
        # the host Adam (three-tier pipeline); 0 = the serial
        # read-update-write loop (degraded or DS_DISK_OFFLOAD_PIPELINE=0)
        io_txt = ""
        if avg_disk_read is not None and avg_disk_write is not None:
            io_txt = (f"  (read {_fmt_s(avg_disk_read)} + write "
                      f"{_fmt_s(avg_disk_write)})/step")
        print(f"  disk tier          {avg_disk_overlap * 100:.0f}% of "
              f"state I/O hidden under host Adam{io_txt}", file=out)
    if avg_pf_hit is not None:
        # async input pipeline: hit = batch already device-resident
        # when the step asked; wait = the exposed input stall per step
        wait_txt = (f"  wait {_fmt_s(avg_pf_wait)}/step"
                    if avg_pf_wait is not None else "")
        print(f"  input prefetch     hit {avg_pf_hit * 100:.0f}%"
              f"{wait_txt}", file=out)
    if avg_ck_save is not None:
        # checkpointing: exposed = step-loop stall per save (sync: the
        # whole serialize; async: just the snapshot D2H); hidden = the
        # background write time the async writer kept off the hot path
        hid_txt = (f"  hidden {_fmt_s(avg_ck_hidden)}/save (async)"
                   if avg_ck_hidden is not None else "")
        print(f"  checkpoint         exposed {_fmt_s(avg_ck_save)}/save"
              f"{hid_txt}", file=out)
    if avg_sv_tps is not None:
        # serving engine (docs/serving.md): throughput + per-token
        # latency (first token of a request = its time to first token)
        lat_txt = ""
        if last_sv_p50 is not None:
            lat_txt = (f"  token p50 {_fmt_s(last_sv_p50)}"
                       f"  p99 {_fmt_s(last_sv_p99)}")
        print(f"  serving            {avg_sv_tps:.1f} tok/s{lat_txt}",
              file=out)
    if sv_requests:
        # per-request latency attribution (docs/observability.md): the
        # Orca-style split of where a request's time went — queue wait
        # (scheduling pressure) vs prefill/TTFT vs per-token decode
        fail_txt = f", {sv_failed} failed" if sv_failed else ""
        print(f"  serve requests     {sv_requests}{fail_txt}", file=out)
        print(f"    queue wait  p50 "
              f"{_fmt_s(report['serve_queue_wait_p50_s'])}  p99 "
              f"{_fmt_s(report['serve_queue_wait_p99_s'])}", file=out)
        print(f"    ttft        p50 {_fmt_s(report['serve_ttft_p50_s'])}"
              f"  p99 {_fmt_s(report['serve_ttft_p99_s'])}", file=out)
        print(f"    decode/tok  p50 "
              f"{_fmt_s(report['serve_decode_p50_s'])}  p99 "
              f"{_fmt_s(report['serve_decode_p99_s'])}", file=out)
    goodput = sv_goodput if sv_goodput is not None else rec_goodput
    if goodput is not None:
        # goodput (docs/serving.md "workload plane"): fraction of
        # requests meeting BOTH phase SLOs, with the per-phase tails
        # and miss counts that say WHICH SLO the load broke
        slo_txt = ""
        if sv_slo_ttft is not None and sv_slo_tpot is not None:
            slo_txt = (f" (ttft<={_fmt_s(sv_slo_ttft)}, "
                       f"tpot<={_fmt_s(sv_slo_tpot)})")
        n_txt = int(sv_goodput_n) if sv_goodput_n is not None \
            else len(sv_phases)
        print(f"  goodput            {goodput * 100:.0f}% of {n_txt} "
              f"requests met both SLOs{slo_txt}", file=out)
        miss_txt = (f"  (miss {ttft_miss})"
                    if ttft_miss is not None else "")
        print(f"    ttft        p50 {_fmt_s(report['serve_ttft_p50_s'])}"
              f"  p99 {_fmt_s(report['serve_ttft_p99_s'])}{miss_txt}",
              file=out)
        miss_txt = (f"  (miss {tpot_miss})"
                    if tpot_miss is not None else "")
        print(f"    tpot        p50 {_fmt_s(report['serve_tpot_p50_s'])}"
              f"  p99 {_fmt_s(report['serve_tpot_p99_s'])}{miss_txt}",
              file=out)
        if report["serve_arrival_span_s"] is not None:
            print(f"    arrivals    span "
                  f"{_fmt_s(report['serve_arrival_span_s'])} "
                  "(open-loop, from record arrival_s)", file=out)
    if report["serve_page_utilization"] is not None:
        # paged KV pool: mean fraction of allocatable pages in use; the
        # free count is the last flush's headroom (docs/serving.md)
        free_txt = (f"  free {int(report['serve_free_pages'])} pages"
                    if report["serve_free_pages"] is not None else "")
        print(f"  kv page pool       "
              f"{report['serve_page_utilization'] * 100:.0f}% utilized"
              f"{free_txt}", file=out)
    if report["serve_prefix_hit_ratio"] is not None:
        # prefix reuse: fraction of admissions that found cached prefix
        # pages, the prompt tokens whose prefill they skipped, and the
        # copy-on-write count (divergent appends into shared pages)
        tok_txt = (f", {int(report['serve_prefix_hit_tokens'])} prompt "
                   "tokens reused"
                   if report["serve_prefix_hit_tokens"] else "")
        cow_txt = (f", {int(report['serve_page_cow_total'])} COW"
                   if report["serve_page_cow_total"] else "")
        print(f"  prefix cache       "
              f"{report['serve_prefix_hit_ratio'] * 100:.0f}% hit"
              f"{tok_txt}{cow_txt}", file=out)
    if report["serve_spec_mean_accepted_len"] is not None:
        # speculative decoding: draft-token acceptance + tokens per
        # target pass — the speedup denominator (wall/token tracks
        # 1/mean-accepted-length, docs/serving.md)
        acc_txt = (f"  accept {report['serve_spec_accept_ratio'] * 100:.0f}"
                   "% of drafts"
                   if report["serve_spec_accept_ratio"] is not None
                   else "")
        print(f"  speculation        "
              f"{report['serve_spec_mean_accepted_len']:.2f} tokens/"
              f"target pass{acc_txt}", file=out)
    if sv_param_bytes is not None or sv_kv_bytes is not None:
        # serving memory: device bytes of params (int8 + scales under
        # weight quantization) and the KV cache spec (incl. quant
        # sidecars) — the KV-byte claims bench legs used to recompute
        # by hand now come from this one plane
        print(f"  serving memory     params "
              f"{_fmt_bytes(sv_param_bytes)}  kv "
              f"{_fmt_bytes(sv_kv_bytes)}", file=out)
    if sv_adapters_resident is not None:
        # multi-tenant adapter plane: HBM slot residency + the pool's
        # hit/fault/eviction ledger — faults are host->HBM fetches (a
        # cold tenant's admission stall), evictions mean the hot set
        # outgrew hbm_adapter_slots (docs/serving.md)
        bytes_txt = (f" ({_fmt_bytes(sv_adapter_bytes)})"
                     if sv_adapter_bytes else "")
        ledger = ", ".join(
            f"{name} {int(v)}" for name, v in
            (("hits", sv_adapter_hits), ("faults", sv_adapter_faults),
             ("evictions", sv_adapter_evictions)) if v is not None)
        print(f"  adapters           {int(sv_adapters_resident)} "
              f"resident{bytes_txt}"
              f"{'  ' + ledger if ledger else ''}", file=out)
    if sv_kv_parked is not None:
        # KV tier: idle sessions parked off HBM + the spill/fetch byte
        # ledger; resume p99 is the fetch-latency tail a parked
        # session's return pays (docs/serving.md "KV tiering")
        flow_txt = ""
        if sv_kv_spill_bytes is not None \
                or sv_kv_fetch_bytes is not None:
            flow_txt = (f"  spilled {_fmt_bytes(sv_kv_spill_bytes)}"
                        f"  fetched {_fmt_bytes(sv_kv_fetch_bytes)}")
        res_txt = (f"  resume p99 {_fmt_s(sv_kv_resume_p99)}"
                   if sv_kv_resume_p99 is not None else "")
        print(f"  kv tier            {int(sv_kv_parked)} session(s) "
              f"parked{flow_txt}{res_txt}", file=out)
    if beat_ages:
        # liveness (docs/elastic.md): supervisor-visible staleness made
        # operator-visible — last beat age per host at the final sync
        print(f"  liveness           {len(beat_ages)} host(s), last "
              f"beat age max {_fmt_s(max(beat_ages.values()))}",
              file=out)
    if stragglers is not None:
        # elastic fleet health: hosts flagged slower than the configured
        # multiple of the fleet-median step time (docs/elastic.md)
        print(f"  stragglers         {int(stragglers)} host(s) flagged "
              "(step time > ratio x fleet median)", file=out)
    print(f"  peak HBM           {_fmt_bytes(peak_hbm)}", file=out)
    if host_rss is not None:
        print(f"  peak host RSS      {_fmt_bytes(host_rss)}", file=out)
    if bad_lines:
        print(f"  (skipped {bad_lines} unparseable lines)", file=out)
    return report


def _read_jsonl_tolerant(path: str):
    """(records, skipped) — a killed run's torn final line is counted,
    never silently dropped."""
    records: List[dict] = []
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                skipped += 1
    return records, skipped


def diagnose(directory: str, out=None) -> dict:
    """Post-mortem over a telemetry output directory: correlate the
    newest ``flightrec_<step>.json`` with events.jsonl and trace.json —
    which stage failed first, whether/what degraded, the queue-depth
    trajectory leading up to it, and the original exception.  Every
    artifact is optional (a crash may have lost some); truncated files
    are tolerated and the skip counts reported."""
    out = out if out is not None else sys.stdout
    report: dict = {"directory": directory, "skipped_lines": 0}
    print(f"telemetry diagnose: {directory}", file=out)

    # -- flight record (newest by step) ---------------------------------
    recs = glob.glob(os.path.join(directory, "flightrec_*.json"))

    def _step_of(p):
        try:
            return int(os.path.basename(p)[len("flightrec_"):-len(".json")])
        except ValueError:
            return -1
    flight = None
    if recs:
        path = max(recs, key=_step_of)
        try:
            with open(path) as f:
                flight = json.load(f)
        except (OSError, ValueError) as e:
            print(f"  flight record {os.path.basename(path)}: "
                  f"UNREADABLE ({e})", file=out)
    if flight is None:
        print("  flight record      none found", file=out)
    else:
        report["flightrec_step"] = flight.get("step")
        report["reason"] = flight.get("reason")
        report["error"] = flight.get("error")
        print(f"  flight record      step {flight.get('step')} — "
              f"{flight.get('reason')}", file=out)
        if flight.get("error"):
            print(f"  original exception {flight['error']}", file=out)
        first_failure = None
        degraded = []
        for sname, st in (flight.get("stages") or {}).items():
            if st.get("degraded"):
                degraded.append(sname)
            for ev in st.get("events") or []:
                if ev.get("kind") in ("failure", "surfaced", "poison",
                                      "job_failed"):
                    if first_failure is None or \
                            ev.get("t", 0) < first_failure[1].get("t", 0):
                        first_failure = (sname, ev)
        report["degraded_stages"] = sorted(degraded)
        if degraded:
            print(f"  degraded stage(s)  {', '.join(sorted(degraded))}",
                  file=out)
        if first_failure is not None:
            sname, ev = first_failure
            report["first_failure_stage"] = sname
            report["first_failure_error"] = ev.get("error")
            print(f"  first failure      stage {sname!r}: "
                  f"{ev.get('error')}", file=out)
            if report.get("error") is None:
                report["error"] = ev.get("error")
        for sname, st in sorted((flight.get("stages") or {}).items()):
            depths = [ev["depth"] for ev in st.get("events") or []
                      if ev.get("depth") is not None]
            evn = len(st.get("events") or [])
            if depths:
                print(f"  stage {sname:<12} {evn} events; queue depth "
                      f"{depths[0]} -> {depths[-1]} "
                      f"(min {min(depths)}, max {max(depths)})",
                      file=out)
                report.setdefault("depth_trajectory", {})[sname] = {
                    "first": depths[0], "last": depths[-1],
                    "min": min(depths), "max": max(depths),
                    "samples": len(depths)}
            else:
                print(f"  stage {sname:<12} {evn} events", file=out)

    # -- events.jsonl correlation ---------------------------------------
    records: List[dict] = []
    events_path = os.path.join(directory, "events.jsonl")
    if os.path.isfile(events_path):
        records, skipped = _read_jsonl_tolerant(events_path)
        report["skipped_lines"] = skipped
        steps = [r.get("step") for r in records
                 if r.get("kind") == "step" and r.get("step") is not None]
        failed_reqs = [r for r in records
                       if r.get("kind") == "serve_request"
                       and r.get("error")]
        report["last_step"] = max(steps) if steps else None
        report["failed_requests"] = len(failed_reqs)
        print(f"  events.jsonl       {len(records)} records, last step "
              f"{report['last_step']}", file=out)
        if failed_reqs:
            r0 = failed_reqs[0]
            print(f"  failed requests    {len(failed_reqs)} (first: "
                  f"rid={r0.get('rid')} {r0.get('error')})", file=out)
        if skipped:
            print(f"  (skipped {skipped} malformed/torn events.jsonl "
                  "line(s) — truncated final write of a killed run)",
                  file=out)
    else:
        print("  events.jsonl       not present", file=out)

    # -- serving-fleet correlation (docs/serving.md "serving fleet") ----
    # a fleet directory holds the router's events.jsonl (fleet_* kinds)
    # plus one replica_<id>/ telemetry subdir per replica — correlate
    # them into the fleet post-mortem: which replica failed first, how
    # many requests failed over, and which never completed (dangling)
    replica_dirs = sorted(
        p for p in glob.glob(os.path.join(directory, "replica_*"))
        if os.path.isdir(p))
    fleet_kinds = any(str(r.get("kind", "")).startswith("fleet_")
                      or r.get("kind") in ("replica_dead", "spawn")
                      for r in records)
    if replica_dirs or fleet_kinds:
        submits = {r.get("rid") for r in records
                   if r.get("kind") == "fleet_submit"}
        completes = {r.get("rid") for r in records
                     if r.get("kind") == "fleet_request"}
        dangling = sorted(x for x in submits - completes
                          if x is not None)
        deaths = [r for r in records if r.get("kind") == "replica_dead"]
        failovers = sum(int(r.get("failed_over") or 0) for r in deaths)
        midstream = [r for r in records
                     if r.get("kind") == "fleet_request"
                     and r.get("error")]
        report["fleet_replica_dirs"] = len(replica_dirs)
        report["fleet_failover_count"] = failovers
        report["fleet_dangling_requests"] = len(dangling)
        report["fleet_failed_requests"] = len(midstream)
        print(f"  fleet              {len(replica_dirs)} replica "
              f"dir(s), {len(deaths)} replica death(s), {failovers} "
              "request(s) failed over", file=out)
        if deaths:
            d0 = min(deaths, key=lambda r: r.get("t", 0))
            report["fleet_first_dead_replica"] = d0.get("replica")
            print(f"  first replica dead replica {d0.get('replica')} — "
                  f"{d0.get('reason')}", file=out)
        # earliest failure event across the replicas' own flight
        # records: the corpse that started the cascade
        first_fail = None
        for rd in replica_dirs:
            for path in glob.glob(os.path.join(rd, "flightrec_*.json")):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                for sname, st in (doc.get("stages") or {}).items():
                    for ev in st.get("events") or []:
                        if ev.get("kind") in ("failure", "poison",
                                              "surfaced", "job_failed"):
                            key = (ev.get("t", 0), os.path.basename(rd),
                                   sname, ev.get("error"))
                            if first_fail is None or key < first_fail:
                                first_fail = key
        if first_fail is not None:
            _, rname, sname, ferr = first_fail
            report["fleet_first_failing_replica"] = rname
            print(f"  first failing      {rname} (stage {sname!r}): "
                  f"{ferr}", file=out)
        # per-role breakdown (disaggregated fleets, docs/serving.md
        # "disaggregated fleet"): spawn records carry the role, and the
        # migration records ARE the custody ledger — which phase of the
        # fleet was dying, and where every migrated KV blob ended up
        role_of = {r.get("replica"): r.get("role") for r in records
                   if r.get("kind") == "spawn" and r.get("role")}
        migrations = [r for r in records
                      if r.get("kind") == "migration"]
        if any(v != "mixed" for v in role_of.values()) or migrations:
            by_role: dict = {}
            for repid, role in sorted(
                    (k, v) for k, v in role_of.items()
                    if k is not None):
                by_role.setdefault(role, []).append(repid)
            report["fleet_roles"] = {k: len(v)
                                     for k, v in by_role.items()}
            for role in sorted(by_role):
                ids = by_role[role]
                role_deaths = [d for d in deaths
                               if d.get("replica") in ids]
                line = (f"  role {role:<13} {len(ids)} replica(s) "
                        f"spawned, {len(role_deaths)} death(s)")
                if role_deaths:
                    d0 = min(role_deaths, key=lambda r: r.get("t", 0))
                    report.setdefault("fleet_role_first_dead",
                                      {})[role] = d0.get("replica")
                    line += (f"; first dead replica "
                             f"{d0.get('replica')} — "
                             f"{d0.get('reason')}")
                print(line, file=out)
            if migrations:
                taken = sum(1 for m in migrations
                            if m.get("custody") == "router"
                            and not m.get("requeued"))
                handed = sum(1 for m in migrations
                             if m.get("custody") == "decode")
                requeued = sum(1 for m in migrations
                               if m.get("requeued"))
                report["fleet_migrations"] = handed
                report["fleet_migration_requeued"] = requeued
                line = (f"  migrations         {taken} KV blob(s) "
                        f"into router custody, {handed} handed to "
                        "decode replicas")
                if requeued:
                    line += (f", {requeued} re-dispatched after a "
                             "decode-replica death")
                print(line, file=out)
        if midstream:
            m0 = midstream[0]
            print(f"  mid-stream failed  {len(midstream)} request(s) "
                  f"(first: rid={m0.get('rid')} {m0.get('error')})",
                  file=out)
        if dangling:
            shown = ", ".join(str(x) for x in dangling[:8])
            more = "..." if len(dangling) > 8 else ""
            print(f"  DANGLING requests  {len(dangling)} submitted but "
                  f"never completed (rid {shown}{more}) — in flight "
                  "at the failure", file=out)

    # -- trace.json correlation -----------------------------------------
    trace_path = os.path.join(directory, "trace.json")
    if os.path.isfile(trace_path):
        try:
            with open(trace_path) as f:
                doc = json.load(f)
            evs = doc.get("traceEvents", [])
            flows = [e for e in evs if e.get("ph") in ("s", "t", "f")]
            starts = {e["id"] for e in flows if e["ph"] == "s"}
            ends = {e["id"] for e in flows if e["ph"] == "f"}
            dangling = len(starts - ends)
            dropped = int((doc.get("otherData") or {})
                          .get("dropped_events", 0))
            report["trace_events"] = len(evs)
            report["flow_events"] = len(flows)
            report["dangling_flows"] = dangling
            report["trace_dropped_events"] = dropped
            note = ""
            if dangling:
                note = (f", {dangling} DANGLING flow(s) — work in "
                        "flight at the failure")
                if dropped:
                    # a capped buffer can drop a flow's events; don't
                    # let that masquerade as in-flight work
                    note += (" (CAVEAT: trace buffer dropped "
                             f"{dropped} events — dangling may be "
                             "truncation, not in-flight work)")
            elif dropped:
                note = f" ({dropped} events dropped at the buffer cap)"
            print(f"  trace.json         {len(evs)} events, "
                  f"{len(flows)} flow events{note}", file=out)
        except (OSError, ValueError) as e:
            # a killed run can tear the trace mid-write; say so rather
            # than crash the post-mortem
            report["trace_unreadable"] = True
            print(f"  trace.json         unreadable/truncated ({e})",
                  file=out)
    else:
        print("  trace.json         not present", file=out)
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry",
        description="offline reports over telemetry event files")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_sum = sub.add_parser("summarize",
                           help="p50/p95/p99 step time, samples/sec, "
                                "peak HBM from an events.jsonl")
    p_sum.add_argument("events", help="path to events.jsonl")
    p_diag = sub.add_parser(
        "diagnose",
        help="post-mortem over a telemetry output dir (or a serving-"
             "fleet dir): correlate flightrec_*.json + events.jsonl + "
             "trace.json, plus per-replica flight records and the "
             "router request ledger for fleet dirs")
    p_diag.add_argument("directory",
                        help="telemetry output directory (holds "
                             "flightrec_*.json / events.jsonl / "
                             "trace.json) or a fleet directory "
                             "(router events.jsonl + replica_<id>/ "
                             "subdirs)")
    args = parser.parse_args(argv)
    if args.cmd == "summarize":
        try:
            summarize(args.events)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        return 0
    if args.cmd == "diagnose":
        if not os.path.isdir(args.directory):
            print(f"error: {args.directory} is not a directory",
                  file=sys.stderr)
            return 2
        diagnose(args.directory)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
