"""Unified telemetry for the TPU engine.

    metrics registry  -> Prometheus text / JSONL / SummaryWriter bridge
    span tracing      -> Chrome/Perfetto trace-event JSON (host-side,
                         zero added device syncs)
    compile tracking  -> recompiles_total{program=...} + storm warning
    memory gauges     -> structured memory_status at sync points

The engine constructs ONE :class:`TelemetryHub` per run when the
``telemetry`` config block is enabled; see docs/observability.md.

``python -m deepspeed_tpu.telemetry summarize <events.jsonl>`` reports
p50/p95/p99 step time, samples/sec, and peak HBM offline.
"""
from .compile_monitor import CompileMonitor
from .exporters import (JsonlExporter, SummaryWriterBridge,
                        prometheus_text, write_prometheus)
from .heartbeat import (HeartbeatWriter, StragglerMonitor, beat_ages,
                        read_heartbeats)
from .hub import TelemetryHub, write_flight_record
from .memory import MemorySampler
from .registry import Counter, Gauge, Histogram, MetricsRegistry
from .tracing import SpanHandle, TraceContext, TraceRecorder

__all__ = [
    "CompileMonitor", "Counter", "Gauge", "HeartbeatWriter", "Histogram",
    "JsonlExporter", "MemorySampler", "MetricsRegistry", "SpanHandle",
    "StragglerMonitor", "SummaryWriterBridge", "TelemetryHub",
    "TraceContext", "TraceRecorder", "beat_ages", "prometheus_text",
    "read_heartbeats", "write_flight_record", "write_prometheus",
]
