"""Exporters: JSONL event stream, Prometheus text format, SummaryWriter
bridge.

One registry, three read paths:

  - ``JsonlExporter`` appends structured event records (step timings,
    memory samples, periodic metric snapshots) that
    ``python -m deepspeed_tpu.telemetry summarize`` consumes offline.
  - ``prometheus_text`` renders the registry in the Prometheus text
    exposition format (counters/gauges as plain samples, histograms as
    quantile summaries) for a node_exporter-style scrape file.
  - ``SummaryWriterBridge`` pushes scalar views into the existing
    ``utils.monitor.SummaryWriter`` so TensorBoard keeps working without
    a second collection path.
"""
from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Dict, Optional

from .registry import Counter, Gauge, Histogram, MetricsRegistry


class JsonlExporter:
    """Append-only JSONL event file; flush/close idempotent.

    Writes run on the TRAINING path (record_step buffers a line per
    step), so I/O failure must degrade, not kill the run: the first
    OSError (disk full, EIO, ...) logs one warning and disables the
    exporter — the repo-wide 'never let observability kill the step'
    rule (utils/timer.py states the same for timing)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # "w", not "a": one run per file, consistent with trace.json /
        # metrics.prom — appending would silently blend two runs' steps
        # in summarize.  Point output_path at a per-run directory to
        # keep history.
        self._fh = open(path, "w")
        self._closed = False
        self._degraded = False

    def _disable(self, exc: BaseException):
        from ..utils.logging import logger
        self._degraded = True
        logger.warning(
            "telemetry JSONL exporter disabled after write failure on "
            "%s: %r (training continues; no further events recorded)",
            self.path, exc)

    def write_event(self, kind: str, data: dict, ts: Optional[float] = None):
        if self._closed or self._degraded:
            return
        rec = {"kind": kind, "ts": time.time() if ts is None else ts}
        rec.update(data)
        try:
            self._fh.write(json.dumps(rec) + "\n")
        except (OSError, ValueError) as e:  # ValueError: closed file obj
            self._disable(e)

    def write_snapshot(self, registry: MetricsRegistry,
                       step: Optional[int] = None):
        self.write_event("metrics", {"step": step,
                                     "metrics": registry.snapshot()})

    def flush(self):
        if self._closed or self._degraded:
            return
        try:
            self._fh.flush()
        except (OSError, ValueError) as e:
            self._disable(e)

    def close(self):
        if self._closed:
            return
        self._closed = True
        try:
            self._fh.close()
        except OSError:
            pass


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_labels(labels: Dict[str, str], extra: Optional[Dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (_prom_name(str(k)),
                     str(v).replace("\\", r"\\").replace('"', r'\"')
                     .replace("\n", r"\n"))
        for k, v in sorted(items.items()))
    return "{" + body + "}"


def _prom_value(v) -> str:
    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "NaN"
    return repr(float(v))


def _prom_help(text: str) -> str:
    """HELP-text escaping per the exposition format: backslash and
    newline (label values additionally escape double quotes; HELP does
    not).  A multi-line docstring-ish help must not tear the line-based
    format."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def prometheus_text(registry: MetricsRegistry) -> str:
    """Prometheus text exposition format, one sample per line (every
    non-comment line is ``name{labels} value`` — the acceptance test
    parses line-by-line).  Every metric gets a ``# HELP`` line: metrics
    registered without help text fall back to their own name, so a
    scraper's metadata view never has silent gaps."""
    lines = []
    for m in registry.metrics():
        name = _prom_name(m.name)
        lines.append(f"# HELP {name} {_prom_help(m.help or m.name)}")
        if isinstance(m, Counter):
            lines.append(f"# TYPE {name} counter")
            for key, v in m.series():
                lines.append(f"{name}{_prom_labels(dict(key))} "
                             f"{_prom_value(v)}")
        elif isinstance(m, Gauge):
            lines.append(f"# TYPE {name} gauge")
            for key, v in m.series():
                lines.append(f"{name}{_prom_labels(dict(key))} "
                             f"{_prom_value(v)}")
        elif isinstance(m, Histogram):
            lines.append(f"# TYPE {name} summary")
            for key, res in m.series():
                labels = dict(key)
                for q in (0.5, 0.95, 0.99):
                    lines.append(
                        f"{name}{_prom_labels(labels, {'quantile': q})} "
                        f"{_prom_value(res.percentile(q))}")
                lines.append(f"{name}_sum{_prom_labels(labels)} "
                             f"{_prom_value(res.total)}")
                lines.append(f"{name}_count{_prom_labels(labels)} "
                             f"{_prom_value(res.count)}")
    return "\n".join(lines) + "\n"


def write_prometheus(registry: MetricsRegistry, path: str) -> str:
    """Atomic-ish scrape-file write (tmp + rename) so a concurrent
    scraper never reads a half-written exposition."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(prometheus_text(registry))
    os.replace(tmp, path)
    return path


class SummaryWriterBridge:
    """Mirror registry scalars into a SummaryWriter at sync points.

    Counters/gauges land as their value, histograms as p50/p95 pairs —
    all under a ``telemetry/`` tag prefix so they don't collide with the
    engine's own ``Train/*`` scalars."""

    def __init__(self, registry: MetricsRegistry, writer):
        self.registry = registry
        self.writer = writer

    @staticmethod
    def _tag(name: str, labels: Dict[str, str], suffix: str = "") -> str:
        tag = "telemetry/" + name
        if labels:
            tag += "." + ".".join(f"{k}_{v}" for k, v in sorted(
                labels.items()))
        return tag + suffix

    def push(self, step: int):
        for m in self.registry.metrics():
            if isinstance(m, (Counter, Gauge)):
                for key, v in m.series():
                    self.writer.add_scalar(self._tag(m.name, dict(key)),
                                           float(v), step)
            elif isinstance(m, Histogram):
                for key, res in m.series():
                    labels = dict(key)
                    p50 = res.percentile(0.5)
                    p95 = res.percentile(0.95)
                    if p50 is not None:
                        self.writer.add_scalar(
                            self._tag(m.name, labels, ".p50"), p50, step)
                    if p95 is not None:
                        self.writer.add_scalar(
                            self._tag(m.name, labels, ".p95"), p95, step)
