"""Goodput accounting over the serving completion records.

Goodput (docs/serving.md "workload plane") is the fraction of finished
requests that met BOTH per-phase SLOs:

    TTFT   submit -> first generated token (queue wait + prefill)
    TPOT   mean time per output token over the decode phase

Two planes, one verdict function:

* **offline** — :func:`read_goodput` reconstructs the per-request
  phases from the completion records alone (``serve_request`` from a
  :class:`ServeEngine`, ``fleet_request`` from the router ledger) and
  scores them against the SLOs, tolerating the torn final line of a
  killed run the way ``summarize`` does (skipped count reported,
  never silently dropped).
* **live** — :class:`GoodputTracker` observes completed requests
  during a run and exports the verdicts through the telemetry hub:
  the ``serve_slo_ttft_miss_total`` / ``serve_slo_tpot_miss_total``
  counters, the ``serve_goodput_ratio`` gauge, and one sync flush of
  the ``serve_goodput`` / ``serve_slo_*_s`` scalars the summarize
  "goodput" section reads back.

The phase math is record-only on purpose: an operator scoring a
production artifact and the bench scoring a replay must agree, so
there is exactly one copy of it here.
"""
from __future__ import annotations

from typing import List, Optional

from .cli import _percentile, _read_jsonl_tolerant, _slo_ok


def phases_from_record(rec: dict) -> Optional[dict]:
    """Per-request phase attribution from one completion record.

    Accepts both record shapes — ``serve_request`` (engine: explicit
    ``decode_s_sum``/``decode_tokens``) and ``fleet_request`` (router
    ledger: TPOT reconstructed as ``(total - queue_wait - ttft) /
    (tokens - 1)``).  Pre-PR-17 records without ``arrival_s`` are fine
    (the field rides along when present; nothing here requires it).
    Returns None for records of any other kind.
    """
    kind = rec.get("kind", "serve_request")
    if kind not in ("serve_request", "fleet_request"):
        return None
    queue_wait = rec.get("queue_wait_s")
    ttft = rec.get("ttft_s")
    tpot = None
    dn = rec.get("decode_tokens")
    if dn:
        tpot = float(rec.get("decode_s_sum") or 0.0) / int(dn)
    elif kind == "fleet_request":
        tokens = int(rec.get("tokens") or 0)
        total = rec.get("total_s")
        if tokens > 1 and total is not None and ttft is not None:
            wait = float(queue_wait or 0.0)
            tpot = max(float(total) - wait - float(ttft), 0.0) \
                / (tokens - 1)
    return {
        "rid": rec.get("rid"),
        "arrival_s": rec.get("arrival_s"),
        "queue_wait_s": (float(queue_wait)
                         if queue_wait is not None else None),
        "ttft_s": float(ttft) if ttft is not None else None,
        "tpot_s": tpot,
        "tokens": int(rec.get("tokens") or 0),
        "error": rec.get("error"),
        "started": rec.get("started", True),
    }


def phases_from_request(req) -> dict:
    """The same attribution from a live engine ``Request`` — identical
    math to the record path (``token_times[0]`` is the TTFT stamp, the
    rest are decode intervals), so the live tracker and the offline
    reader can never disagree about a request."""
    times = [float(t) for t in getattr(req, "token_times", [])]
    decode = times[1:]
    admit_t = getattr(req, "admit_t", None)
    return {
        "rid": req.rid,
        "arrival_s": None,
        "queue_wait_s": (admit_t - req.submit_t if admit_t else None),
        "ttft_s": times[0] if times else None,
        "tpot_s": (sum(decode) / len(decode) if decode else None),
        "tokens": len(req.tokens),
        "error": (repr(req.error) if req.error is not None else None),
        "started": True,
    }


def score(phases: List[dict], slo_ttft_s: float,
          slo_tpot_s: float) -> dict:
    """Score attributed requests against both phase SLOs.

    A request is GOOD only when it finished without error, produced a
    first token within the TTFT SLO, and held the TPOT SLO over its
    decode phase (a one-token request has no decode phase and passes
    TPOT vacuously — there was no output cadence to violate).
    """
    good = ttft_miss = tpot_miss = failed = 0
    ttfts: List[float] = []
    tpots: List[float] = []
    waits: List[float] = []
    for ph in phases:
        if ph.get("error"):
            failed += 1
            continue
        ttft, tpot = ph.get("ttft_s"), ph.get("tpot_s")
        if ttft is None or ttft > slo_ttft_s:
            ttft_miss += 1
        if tpot is not None and tpot > slo_tpot_s:
            tpot_miss += 1
        if _slo_ok(ttft, tpot, slo_ttft_s, slo_tpot_s):
            good += 1
        if ttft is not None:
            ttfts.append(ttft)
        if tpot is not None:
            tpots.append(tpot)
        if ph.get("queue_wait_s") is not None:
            waits.append(ph["queue_wait_s"])
    ttfts.sort()
    tpots.sort()
    waits.sort()
    n = len(phases)
    return {
        "requests": n,
        "failed": failed,
        "goodput": good / n if n else None,
        "slo_ttft_s": slo_ttft_s,
        "slo_tpot_s": slo_tpot_s,
        "ttft_miss": ttft_miss,
        "tpot_miss": tpot_miss,
        "ttft_p50_s": _percentile(ttfts, 0.50),
        "ttft_p99_s": _percentile(ttfts, 0.99),
        "tpot_p50_s": _percentile(tpots, 0.50),
        "tpot_p99_s": _percentile(tpots, 0.99),
        "queue_wait_p50_s": _percentile(waits, 0.50),
        "queue_wait_p99_s": _percentile(waits, 0.99),
    }


def read_goodput(path: str, slo_ttft_s: float,
                 slo_tpot_s: float) -> dict:
    """Offline goodput over an events.jsonl (engine telemetry dir or
    fleet ledger): tolerant read, phase attribution, SLO scoring.  The
    skipped (torn/truncated) line count rides in the report — the
    summarize idiom."""
    records, skipped = _read_jsonl_tolerant(path)
    phases = [ph for ph in (phases_from_record(r) for r in records)
              if ph is not None]
    report = score(phases, slo_ttft_s, slo_tpot_s)
    report["skipped_lines"] = skipped
    return report


class GoodputTracker:
    """Live per-request SLO verdicts over a :class:`TelemetryHub`.

    ``observe()`` one attributed request at a time (the dicts
    :func:`phases_from_request` / :func:`phases_from_record` build);
    ``flush(step)`` exports the run's verdict through every plane the
    hub owns — counters/gauge into the registry, scalars into one sync
    record — so ``telemetry summarize`` reports goodput offline from
    events.jsonl alone.
    """

    def __init__(self, slo_ttft_s: float, slo_tpot_s: float, hub=None):
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_tpot_s = float(slo_tpot_s)
        self.hub = hub
        self.phases: List[dict] = []
        if hub is not None:
            reg = hub.registry
            self._ttft_miss = reg.counter(
                "serve_slo_ttft_miss_total",
                "requests whose time-to-first-token exceeded the TTFT "
                "SLO")
            self._tpot_miss = reg.counter(
                "serve_slo_tpot_miss_total",
                "requests whose mean time-per-output-token exceeded "
                "the TPOT SLO")
            self._goodput_gauge = reg.gauge(
                "serve_goodput_ratio",
                "fraction of finished requests that met BOTH phase "
                "SLOs (TTFT and TPOT)")

    def observe(self, phase: dict) -> bool:
        """Record one completed request; returns its verdict."""
        self.phases.append(phase)
        ttft, tpot = phase.get("ttft_s"), phase.get("tpot_s")
        ok = not phase.get("error") and _slo_ok(
            ttft, tpot, self.slo_ttft_s, self.slo_tpot_s)
        if self.hub is not None:
            if ttft is None or ttft > self.slo_ttft_s:
                self._ttft_miss.inc()
            if tpot is not None and tpot > self.slo_tpot_s:
                self._tpot_miss.inc()
        return ok

    def report(self) -> dict:
        return score(self.phases, self.slo_ttft_s, self.slo_tpot_s)

    def flush(self, step: int = 0) -> dict:
        """One sync flush of the goodput scalars (summarize reads
        exactly these; the LAST flush is the run's answer)."""
        rep = self.report()
        if self.hub is not None and rep["goodput"] is not None:
            self._goodput_gauge.set(rep["goodput"])
            scalars = {
                "serve_goodput": rep["goodput"],
                "serve_goodput_requests": float(rep["requests"]),
                "serve_slo_ttft_s": self.slo_ttft_s,
                "serve_slo_tpot_s": self.slo_tpot_s,
            }
            self.hub.on_sync(step=step, scalars=scalars)
        return rep
