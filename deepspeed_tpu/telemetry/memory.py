"""Device-memory gauges from the structured ``memory_status`` path.

``runtime.utils.collect_memory_stats()`` is the ONE collection point —
the log line, these gauges, and the JSONL memory events all render the
same dict instead of re-parsing each other's strings.

Sampling reads PJRT's ``memory_stats()`` (allocator bookkeeping, no
device drain) and ``/proc/self/status`` — host-only, so the engine can
sample at its periodic sync without adding a device sync of its own.
"""
from __future__ import annotations

from typing import Optional

from .registry import MetricsRegistry


class MemorySampler:
    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.bytes_in_use = registry.gauge(
            "device_bytes_in_use", "HBM bytes currently allocated")
        self.peak_bytes = registry.gauge(
            "device_peak_bytes_in_use", "peak HBM bytes allocated")
        self.bytes_limit = registry.gauge(
            "device_bytes_limit", "HBM allocator capacity")
        self.host_rss = registry.gauge(
            "host_rss_bytes", "process resident set size")

    def sample(self) -> dict:
        """Collect once, set every gauge, return the structured dict
        (the caller forwards it to the JSONL exporter / trace counter
        track)."""
        from ..runtime.utils import collect_memory_stats
        stats = collect_memory_stats()
        for dev in stats.get("devices", []):
            did = str(dev.get("id"))
            if dev.get("bytes_in_use") is not None:
                self.bytes_in_use.set(dev["bytes_in_use"], device=did)
            if dev.get("peak_bytes_in_use") is not None:
                self.peak_bytes.set(dev["peak_bytes_in_use"], device=did)
            if dev.get("bytes_limit") is not None:
                self.bytes_limit.set(dev["bytes_limit"], device=did)
        rss = stats.get("host_rss_bytes")
        if rss is not None:
            self.host_rss.set(rss)
        return stats

    def peak_hbm_bytes(self) -> Optional[float]:
        """Max peak across sampled devices (the summarize CLI's
        headline number)."""
        series = self.peak_bytes.series()
        if not series:
            return None
        return max(v for _, v in series)
