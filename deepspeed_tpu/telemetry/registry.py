"""Metrics registry: counters, gauges, histograms with bounded reservoirs.

The runtime reports into ONE registry so every exporter (JSONL events,
Prometheus text, the SummaryWriter bridge) sees the same data — the
reference scatters the same facts across ThroughputTimer prints,
TensorBoard scalars, and wall_clock_breakdown logs
(reference: deepspeed/utils/timer.py, runtime/engine.py:977-1030).

Recording is host-only and cheap (a dict update under a lock); nothing
here ever touches a device buffer, which is what lets the engine record
per step without breaking its async-dispatch overlap.
"""
from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = "metric"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic per-label-set counter (``recompiles_total{program=...}``)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels: str):
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def series(self) -> List[Tuple[_LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())


class Gauge(_Metric):
    """Last-write-wins value (``device_bytes_in_use{device="0"}``)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[_LabelKey, float] = {}

    def set(self, value: float, **labels: str):
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def value(self, **labels: str) -> Optional[float]:
        with self._lock:
            return self._values.get(_label_key(labels))

    def series(self) -> List[Tuple[_LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())


class _Reservoir:
    """Bounded sample set: exact until ``size`` observations, then
    uniform reservoir sampling (Vitter's algorithm R) — percentiles stay
    O(size) memory over unbounded streams, the property that makes a
    histogram safe to leave enabled for a million-step run."""

    def __init__(self, size: int, seed: int = 0):
        self.size = size
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.samples: List[float] = []
        self._rng = random.Random(seed)

    def observe(self, value: float):
        value = float(value)
        self.count += 1
        self.total += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if len(self.samples) < self.size:
            self.samples.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < self.size:
                self.samples[j] = value

    def percentile(self, q: float) -> Optional[float]:
        if not self.samples:
            return None
        s = sorted(self.samples)
        if len(s) == 1:
            return s[0]
        pos = (len(s) - 1) * min(max(q, 0.0), 1.0)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac


class Histogram(_Metric):
    """Distribution with a bounded reservoir per label set."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", reservoir_size: int = 2048):
        super().__init__(name, help)
        self.reservoir_size = reservoir_size
        self._series: Dict[_LabelKey, _Reservoir] = {}

    def observe(self, value: float, **labels: str):
        key = _label_key(labels)
        with self._lock:
            res = self._series.get(key)
            if res is None:
                res = self._series[key] = _Reservoir(
                    self.reservoir_size, seed=hash(key) & 0xFFFF)
            res.observe(value)

    def reservoir(self, **labels: str) -> Optional[_Reservoir]:
        with self._lock:
            return self._series.get(_label_key(labels))

    def series(self) -> List[Tuple[_LabelKey, _Reservoir]]:
        with self._lock:
            return sorted(self._series.items(), key=lambda kv: kv[0])


class MetricsRegistry:
    """Named metrics, created idempotently (the engine, the compile
    monitor, and user code can all ask for the same counter)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested {cls.kind}")
                return m
            m = cls(name, help, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  reservoir_size: int = 2048) -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   reservoir_size=reservoir_size)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> List[dict]:
        """Plain-data view of every metric (the JSONL exporter's unit)."""
        out: List[dict] = []
        for m in self.metrics():
            if isinstance(m, (Counter, Gauge)):
                for key, v in m.series():
                    out.append({"name": m.name, "kind": m.kind,
                                "labels": dict(key), "value": v})
            elif isinstance(m, Histogram):
                for key, res in m.series():
                    out.append({
                        "name": m.name, "kind": m.kind,
                        "labels": dict(key),
                        "count": res.count, "sum": res.total,
                        "min": res.min, "max": res.max,
                        "p50": res.percentile(0.50),
                        "p95": res.percentile(0.95),
                        "p99": res.percentile(0.99),
                    })
        return out
