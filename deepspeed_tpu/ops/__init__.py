from .adam import fused_adam, FusedAdamState, FusedAdam, DeepSpeedCPUAdam
from .lamb import fused_lamb, FusedLambState, FusedLamb
