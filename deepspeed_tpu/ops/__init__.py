from .adam import fused_adam, FusedAdamState
from .lamb import fused_lamb, FusedLambState
from .cpu_adam import DeepSpeedCPUAdam

# Reference-parity aliases (reference exposes torch Optimizer classes
# FusedAdam/FusedLamb; here the same roles are optax-style gradient
# transformations — the factory is the class analogue).
FusedAdam = fused_adam
FusedLamb = fused_lamb
