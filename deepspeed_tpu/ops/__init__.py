from .adam import fused_adam, FusedAdamState
from .lamb import fused_lamb, FusedLambState
from .cpu_adam import DeepSpeedCPUAdam
